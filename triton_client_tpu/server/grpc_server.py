"""gRPC v2 frontend (grpc.aio).

Implements ``inference.GRPCInferenceService`` (this framework's own IDL,
``protocol/inference.proto``) — the RPC surface the reference gRPC client
drives (surveyed at grpc/_client.py).  Tensor data travels positionally in
``raw_input_contents``/``raw_output_contents`` (reference
grpc/_infer_input.py:160-174, _infer_result.py:63-97); typed
``InferTensorContents`` decoding is also supported for third-party stubs that
use it (e.g. the Go generated example).
"""

from __future__ import annotations

import asyncio
import math
import time
from typing import Any, Dict, Optional

import grpc
import numpy as np

from ..protocol import inference_pb2 as pb
from ..protocol.service import add_GRPCInferenceServiceServicer_to_server
from ..utils import deserialize_bytes_tensor, triton_to_np_dtype
from .core import InferenceCore
from .log import log_off_loop
from .memory import DEFAULT_MAX_REQUEST_BYTES
from .model import datatype_to_pb
from .qos import tenant_from_headers
from .types import (InferError, InferRequest, InputTensor,
                    RequestedOutput, ShmRef, apply_request_deadline,
                    apply_request_priority, reshape_input)
# the pb param codecs live in wire.py (shared with the response
# templates); re-exported here for the rest of the server package
from .wire import (build_pb_response, encode_pb_response, pb_param_to_py,
                   py_to_pb_param)


def _read_trace_metadata(req: InferRequest, context) -> None:
    """Fill the request's trace-propagation and QoS-identity fields from
    invocation metadata (`triton-request-id` / `traceparent` stamped by
    the instrumented clients; `triton-tenant` / `authorization` resolving
    the tenant, same precedence as the HTTP frontend)."""
    tenant_hdr = auth_hdr = None
    try:
        md = context.invocation_metadata() or ()
        for key, value in md:
            if key == "triton-request-id":
                req.client_request_id = value
            elif key == "traceparent":
                req.traceparent = value
            elif key == "triton-tenant":
                tenant_hdr = value
            elif key == "authorization":
                auth_hdr = value
    except Exception:
        pass  # metadata unavailable (e.g. gRPC-Web bridge test doubles)
    req.tenant = tenant_from_headers(tenant_hdr, auth_hdr)


def _decode_pb_request(request: pb.ModelInferRequest) -> InferRequest:
    req = InferRequest(
        model_name=request.model_name,
        model_version=request.model_version,
        id=request.id,
        parameters={k: pb_param_to_py(v) for k, v in request.parameters.items()},
    )
    # the v2 `timeout` parameter (µs) becomes the request's absolute
    # deadline; expired requests are dropped at dequeue with zero compute.
    # `priority` (0 = highest) is consumed into the QoS tier the same way
    apply_request_deadline(req)
    apply_request_priority(req)
    raw = list(request.raw_input_contents)
    # raw_input_contents carries entries ONLY for non-shm inputs, in input
    # order (reference wire semantics: grpc/_utils.py packs raw buffers in a
    # parallel list, shm inputs contribute no entry).
    n_raw_expected = sum(
        1 for t in request.inputs
        if "shared_memory_region" not in t.parameters
    )
    if raw and len(raw) != n_raw_expected:
        raise InferError(
            "raw_input_contents does not match the number of non-shared-"
            f"memory inputs (got {len(raw)}, expected {n_raw_expected})"
        )
    raw_idx = 0
    for t in request.inputs:
        shape = tuple(int(s) for s in t.shape)
        params = {k: pb_param_to_py(v) for k, v in t.parameters.items()}
        tensor = InputTensor(name=t.name, datatype=t.datatype, shape=shape, parameters=params)
        shm_name = params.get("shared_memory_region")
        if shm_name:
            try:
                tensor.shm = ShmRef(
                    region_name=shm_name,
                    byte_size=int(params["shared_memory_byte_size"]),
                    offset=int(params.get("shared_memory_offset", 0)),
                )
            except (KeyError, TypeError, ValueError) as e:
                raise InferError(
                    f"malformed shared-memory parameters for input "
                    f"'{t.name}': {e}")
        elif raw:
            tensor.data = _raw_to_array(raw[raw_idx], t.datatype, shape, t.name)
            raw_idx += 1
        elif t.HasField("contents"):
            tensor.data = _contents_to_array(t.contents, t.datatype, shape, t.name)
        else:
            raise InferError(f"input '{t.name}' has no data")
        req.inputs.append(tensor)
    for o in request.outputs:
        params = {k: pb_param_to_py(v) for k, v in o.parameters.items()}
        out = RequestedOutput(
            name=o.name,
            class_count=int(params.get("classification", 0)),
            parameters=params,
        )
        shm_name = params.get("shared_memory_region")
        if shm_name:
            try:
                out.shm = ShmRef(
                    region_name=shm_name,
                    byte_size=int(params["shared_memory_byte_size"]),
                    offset=int(params.get("shared_memory_offset", 0)),
                )
            except (KeyError, TypeError, ValueError) as e:
                raise InferError(
                    f"malformed shared-memory parameters for output "
                    f"'{o.name}': {e}")
        req.outputs.append(out)
    return req


def _raw_to_array(chunk: bytes, datatype: str, shape, name: str) -> np.ndarray:
    if datatype == "BYTES":
        try:
            flat = deserialize_bytes_tensor(chunk)
        except Exception as e:
            # the codec raises the CLIENT exception class on a truncated
            # length-prefixed stream — uncaught it escapes the InferError
            # handlers as UNKNOWN/500 instead of a clean client error
            # (surfaced by the gRPC fuzz pass)
            raise InferError(
                f"malformed BYTES payload for input '{name}': {e}")
        return reshape_input(flat, shape, name)
    dt = triton_to_np_dtype(datatype)
    if dt is None:
        raise InferError(f"unsupported datatype '{datatype}' for input '{name}'")
    # math.prod over python ints (empty shape -> 1): np.prod pays a
    # ufunc-reduction dispatch per request on this per-tensor hot path
    count = math.prod(shape)
    if len(chunk) != count * dt.itemsize:
        raise InferError(
            f"unexpected total byte size {len(chunk)} for input '{name}', "
            f"expecting {count * dt.itemsize}"
        )
    return reshape_input(np.frombuffer(chunk, dtype=dt), shape, name)


_CONTENTS_FIELD = {
    "BOOL": "bool_contents",
    "INT8": "int_contents",
    "INT16": "int_contents",
    "INT32": "int_contents",
    "INT64": "int64_contents",
    "UINT8": "uint_contents",
    "UINT16": "uint_contents",
    "UINT32": "uint_contents",
    "UINT64": "uint64_contents",
    "FP32": "fp32_contents",
    "FP64": "fp64_contents",
    "BYTES": "bytes_contents",
}


def _contents_to_array(contents, datatype: str, shape, name: str) -> np.ndarray:
    field = _CONTENTS_FIELD.get(datatype)
    if field is None:
        raise InferError(
            f"typed contents not supported for datatype '{datatype}' (input '{name}')"
        )
    values = list(getattr(contents, field))
    if datatype == "BYTES":
        return reshape_input(
            np.array(values, dtype=np.object_), shape, name)
    return reshape_input(
        np.array(values, dtype=triton_to_np_dtype(datatype)), shape, name)


# Response encoding lives in server/wire.py: ``build_pb_response`` is the
# slow path (streams use it — their parameter flags vary per frame),
# ``encode_pb_response`` adds the per-(model, output-set) template fast
# path the unary RPC rides.


class InferenceServicer:
    def __init__(self, core: InferenceCore):
        self._core = core

    # -- health / metadata -------------------------------------------------
    async def ServerLive(self, request, context):
        return pb.ServerLiveResponse(live=self._core.live)

    async def ServerReady(self, request, context):
        # mirrors HTTP /v2/health/ready: not-ready during startup warmup
        # or while any model is mid-load (see InferenceCore.ready)
        return pb.ServerReadyResponse(ready=self._core.ready())

    async def ModelReady(self, request, context):
        # registry-ready AND not quarantined after device faults
        # (mirrors HTTP /v2/models/{m}/ready; InferenceCore.model_ready)
        return pb.ModelReadyResponse(
            ready=self._core.model_ready(request.name, request.version)
        )

    async def ServerMetadata(self, request, context):
        md = self._core.server_metadata()
        return pb.ServerMetadataResponse(
            name=md["name"], version=md["version"], extensions=md["extensions"]
        )

    async def ModelMetadata(self, request, context):
        try:
            model = self._core.registry.get(request.name, request.version)
        except InferError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        md = model.metadata()
        resp = pb.ModelMetadataResponse(
            name=md["name"], versions=md["versions"], platform=md["platform"]
        )
        for io, dest in ((md["inputs"], resp.inputs), (md["outputs"], resp.outputs)):
            for t in io:
                dest.add(name=t["name"], datatype=t["datatype"], shape=t["shape"])
        return resp

    async def ModelConfig(self, request, context):
        try:
            model = self._core.registry.get(request.name, request.version)
        except InferError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return pb.ModelConfigResponse(config=model.config)

    async def ModelStatistics(self, request, context):
        try:
            stats = self._core.statistics(request.name or None, request.version)
        except InferError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        resp = pb.ModelStatisticsResponse()
        for s in stats:
            ms = resp.model_stats.add()
            ms.name = s["name"]
            ms.version = s["version"]
            ms.last_inference = s["last_inference"]
            ms.inference_count = s["inference_count"]
            ms.execution_count = s["execution_count"]
            ist = s["inference_stats"]
            for key in ("success", "fail", "queue", "compute_input", "compute_infer", "compute_output"):
                getattr(ms.inference_stats, key).count = ist[key]["count"]
                getattr(ms.inference_stats, key).ns = ist[key]["ns"]
        return resp

    # -- repository --------------------------------------------------------
    async def RepositoryIndex(self, request, context):
        resp = pb.RepositoryIndexResponse()
        for entry in self._core.registry.index(ready_only=request.ready):
            resp.models.add(
                name=entry["name"],
                version=entry.get("version", "1"),
                state=entry["state"],
                reason=entry.get("reason", ""),
            )
        return resp

    async def RepositoryModelLoad(self, request, context):
        params = request.parameters
        config_override = None
        files = {}
        for k, v in params.items():
            which = v.WhichOneof("parameter_choice")
            if k == "config" and which == "string_param":
                config_override = v.string_param
            elif k.startswith("file:") and which == "bytes_param":
                import base64

                files[k] = base64.b64encode(v.bytes_param).decode()
        try:
            await self._core.load_model(
                request.model_name, config_override=config_override,
                files=files or None
            )
        except InferError as e:
            await context.abort(grpc.StatusCode.INTERNAL, str(e))
        return pb.RepositoryModelLoadResponse()

    async def RepositoryModelUnload(self, request, context):
        unload_dependents = False
        p = request.parameters.get("unload_dependents")
        if p is not None and p.WhichOneof("parameter_choice") == "bool_param":
            unload_dependents = p.bool_param
        try:
            self._core.registry.unload(request.model_name, unload_dependents)
        except InferError as e:
            await context.abort(grpc.StatusCode.INTERNAL, str(e))
        self._core.retire_name_caches(request.model_name)
        log_off_loop(
            self._core.log.info,
            f"successfully unloaded model '{request.model_name}'")
        return pb.RepositoryModelUnloadResponse()

    # -- shared memory -----------------------------------------------------
    async def SystemSharedMemoryStatus(self, request, context):
        resp = pb.SystemSharedMemoryStatusResponse()
        for name, r in self._core.system_shm.status(request.name or None).items():
            resp.regions[name].name = r["name"]
            resp.regions[name].key = r["key"]
            resp.regions[name].offset = r["offset"]
            resp.regions[name].byte_size = r["byte_size"]
        return resp

    async def SystemSharedMemoryRegister(self, request, context):
        try:
            self._core.system_shm.register(
                request.name, request.key, request.offset, request.byte_size
            )
        except InferError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.SystemSharedMemoryRegisterResponse()

    async def SystemSharedMemoryUnregister(self, request, context):
        self._core.system_shm.unregister(request.name or None)
        return pb.SystemSharedMemoryUnregisterResponse()

    async def CudaSharedMemoryStatus(self, request, context):
        resp = pb.CudaSharedMemoryStatusResponse()
        for name, r in self._core.xla_shm.status(request.name or None).items():
            resp.regions[name].name = r["name"]
            resp.regions[name].device_id = r["device_id"]
            resp.regions[name].byte_size = r["byte_size"]
        return resp

    async def CudaSharedMemoryRegister(self, request, context):
        try:
            self._core.xla_shm.register(
                request.name, request.raw_handle, request.device_id, request.byte_size
            )
        except InferError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.CudaSharedMemoryRegisterResponse()

    async def CudaSharedMemoryUnregister(self, request, context):
        self._core.xla_shm.unregister(request.name or None)
        return pb.CudaSharedMemoryUnregisterResponse()

    # -- trace / logging ---------------------------------------------------
    async def TraceSetting(self, request, context):
        from .trace import TRACE_DEFAULTS, validate_trace_update

        model = request.model_name or ""
        if model:
            try:
                self._core.registry.get(model)
                # empty value in model scope clears the override (back to
                # inheriting global); explicit values override
                update = {k: list(v.value)
                          for k, v in request.settings.items() if v.value}
                cleared = []
                for k, v in request.settings.items():
                    if v.value:
                        continue
                    if k not in TRACE_DEFAULTS:
                        # same contract as HTTP: a typo'd clear must not
                        # silently succeed
                        raise InferError(
                            f"unknown trace setting '{k}'", 400)
                    cleared.append(k)
                validate_trace_update(update, model_scope=True)
            except InferError as e:
                code = (grpc.StatusCode.UNIMPLEMENTED
                        if e.http_status == 501
                        else grpc.StatusCode.INVALID_ARGUMENT)
                await context.abort(code, str(e))
            if update or cleared:
                self._core.tracer.update_model(model, update, cleared)
            resp = pb.TraceSettingResponse()
            for k, vals in self._core.tracer.effective_settings(
                    model).items():
                resp.settings[k].value.extend(vals)
            return resp
        # an empty value list (SetInParent with no values) clears the key back
        # to its default — reference update_trace_settings(None) contract
        update = {}
        try:
            for k, v in request.settings.items():
                if v.value:
                    update[k] = list(v.value)
                else:
                    # empty clears to default; a typo'd clear flows into
                    # the shared validator, which rejects unknown keys —
                    # same contract as model scope
                    update[k] = list(TRACE_DEFAULTS.get(k, []))
            validate_trace_update(update)
        except InferError as e:
            code = (grpc.StatusCode.UNIMPLEMENTED if e.http_status == 501
                    else grpc.StatusCode.INVALID_ARGUMENT)
            await context.abort(code, str(e))
        if update:  # get_trace_settings sends an empty map — a read, not an
            # update; it must not reset the sampling counters or count budget
            self._core.trace_settings.update(update)
            self._core.tracer.settings_updated()
        resp = pb.TraceSettingResponse()
        for k, vals in self._core.trace_settings.items():
            resp.settings[k].value.extend(vals)
        return resp

    async def FlightRecorder(self, request, context):
        """Debug surface: the flight recorder's recent ring + pinned
        outliers, as the same JSON the HTTP endpoint serves (see
        protocol/debug_pb2.py for why JSON-in-proto).  Snapshot +
        serialization run off-loop — a large ring must not stall
        in-flight inference (same contract as the HTTP endpoint)."""
        import json as _json

        from ..protocol import debug_pb2 as pb_debug
        from .flight_recorder import parse_snapshot_limit

        model = request.model_name or None
        try:
            # proto uint32 cannot carry a negative or non-integer, but the
            # validation mirrors HTTP's ?limit= contract anyway so both
            # wire surfaces stay byte-for-byte identical in behavior (and
            # a future int-typed field cannot silently regress it)
            limit = parse_snapshot_limit(request.limit or 0)
        except InferError as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        body = await asyncio.get_running_loop().run_in_executor(
            None, lambda: _json.dumps(
                self._core.flight_recorder.snapshot(
                    model=model, limit=limit)))
        return pb_debug.FlightRecorderResponse(payload_json=body)

    async def DeviceStats(self, request, context):
        """Debug surface: the device/scheduler observability snapshot
        (device_stats + SLO state) — same JSON as HTTP's
        ``GET /v2/debug/device_stats``, same off-loop serialization."""
        import json as _json

        from ..protocol import debug_pb2 as pb_debug

        model = request.model_name or None

        def _snap():
            out = self._core.device_stats.snapshot(model=model)
            out["slo"] = self._core.slo.snapshot(model=model)
            # byte-admission ledger, same shape as the HTTP surface
            out["memory"] = self._core.memory.snapshot()
            from . import kvcache

            out["kv_cache"] = kvcache.snapshot()
            return _json.dumps(out)

        body = await asyncio.get_running_loop().run_in_executor(None, _snap)
        return pb_debug.DeviceStatsResponse(payload_json=body)

    async def Costs(self, request, context):
        """Debug surface: the per-tenant cost-attribution ledger
        (server/costs.py) — same JSON as HTTP's ``GET /v2/debug/costs``,
        same off-loop serialization."""
        import json as _json

        from ..protocol import debug_pb2 as pb_debug

        model = request.model_name or None
        body = await asyncio.get_running_loop().run_in_executor(
            None, lambda: _json.dumps(
                self._core.cost_ledger.snapshot(model=model)))
        return pb_debug.CostsResponse(payload_json=body)

    async def LogSettings(self, request, context):
        for k, v in request.settings.items():
            which = v.WhichOneof("parameter_choice")
            if which:
                self._core.log_settings[k] = getattr(v, which)
        resp = pb.LogSettingsResponse()
        for k, val in self._core.log_settings.items():
            if isinstance(val, bool):
                resp.settings[k].bool_param = val
            elif isinstance(val, int):
                resp.settings[k].uint32_param = val
            else:
                resp.settings[k].string_param = str(val)
        return resp

    # -- inference ---------------------------------------------------------
    async def ModelInfer(self, request, context):
        try:
            t_recv = time.monotonic_ns()
            req = _decode_pb_request(request)
            _read_trace_metadata(req, context)
            # span tracing: proto decode is the DECODE child span
            # (arrival_ns stays at construction — queue statistics must not
            # absorb proto-decode time); this frontend finalizes so
            # SERIALIZE/NETWORK_WRITE land in the trace
            req.decode_start_ns = t_recv
            req.decode_end_ns = time.monotonic_ns()
            req.trace_handoff = True
            req.protocol = "grpc"
            # the memory governor's ledger entry: serialized message size
            req.wire_bytes = request.ByteSize()
            resp = await self._core.infer(req)
        except InferError as e:
            rid = getattr(req, "client_request_id", "") \
                if "req" in locals() else ""
            if e.http_status >= 500:
                log_off_loop(
                    self._core.log.error,
                    f"grpc ModelInfer '{request.model_name}' failed: {e}",
                    rid)
            elif self._core.log.verbose_enabled():
                log_off_loop(
                    self._core.log.verbose, 1,
                    f"grpc ModelInfer '{request.model_name}' -> "
                    f"{e.http_status}: {e}", rid)
            ra = getattr(e, "retry_after_s", None)
            if ra is not None:
                # server pushback (gRPC A6): the resilience layer reads
                # this trailing metadata and backs off for exactly this
                # horizon instead of its computed jitter
                try:
                    context.set_trailing_metadata(
                        (("retry-after-ms", str(int(ra * 1000))),))
                except Exception:
                    pass  # metadata already sent / bridge test double
            await context.abort(_grpc_code(e), str(e))
        if self._core.log.verbose_enabled():
            log_off_loop(
                self._core.log.verbose, 1,
                f"grpc ModelInfer '{request.model_name}' -> OK",
                req.client_request_id)
        if req.client_request_id:
            # echo the correlation id in trailing metadata (the response
            # parameters carry it too, for clients that never see metadata)
            try:
                context.set_trailing_metadata(
                    (("triton-request-id", req.client_request_id),))
            except Exception:
                pass  # metadata already sent / transport gone
        trace = resp.trace
        try:
            t_ser0 = time.monotonic_ns() if trace is not None else 0
            # wire fast path: template-stamped response message (see
            # server/wire.py) — the one remaining payload copy is the
            # protobuf-required bytes materialization
            pb_resp = encode_pb_response(
                resp, cache=self._core.grpc_wire_templates,
                generation=self._core.registry.generation(resp.model_name))
            if trace is not None:
                t_ser1 = time.monotonic_ns()
                trace.add_span("SERIALIZE", t_ser0, t_ser1)
                # grpc.aio serializes+writes after the handler returns; this
                # span covers the handoff work still visible from here
                trace.add_span("NETWORK_WRITE", t_ser1, time.monotonic_ns())
        except BaseException as e:
            # encode failures after the core reported success must still
            # land in the flight record as failures (same contract as the
            # HTTP frontend)
            if trace is not None:
                trace.mark_failed(e)
            raise
        finally:
            if trace is not None:
                await trace.emit_async()
        return pb_resp

    async def ModelStreamInfer(self, request_iterator, context):
        """Bidi stream: requests arrive as they're sent; each produces one or
        more ``ModelStreamInferResponse``s (errors travel in-band in
        ``error_message``, reference _infer_stream.py:142-167)."""
        async for request in request_iterator:
            try:
                req = _decode_pb_request(request)
                _read_trace_metadata(req, context)
                req.protocol = "grpc"
                req.wire_bytes = request.ByteSize()
                enable_empty_final = bool(
                    req.parameters.get("triton_enable_empty_final_response", False)
                )
                agen = self._core.infer_stream(req)
                try:
                    async for resp in agen:
                        is_empty_final = (
                            not resp.outputs
                            and resp.parameters.get("triton_final_response") is True
                        )
                        if is_empty_final and not enable_empty_final:
                            continue
                        tr = resp.trace
                        if tr is None:
                            yield pb.ModelStreamInferResponse(
                                infer_response=build_pb_response(resp)
                            )
                            continue
                        # traced stream: proto encode + transport handoff
                        # per flushed chunk, batched at the token stride
                        # inside record_write
                        t0 = time.monotonic_ns()
                        yield pb.ModelStreamInferResponse(
                            infer_response=build_pb_response(resp)
                        )
                        tr.record_write(t0, time.monotonic_ns())
                finally:
                    # deterministic close: a broken bidi transport must
                    # reach the core's stream envelope (cancel accounting,
                    # the stream trace record) now, not at GC time
                    await agen.aclose()
            except InferError as e:
                # the bidi wire has no per-message grpc code, so the
                # status rides in-band as a "[NNN] " prefix — streaming
                # clients (grpc/_utils.stream_error_to_exception) map it
                # back to a typed status so shed/deadline failures stay
                # classifiable on streams too
                yield pb.ModelStreamInferResponse(
                    error_message=f"[{e.http_status}] {e}")
            except Exception as e:  # pragma: no cover - defensive
                yield pb.ModelStreamInferResponse(error_message=str(e))


def _grpc_code(e: InferError) -> grpc.StatusCode:
    return {
        400: grpc.StatusCode.INVALID_ARGUMENT,
        404: grpc.StatusCode.NOT_FOUND,
        # oversize wire payloads (the --max-request-bytes cap; normally
        # rejected by the channel option before the handler runs, but a
        # handler-raised 413 — e.g. through the gRPC-Web bridge — must
        # map to the same code the transport rejection carries)
        413: grpc.StatusCode.RESOURCE_EXHAUSTED,
        # resilience layer: shed load / drain / blown deadline map to the
        # codes the client retry policy gates on (RESOURCE_EXHAUSTED and
        # UNAVAILABLE retryable; DEADLINE_EXCEEDED deliberately not)
        429: grpc.StatusCode.RESOURCE_EXHAUSTED,
        503: grpc.StatusCode.UNAVAILABLE,
        504: grpc.StatusCode.DEADLINE_EXCEEDED,
        500: grpc.StatusCode.INTERNAL,
    }.get(e.http_status, grpc.StatusCode.UNKNOWN)


def build_grpc_server(
    core: InferenceCore, address: str = "[::]:8001", tls=None,
    reuse_port: bool = False,
    max_request_bytes: int = DEFAULT_MAX_REQUEST_BYTES,
) -> "grpc.aio.Server":
    cap = max(0, int(max_request_bytes or 0))
    server = grpc.aio.server(
        options=[
            ("grpc.max_send_message_length", -1),
            # wire ingress cap (server/memory.py layer 1): a REAL channel
            # option, so an oversize message is refused by the transport
            # — RESOURCE_EXHAUSTED carrying both sizes ("Received message
            # larger than max (N vs. M)") — before the body ever
            # materializes in this process.  0 = explicit opt-out
            # (--max-request-bytes 0), restoring the old unbounded -1
            ("grpc.max_receive_message_length", cap if cap else -1),
            # explicit either way: ON for the multi-process frontend
            # topology (N workers share the port, kernel balances
            # accepts), OFF for single-process so a double-bind fails
            # loudly instead of silently splitting traffic (gRPC's
            # Linux default is ON)
            ("grpc.so_reuseport", 1 if reuse_port else 0),
        ]
    )
    add_GRPCInferenceServiceServicer_to_server(InferenceServicer(core), server)
    if tls is not None:
        server.add_secure_port(address, tls.grpc_credentials())
    else:
        server.add_insecure_port(address)
    return server
