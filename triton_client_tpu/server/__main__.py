"""CLI: ``python -m triton_client_tpu.server`` — run the v2 serving harness.

Examples::

    # serve the built-in model zoo (simple, simple_identity, ...):
    python -m triton_client_tpu.server --zoo

    # serve a Triton-style model repository directory:
    python -m triton_client_tpu.server --model-repository ./models
"""

from __future__ import annotations

import argparse
import asyncio
import os

# The container's sitecustomize imports jax at interpreter startup, BEFORE
# user env vars are consulted — so ``JAX_PLATFORMS=cpu python -m ...`` is
# silently ignored and the server grabs the TPU. Re-apply the requested
# platform through jax.config, which still works until a backend
# initializes.
if "JAX_PLATFORMS" in os.environ:
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from .core import InferenceCore
from .frontends import start_frontends
from .registry import ModelRegistry
from .tls import maybe_tls


def main() -> None:
    parser = argparse.ArgumentParser(description="triton_client_tpu serving harness")
    parser.add_argument("--model-repository", default=None, help="model repository dir")
    parser.add_argument("--zoo", action="store_true", help="register the built-in model zoo")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument("--grpc-port", type=int, default=8001)
    parser.add_argument("--frontends", type=int, default=1, metavar="N",
                        help="number of frontend processes sharing the "
                        "HTTP/gRPC ports via SO_REUSEPORT (the kernel "
                        "load-balances accepted connections), so the "
                        "serving data plane scales past one Python "
                        "process's GIL.  Each worker exposes its own "
                        "metrics port at --metrics-port + index; client "
                        "shared-memory registrations are shared across "
                        "workers through a manifest directory.  Default 1 "
                        "(single process, no SO_REUSEPORT)")
    parser.add_argument("--frontend-worker", type=int, default=None,
                        help=argparse.SUPPRESS)  # internal: worker index
    parser.add_argument("--worker-restart-limit", type=int, default=5,
                        metavar="K",
                        help="self-healing supervisor storm bound: a "
                        "crashed frontend worker is restarted with capped "
                        "exponential backoff, but K crashes of one worker "
                        "inside --worker-restart-window fail the whole "
                        "fleet fast (a broken binary must not hot-loop); "
                        "1 restores the old fail-fast-on-first-crash "
                        "behavior (default 5)")
    parser.add_argument("--worker-restart-window", type=float, default=30.0,
                        metavar="S",
                        help="sliding window (seconds) the storm bound "
                        "counts crashes over; crashes aging out of it also "
                        "reset the restart backoff (default 30)")
    parser.add_argument("--autoscale", action="append", default=None,
                        metavar="MODEL=MIN..MAX",
                        help="enable closed-loop instance autoscaling for "
                        "MODEL between MIN and MAX concurrent batches "
                        "(repeatable; either bound may be omitted around "
                        "'..').  Scale-out triggers on SLO burn rate at/"
                        "over --slo-burn-threshold or a deep batcher "
                        "backlog; scale-in on sustained idle duty cycle.  "
                        "Model configs can declare the same via "
                        "autoscale.min_instances / autoscale.max_instances "
                        "parameters")
    parser.add_argument("--autoscale-interval", type=float, default=1.0,
                        metavar="S",
                        help="fleet control-loop evaluation period "
                        "(default 1.0s)")
    parser.add_argument("--verbose", "-v", action="store_true")
    parser.add_argument("--ssl-certfile", default=None,
                        help="serve HTTPS/secure-gRPC with this PEM cert chain")
    parser.add_argument("--ssl-keyfile", default=None,
                        help="PEM private key matching --ssl-certfile")
    parser.add_argument("--capture-slower-than", default="p99",
                        metavar="P|MS",
                        help="flight-recorder watchdog threshold: a live "
                        "per-model quantile (p50/p90/p95/p99/p999, default "
                        "p99) or an absolute milliseconds value — requests "
                        "beyond it (and every failure) are pinned with a "
                        "full span tree")
    parser.add_argument("--flight-recorder-size", type=int, default=1024,
                        help="ring-buffer capacity of the always-on "
                        "flight recorder (recent-request summaries)")
    parser.add_argument("--flight-recorder-outliers", type=int, default=32,
                        help="pinned-outlier buffer capacity (slow/failed "
                        "requests with full span trees)")
    parser.add_argument("--no-flight-recorder", action="store_true",
                        help="disable per-request flight recording "
                        "entirely (the /v2/debug/flight_recorder surface "
                        "stays up but records nothing)")
    parser.add_argument("--slo", action="append", default=None,
                        metavar="MODEL=P99_MS[:AVAILABILITY]",
                        help="per-model SLO (repeatable): p99 latency "
                        "target in ms plus an availability objective "
                        "(default 0.999).  Drives the nv_slo_burn_rate / "
                        "nv_slo_budget_remaining gauges (5m/1h "
                        "multi-window burn rates over 1-availability "
                        "error budget) and burn-rate-triggered flight-"
                        "recorder pinning; model configs can declare the "
                        "same via slo.p99_ms / slo.availability "
                        "parameters")
    parser.add_argument("--slo-burn-threshold", type=float, default=None,
                        metavar="X",
                        help="multi-window breach threshold: a model is "
                        "breaching (and SLO-bad requests are pinned) when "
                        "BOTH the 5m and 1h burn rates exceed this "
                        "(default 14.4, the canonical fast-burn page "
                        "threshold)")
    parser.add_argument("--profile-hz", type=float, default=None,
                        metavar="HZ",
                        help="always-on host sampling profiler rate "
                        "(folded stacks per thread role, nv_host_* "
                        "metrics, /v2/debug/profile).  Default from "
                        "TRITON_TPU_PROFILE_HZ, else 19; 0 disables the "
                        "sampler (the loop-lag probe and GC accounting "
                        "stay on — they are effectively free)")
    parser.add_argument("--incident-dir", default=None, metavar="DIR",
                        help="directory for automatic incident bundles "
                        "(postmortems on SLO burn, worker crash, watchdog "
                        "storm, chaos draws, SIGUSR2, or POST "
                        "/v2/debug/incident) and the faulthandler dump "
                        "file.  Default from TRITON_TPU_INCIDENT_DIR, "
                        "else <tmpdir>/tc-tpu-incidents")
    parser.add_argument("--incident-keep", type=int, default=8, metavar="N",
                        help="keep-last-N incident bundle retention: the "
                        "oldest bundles beyond N are pruned after each "
                        "write, so a flapping trigger cannot fill the "
                        "disk (default 8)")
    parser.add_argument("--no-device-stats", action="store_true",
                        help="disable the device/scheduler stats "
                        "collector (nv_tpu_* metrics, batcher tick "
                        "profiling) — the A/B lever bench.py uses to "
                        "bound its fast-path cost")
    parser.add_argument("--drain-timeout", type=float, default=10.0,
                        metavar="S",
                        help="graceful-drain budget on SIGINT/SIGTERM: "
                        "stop accepting (new requests get 503 + "
                        "Retry-After, readiness goes false), wait this "
                        "long for in-flight requests, then tear down")
    from .memory import DEFAULT_MAX_REQUEST_BYTES

    parser.add_argument("--max-request-bytes", type=int,
                        default=DEFAULT_MAX_REQUEST_BYTES, metavar="N",
                        help="wire ingress cap on BOTH frontends: any "
                        "request larger than N bytes is refused before "
                        "its body materializes (HTTP 413 / gRPC "
                        "RESOURCE_EXHAUSTED carrying the limit).  A bare "
                        "serve is bounded by default (64 MiB); 0 is the "
                        "explicit opt-out restoring unbounded ingress")
    parser.add_argument("--mem-budget-bytes", type=int, default=0,
                        metavar="N",
                        help="host byte budget for queued + in-flight "
                        "request/response payloads: over-budget arrivals "
                        "are shed tier-aware (best-effort and largest "
                        "first) with typed 429 + Retry-After instead of "
                        "growing toward the OOM killer (0 = track only, "
                        "never shed)")
    parser.add_argument("--max-queue-size", type=int, default=0,
                        help="default per-model admission bound: requests "
                        "beyond this many pending per model are shed with "
                        "HTTP 429 / gRPC RESOURCE_EXHAUSTED + Retry-After "
                        "(0 = unbounded; a model config's max_queue_size "
                        "parameter overrides per model)")
    parser.add_argument("--shed-retry-after", type=float, default=0.25,
                        metavar="S",
                        help="BASE pushback horizon (seconds) sent with "
                        "shed responses (Retry-After / retry-after-ms); "
                        "the actual horizon scales with the shed tier's "
                        "queue depth")
    parser.add_argument("--qos-tiers", type=int, default=4,
                        help="number of QoS priority tiers; the v2 request "
                        "priority parameter (0 = highest) maps to tier "
                        "min(priority, tiers-1) and the last tier is the "
                        "preemptible best-effort lane (default 4)")
    parser.add_argument("--qos-weights", default=None, metavar="W0,W1,...",
                        help="weighted-fair dequeue weights, one per tier "
                        "(e.g. '8,4,2,1'); default: strict priority")
    parser.add_argument("--qos-tenant-rate", type=float, default=0.0,
                        metavar="RPS",
                        help="default per-tenant token-bucket rate in "
                        "requests/s (0 = no tenant rate limiting); the "
                        "tenant comes from the triton-tenant header or "
                        "basic-auth username, else 'anonymous'")
    parser.add_argument("--qos-tenant-burst", type=float, default=None,
                        help="token-bucket burst allowance (default: "
                        "max(1, rate))")
    parser.add_argument("--qos-tenant-limit", action="append", default=None,
                        metavar="NAME=RATE[:BURST]",
                        help="per-tenant rate override (repeatable); "
                        "RATE 0 exempts the tenant from rate limiting")
    parser.add_argument("--qos-best-effort-fraction", type=float,
                        default=0.5, metavar="F",
                        help="fraction of a model's max_queue_size the "
                        "best-effort tier may fill before it is shed "
                        "(tier 0 always gets 100%%; intermediate tiers "
                        "interpolate; default 0.5)")
    parser.add_argument("--kv-cache-bytes", action="append", default=None,
                        metavar="MODEL=N | N",
                        help="prefix/KV-cache byte budget: 'MODEL=N' pins "
                        "a per-model block-store budget, a bare 'N' sets "
                        "the default for every decode model (repeatable; "
                        "equivalent to TRITON_TPU_KV_CACHE_BYTES[_MODEL]; "
                        "0/unset = cache off).  Block granularity comes "
                        "from TRITON_TPU_KV_BLOCK_TOKENS (default 64)")
    parser.add_argument("--cache-budget-bytes", type=int, default=0,
                        help="byte budget across all response-cache "
                        "entries; inserts evict LRU entries to fit "
                        "(0 = entry-count bound only).  Per-model TTL "
                        "comes from the model config's "
                        "response_cache.ttl_s parameter")
    parser.add_argument("--chaos", type=float, default=0.0, metavar="RATE",
                        help="fault-injection rate in [0,1]: each request "
                        "draws from a seeded RNG and at RATE gets a fault "
                        "from --chaos-kinds (testing the retry/shed/"
                        "deadline paths end to end; injected faults are "
                        "pinned by the flight recorder)")
    parser.add_argument("--chaos-kinds", default="error",
                        help="comma list of latency,error,abort,"
                        "worker_kill,load_fail,mem_pressure,device_error "
                        "(default: error).  device_error fires at the "
                        "decode worker's dispatch boundaries: it "
                        "invalidates the donated bucket buffers and "
                        "raises an XLA-shaped failure, driving the real "
                        "rebuild / generation-recovery / quarantine path")
    parser.add_argument("--chaos-seed", type=int, default=0,
                        help="RNG seed — a fixed seed reproduces the "
                        "exact fault sequence")
    parser.add_argument("--chaos-latency-ms", type=float, default=50.0,
                        help="added delay for latency faults")
    parser.add_argument("--chaos-model", action="append", default=None,
                        metavar="NAME",
                        help="restrict injection to this model "
                        "(repeatable; default: all models)")
    parser.add_argument("--chaos-transient", type=float, default=0.0,
                        metavar="S",
                        help="recovery window after each injected fault "
                        "(seconds): models time-correlated transient "
                        "faults, so prompt retries land clean "
                        "(0 = independent per-request draws)")
    parser.add_argument("--chaos-pressure-s", type=float, default=1.0,
                        metavar="S",
                        help="mem_pressure window: how long each draw "
                        "holds the shrunken byte budget before it "
                        "restores on its own (default 1.0s)")
    parser.add_argument("--chaos-pressure-factor", type=float, default=0.5,
                        metavar="F",
                        help="mem_pressure shrink: the live byte budget "
                        "drops to F x --mem-budget-bytes while a "
                        "pressure window holds (default 0.5)")
    parser.add_argument("--device-fault-threshold", type=int, default=3,
                        metavar="K",
                        help="dispatch faults inside --device-fault-window "
                        "that quarantine a model: not-ready on both "
                        "protocols, typed retryable 503s with pushback "
                        "until a probe dispatch succeeds (default 3)")
    parser.add_argument("--device-fault-window", type=float, default=30.0,
                        metavar="S",
                        help="sliding window for the K-fault quarantine "
                        "detector (default 30s)")
    parser.add_argument("--device-fault-probe-backoff", type=float,
                        default=1.0, metavar="S",
                        help="initial delay before a quarantined model's "
                        "first probe dispatch; doubles per failed probe "
                        "(default 1s)")
    parser.add_argument("--device-fault-probe-backoff-max", type=float,
                        default=30.0, metavar="S",
                        help="probe backoff ceiling (default 30s)")
    parser.add_argument("--tick-stall-ms", type=float, default=None,
                        metavar="MS",
                        help="arm the decode readback watchdog: a tick/"
                        "prefill readback that takes longer than MS to "
                        "resolve reports a tick_stall device fault and "
                        "quarantines the model (a wedged dispatch cannot "
                        "be killed host-side — this reroutes traffic and "
                        "captures the incident while it is stuck; sets "
                        "TRITON_TPU_TICK_STALL_MS)")
    parser.add_argument("--metrics-port", type=int, default=8002,
                        help="dedicated Prometheus /metrics port (Triton "
                        "convention; 0 disables — /metrics stays on the "
                        "main HTTP port either way)")
    parser.add_argument("--otlp-endpoint", default=None, metavar="URL",
                        help="OTLP/HTTP collector to export trace spans "
                        "to (e.g. http://collector:4318 — /v1/traces is "
                        "appended when the URL has no path).  Dependency-"
                        "free: records are encoded as proto-JSON "
                        "ResourceSpans and batched by a background "
                        "exporter that never blocks the serving path")
    parser.add_argument("--coordinator-address", default=None,
                        help="host:port of process 0 — enables multi-host "
                        "(jax.distributed over DCN); every host runs this "
                        "server and shares the global device mesh")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--serve-mesh", default=None, metavar="SPEC",
                        help="device mesh served models shard over: '1' "
                        "(default, one chip), 'all', an integer N, or an "
                        "explicit shape like 'dp=1,pp=2,ep=2,sp=1,tp=2' "
                        "(sets TRITON_TPU_SERVE_MESH)")
    args = parser.parse_args()
    if args.serve_mesh is not None:
        os.environ["TRITON_TPU_SERVE_MESH"] = args.serve_mesh
    if args.tick_stall_ms is not None:
        if args.tick_stall_ms <= 0:
            parser.error("--tick-stall-ms must be positive")
        # env-var handoff like --serve-mesh: the decode worker arms its
        # watchdog from the environment at lazy init
        os.environ["TRITON_TPU_TICK_STALL_MS"] = str(args.tick_stall_ms)
    for spec in (args.kv_cache_bytes or []):
        # env-var handoff like the flags above: the decode worker builds
        # its block store from the environment at lazy init (kvcache.py)
        from .kvcache import cache_env_key

        name, sep, val = spec.partition("=")
        raw = val if sep else name
        try:
            nbytes = int(raw)
        except ValueError:
            parser.error(f"--kv-cache-bytes {spec!r}: budget must be an "
                         "integer byte count")
        if nbytes < 0:
            parser.error(f"--kv-cache-bytes {spec!r}: budget must be >= 0")
        key = cache_env_key(name) if sep else "TRITON_TPU_KV_CACHE_BYTES"
        os.environ[key] = str(nbytes)
    if args.device_fault_threshold < 1:
        parser.error("--device-fault-threshold must be >= 1")
    if args.device_fault_window <= 0:
        parser.error("--device-fault-window must be positive")
    if args.frontends < 1:
        parser.error("--frontends must be >= 1")
    # autoscale flags validate BEFORE the supervisor branch: a typo'd
    # spec must be an instant flag error, not N workers crash-looping
    # into a "crash storm" verdict with the real message buried in
    # their stderr
    from .fleet import parse_autoscale_spec

    autoscale_bounds = {}
    for spec in (args.autoscale or []):
        try:
            name, bounds = parse_autoscale_spec(spec)
        except ValueError as e:  # typo'd spec — fail at startup, loudly
            parser.error(str(e))
        autoscale_bounds[name] = bounds
    if args.autoscale_interval <= 0:
        parser.error("--autoscale-interval must be positive")
    worker_index = args.frontend_worker
    if args.frontends > 1 and worker_index is None:
        # supervisor: spawn N frontend workers sharing the ports via
        # SO_REUSEPORT and babysit them — no models load in this process
        _run_supervisor(parser, args)
        return
    from ..parallel import initialize_multihost

    if (args.num_processes is not None or args.process_id is not None) \
            and not (args.coordinator_address
                     or os.environ.get("JAX_COORDINATOR_ADDRESS")):
        parser.error("--num-processes/--process-id require "
                     "--coordinator-address (or JAX_COORDINATOR_ADDRESS)")
    if initialize_multihost(args.coordinator_address, args.num_processes,
                            args.process_id):
        import jax

        print(f"multi-host: process {jax.process_index()}/"
              f"{jax.process_count()}, {len(jax.devices())} global devices")
    try:
        tls = maybe_tls(args.ssl_certfile, args.ssl_keyfile)
    except ValueError as e:
        parser.error(str(e))

    registry = ModelRegistry(repository_path=args.model_repository)
    if args.model_repository:
        for entry in registry.index():
            try:
                registry.load(entry["name"])
                print(f"loaded model '{entry['name']}'")
            except Exception as e:
                print(f"failed to load '{entry['name']}': {e}")
    if args.zoo or not args.model_repository:
        from ..models import zoo

        zoo.register_all(registry)
        print(f"registered model zoo: {[e['name'] for e in registry.index()]}")

    core = InferenceCore(registry)
    core.default_max_queue_size = max(0, args.max_queue_size)
    core.shed_retry_after_s = max(0.0, args.shed_retry_after)
    if args.max_request_bytes < 0:
        parser.error("--max-request-bytes must be >= 0 (0 = unbounded)")
    if args.mem_budget_bytes < 0:
        parser.error("--mem-budget-bytes must be >= 0 (0 = track only)")
    core.memory.budget_bytes = args.mem_budget_bytes
    if args.mem_budget_bytes:
        print(f"memory governor: host budget {args.mem_budget_bytes} bytes")
    from .qos import QosManager, parse_tenant_limit

    try:
        weights = ([int(w) for w in args.qos_weights.split(",")]
                   if args.qos_weights else None)
        tenant_rates = {}
        for spec in (args.qos_tenant_limit or []):
            name, rate, burst = parse_tenant_limit(spec)
            tenant_rates[name] = (rate, burst)
        core.qos = QosManager(
            tiers=args.qos_tiers,
            tenant_rate=max(0.0, args.qos_tenant_rate),
            tenant_burst=args.qos_tenant_burst,
            tenant_rates=tenant_rates,
            best_effort_fraction=args.qos_best_effort_fraction,
            weights=weights)
    except ValueError as e:
        parser.error(str(e))
    if args.cache_budget_bytes > 0:
        core.response_cache.budget_bytes = args.cache_budget_bytes
    # device-fault containment knobs (the manager itself is always on)
    core.device_faults.threshold = args.device_fault_threshold
    core.device_faults.window_s = args.device_fault_window
    core.device_faults.probe_backoff_s = max(
        0.05, args.device_fault_probe_backoff)
    core.device_faults.probe_backoff_max_s = max(
        core.device_faults.probe_backoff_s,
        args.device_fault_probe_backoff_max)
    if args.chaos > 0.0:
        from .chaos import build_injector

        try:
            core.chaos = build_injector(
                args.chaos, kinds_csv=args.chaos_kinds,
                seed=args.chaos_seed, latency_ms=args.chaos_latency_ms,
                models=args.chaos_model,
                transient_s=max(0.0, args.chaos_transient),
                pressure_s=max(0.0, args.chaos_pressure_s),
                pressure_factor=args.chaos_pressure_factor)
        except ValueError as e:
            parser.error(str(e))
        print(f"chaos injection ON: rate={args.chaos} "
              f"kinds={core.chaos.kinds} seed={args.chaos_seed}")
        if "worker_kill" in core.chaos.kinds:
            # a worker_kill draw must look exactly like a real crash: hard
            # process exit, no drain, no atexit — the self-healing
            # supervisor (or the operator's init system) is what heals it
            core.chaos.worker_kill_cb = lambda: os._exit(70)
            print("chaos: worker_kill armed — this process hard-exits "
                  "when the fault fires")
    from .fleet import FleetController

    # the controller is always attached (rolling updates + nv_fleet_*
    # actuation counters need it); its loop only ever actuates models
    # with explicit --autoscale bounds or autoscale.* config parameters
    core.fleet = FleetController(core, interval_s=args.autoscale_interval,
                                 bounds=autoscale_bounds)
    for name, (lo, hi) in sorted(autoscale_bounds.items()):
        print(f"autoscale: {name} instances in [{lo}, {hi}]")
    try:
        core.flight_recorder.configure(
            capacity=args.flight_recorder_size,
            outlier_capacity=args.flight_recorder_outliers,
            capture_slower_than=args.capture_slower_than,
            enabled=not args.no_flight_recorder)
    except Exception as e:  # invalid threshold spec — fail at startup
        parser.error(str(e))
    from .device_stats import parse_slo_spec

    if args.no_device_stats:
        core.device_stats.enabled = False
    # host self-observation: the CLI flag wins over the env default the
    # profiler was constructed with; the incident dir also hosts the
    # faulthandler dump (enabled below) so every postmortem artifact of
    # one process lands in one place
    if args.profile_hz is not None:
        if args.profile_hz < 0:
            parser.error("--profile-hz must be >= 0 (0 = sampler off)")
        core.profiler.hz = args.profile_hz
    if args.incident_keep < 1:
        parser.error("--incident-keep must be >= 1")
    core.incidents.keep = args.incident_keep
    if args.incident_dir:
        core.incidents.dir = args.incident_dir
    os.makedirs(core.incidents.dir, exist_ok=True)
    # faulthandler on by default: a hard hang or fatal signal dumps every
    # thread's stack into the incident dir instead of dying silently.
    # The file object must outlive the process (faulthandler keeps only
    # the fd) — parked on the core.
    import faulthandler

    core._faulthandler_file = open(
        os.path.join(core.incidents.dir,
                     f"faulthandler-{os.getpid()}.log"), "w")
    faulthandler.enable(file=core._faulthandler_file)
    print(f"incident capture: dir={core.incidents.dir} "
          f"keep={core.incidents.keep} profiler_hz={core.profiler.hz:g} "
          "(SIGUSR2 triggers a manual bundle)")
    if args.slo_burn_threshold is not None:
        if args.slo_burn_threshold <= 0:
            parser.error("--slo-burn-threshold must be positive")
        core.slo.burn_threshold = args.slo_burn_threshold
    for spec in (args.slo or []):
        try:
            name, objective = parse_slo_spec(spec)
        except ValueError as e:  # typo'd SLO — fail at startup, loudly
            parser.error(str(e))
        core.slo.set_objective(name, objective)
        print(f"SLO: {name} p99<={objective.p99_ms:g}ms "
              f"availability={objective.availability:g}")

    # replica identity: every trace record this process emits carries it,
    # so a cross-replica journey join can tell which replica served which
    # attempt.  TRITON_TPU_REPLICA wins (fleet operators name replicas);
    # otherwise host:port plus the frontend worker index when sharded.
    replica = os.environ.get("TRITON_TPU_REPLICA", "")
    if not replica:
        replica = f"{args.host}:{args.http_port}"
        if worker_index is not None:
            replica += f"#w{worker_index}"
    core.tracer.replica = replica
    if args.otlp_endpoint:
        try:
            core.enable_otlp(args.otlp_endpoint, replica=replica)
        except ValueError as e:
            parser.error(str(e))
        print(f"OTLP export: {args.otlp_endpoint} (replica={replica})")

    # per-worker metrics port: the main ports are kernel-balanced across
    # workers, so the dedicated metrics/debug port is the one per-process
    # surface — worker i serves it at base + i
    metrics_port = ((args.metrics_port + (worker_index or 0))
                    if args.metrics_port else None)

    async def serve():
        import signal

        from .frontends import install_aio_noise_filter, stop_frontends

        # grpc.aio poller wakeup races print benign BlockingIOError
        # tracebacks through the default handler; filter that one
        # signature (see frontends.install_aio_noise_filter)
        install_aio_noise_filter(asyncio.get_running_loop())
        warmed = await core.warmup_models()
        if warmed:
            print(f"warmed up: {warmed}")
        core.fleet.start()  # the closed-loop evaluation tick
        # hold the returned handles: a dropped grpc.aio.Server is torn down
        # by its finalizer, silently closing the port
        frontends = await start_frontends(
            core, args.host, args.http_port, args.grpc_port, tls=tls,
            metrics_port=metrics_port,
            reuse_port=worker_index is not None,
            max_request_bytes=args.max_request_bytes)
        scheme = "https" if tls else "http"
        metrics = (f" metrics={args.host}:{metrics_port}"
                   if metrics_port else "")
        worker = (f" [frontend worker {worker_index}/{args.frontends}]"
                  if worker_index is not None else "")
        print(
            f"serving v2 protocol: {scheme}={args.host}:{args.http_port} "
            f"grpc{'s' if tls else ''}={args.host}:{args.grpc_port}"
            f"{metrics}{worker}"
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-unix event loops
                pass
        # SIGUSR2 = "bundle the process, keep serving": the operator's
        # live postmortem trigger (the bundle writes on its own thread)
        if hasattr(signal, "SIGUSR2"):
            try:
                loop.add_signal_handler(
                    signal.SIGUSR2,
                    lambda: core.incidents.trigger(
                        "sigusr2", reason="operator SIGUSR2"))
            except NotImplementedError:  # non-unix event loops
                pass
        await stop.wait()
        # graceful drain BEFORE the listeners close: new requests get a
        # proper 503 + Retry-After (and readiness flips false so a load
        # balancer stops routing) while in-flight ones run to completion —
        # killing the sockets first would sever them with connection resets
        print("shutting down: draining in-flight requests "
              f"(up to {args.drain_timeout:g}s)")
        await core.shutdown(drain_s=max(0.0, args.drain_timeout))
        await stop_frontends(*frontends)

    # optional uvloop (TRITON_TPU_UVLOOP=1): the same env gate the aio
    # clients honor now accelerates the server's event loop too — both
    # ends of the socket.  Graceful stdlib fallback when not installed.
    from .._uvloop import maybe_install_uvloop

    if maybe_install_uvloop():
        print("event loop: uvloop")
    try:
        asyncio.run(serve())
    except KeyboardInterrupt:
        pass  # second ^C mid-drain, or non-unix loop without handlers


def _run_supervisor(parser, args) -> None:
    """``--frontends N`` parent: spawn N workers that re-exec this module
    with ``--frontend-worker i``, each binding the SAME HTTP/gRPC ports
    with SO_REUSEPORT (the kernel balances accepted connections across
    them).  Shutdown reuses the PR 4 drain machinery per worker: signals
    are forwarded and every worker runs its own graceful drain.

    The supervisor is SELF-HEALING (server/fleet.py): a worker that dies
    on its own is respawned with capped exponential backoff — the
    replacement re-execs with the same SO_REUSEPORT ports and the same
    shm-manifest directory, so it rejoins the kernel's accept balancing
    and re-resolves client shared-memory registrations from the manifest
    with no client action.  Restarts are counted into the shared fleet
    state file (``nv_fleet_worker_restart_total`` on every worker's
    metrics surface).  Only a crash STORM — ``--worker-restart-limit``
    crashes of one worker inside ``--worker-restart-window`` — fails the
    fleet fast (drain the siblings rather than hot-loop a broken
    binary)."""
    import shutil
    import signal
    import socket
    import subprocess
    import sys
    import tempfile
    import time

    from .fleet import (FLEET_STATE_ENV, RestartPolicy, SupervisorState,
                        crash_reason_from_exit)

    if not hasattr(socket, "SO_REUSEPORT"):
        parser.error("--frontends > 1 requires SO_REUSEPORT (Linux)")
    if (args.coordinator_address or args.num_processes is not None
            or args.process_id is not None):
        parser.error("--frontends > 1 is incompatible with multi-host "
                     "serving (each host runs one server process)")
    if args.worker_restart_limit < 1:
        parser.error("--worker-restart-limit must be >= 1")
    # each worker hosts a full InferenceCore replica: host-placed models
    # replicate cheaply, but a single accelerator cannot be opened by N
    # processes — keep TPU serving on --frontends 1 (the co-located
    # zero-copy topology) unless the platform says otherwise
    if os.environ.get("JAX_PLATFORMS", "").lower() not in ("cpu", "cuda"):
        print("warning: --frontends > 1 replicates the core per process; "
              "device-placed models need JAX_PLATFORMS=cpu workers or a "
              "single frontend process", file=sys.stderr)
    # client shm registrations land on ONE kernel-picked worker; the
    # manifest directory lets every sibling resolve them (server/shm.py).
    # The fleet state file rides the same directory: workers read restart
    # counters back out of it for nv_fleet_worker_restart_total.
    manifest = tempfile.mkdtemp(prefix="tc-tpu-shm-manifest-")
    fleet_state = SupervisorState(os.path.join(manifest, "fleet-state.json"))
    env = dict(os.environ, TRITON_TPU_SHM_MANIFEST=manifest)
    env[FLEET_STATE_ENV] = fleet_state.path

    def spawn(i: int) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "triton_client_tpu.server",
               *sys.argv[1:], "--frontend-worker", str(i)]
        p = subprocess.Popen(cmd, env=env)
        print(f"frontend worker {i}: pid {p.pid}", flush=True)
        return p

    procs: list = []
    rc = 0
    try:
        procs = [spawn(i) for i in range(args.frontends)]
        policies = [RestartPolicy(storm_limit=args.worker_restart_limit,
                                  window_s=args.worker_restart_window)
                    for _ in procs]
        restart_at = [None] * len(procs)  # pending respawn deadlines
        crash_reason = [None] * len(procs)  # why the pending respawn
        print(f"frontend supervisor: {args.frontends} workers sharing "
              f"http={args.host}:{args.http_port} "
              f"grpc={args.host}:{args.grpc_port} (SO_REUSEPORT, "
              f"self-healing: restart with backoff, fail-fast after "
              f"{args.worker_restart_limit} crashes/"
              f"{args.worker_restart_window:g}s)")
        state = {"stopping": False}

        def forward(signum, _frame):
            # graceful drain per worker: each one sheds new work (503 +
            # Retry-After, readiness false) and finishes in-flight
            # requests inside its own --drain-timeout.  Pending respawns
            # are cancelled — a stopping fleet heals nothing.
            state["stopping"] = True
            for p in procs:
                if p is not None and p.poll() is None:
                    try:
                        p.send_signal(signum)
                    except OSError:
                        pass

        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, forward)

        def fail_fast() -> None:
            state["stopping"] = True
            for q in procs:
                if q is not None and q.poll() is None:
                    try:
                        q.send_signal(signal.SIGTERM)
                    except OSError:
                        pass

        while True:
            now = time.monotonic()
            if not state["stopping"]:
                for i, p in enumerate(procs):
                    if p is None or p.poll() is None:
                        continue
                    # a worker died on its own (any exit while not
                    # stopping is unexpected — the server runs forever)
                    code = p.returncode or 0
                    procs[i] = None
                    # decode WHY before the returncode is lost: signal
                    # name, the chaos worker_kill exit-70 convention, or
                    # the plain exit code — stamped into the fleet state
                    # so the workers' worker-crash incident bundles can
                    # say what killed their sibling
                    crash_reason[i] = crash_reason_from_exit(p.returncode)
                    delay = policies[i].on_crash(now)
                    if delay is None:
                        print(f"frontend worker {i}: "
                              f"{policies[i].storm_limit} crashes inside "
                              f"{policies[i].window_s:g}s — crash storm, "
                              "failing fast (draining siblings)",
                              file=sys.stderr, flush=True)
                        rc = max(rc, 1 if code <= 0 else code)
                        fail_fast()
                        restart_at = [None] * len(procs)
                        break
                    print(f"frontend worker {i} exited rc={code} "
                          f"({crash_reason[i]}); restarting in {delay:g}s "
                          "(SO_REUSEPORT rebind + shm manifest re-issued)",
                          file=sys.stderr, flush=True)
                    restart_at[i] = now + delay
                for i, due in enumerate(restart_at):
                    if due is not None and now >= due \
                            and not state["stopping"]:
                        restart_at[i] = None
                        procs[i] = spawn(i)
                        fleet_state.record_restart(
                            str(i), reason=crash_reason[i])
            alive = any(p is not None and p.poll() is None for p in procs)
            pending = any(due is not None for due in restart_at)
            if state["stopping"] and not alive:
                break
            if not state["stopping"] and not alive and not pending:
                break  # defensive: nothing left to supervise
            time.sleep(0.2)
        # a signal-killed worker (negative returncode) is a failure, not
        # an exotic success; healed crashes don't count against the exit
        rc = max([rc] + [1 if (p.returncode or 0) < 0
                         else (p.returncode or 0)
                         for p in procs if p is not None])
    finally:
        for p in procs:
            if p is not None and p.poll() is None:
                p.kill()
        shutil.rmtree(manifest, ignore_errors=True)
    if rc:
        sys.exit(rc)


if __name__ == "__main__":
    main()
