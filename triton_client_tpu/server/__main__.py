"""CLI: ``python -m triton_client_tpu.server`` — run the v2 serving harness.

Examples::

    # serve the built-in model zoo (simple, simple_identity, ...):
    python -m triton_client_tpu.server --zoo

    # serve a Triton-style model repository directory:
    python -m triton_client_tpu.server --model-repository ./models
"""

from __future__ import annotations

import argparse
import asyncio
import os

# The container's sitecustomize imports jax at interpreter startup, BEFORE
# user env vars are consulted — so ``JAX_PLATFORMS=cpu python -m ...`` is
# silently ignored and the server grabs the TPU. Re-apply the requested
# platform through jax.config, which still works until a backend
# initializes.
if "JAX_PLATFORMS" in os.environ:
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from .core import InferenceCore
from .frontends import start_frontends
from .registry import ModelRegistry
from .tls import maybe_tls


def main() -> None:
    parser = argparse.ArgumentParser(description="triton_client_tpu serving harness")
    parser.add_argument("--model-repository", default=None, help="model repository dir")
    parser.add_argument("--zoo", action="store_true", help="register the built-in model zoo")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument("--grpc-port", type=int, default=8001)
    parser.add_argument("--verbose", "-v", action="store_true")
    parser.add_argument("--ssl-certfile", default=None,
                        help="serve HTTPS/secure-gRPC with this PEM cert chain")
    parser.add_argument("--ssl-keyfile", default=None,
                        help="PEM private key matching --ssl-certfile")
    parser.add_argument("--capture-slower-than", default="p99",
                        metavar="P|MS",
                        help="flight-recorder watchdog threshold: a live "
                        "per-model quantile (p50/p90/p95/p99/p999, default "
                        "p99) or an absolute milliseconds value — requests "
                        "beyond it (and every failure) are pinned with a "
                        "full span tree")
    parser.add_argument("--flight-recorder-size", type=int, default=1024,
                        help="ring-buffer capacity of the always-on "
                        "flight recorder (recent-request summaries)")
    parser.add_argument("--flight-recorder-outliers", type=int, default=32,
                        help="pinned-outlier buffer capacity (slow/failed "
                        "requests with full span trees)")
    parser.add_argument("--no-flight-recorder", action="store_true",
                        help="disable per-request flight recording "
                        "entirely (the /v2/debug/flight_recorder surface "
                        "stays up but records nothing)")
    parser.add_argument("--metrics-port", type=int, default=8002,
                        help="dedicated Prometheus /metrics port (Triton "
                        "convention; 0 disables — /metrics stays on the "
                        "main HTTP port either way)")
    parser.add_argument("--coordinator-address", default=None,
                        help="host:port of process 0 — enables multi-host "
                        "(jax.distributed over DCN); every host runs this "
                        "server and shares the global device mesh")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--serve-mesh", default=None, metavar="SPEC",
                        help="device mesh served models shard over: '1' "
                        "(default, one chip), 'all', an integer N, or an "
                        "explicit shape like 'dp=1,pp=2,ep=2,sp=1,tp=2' "
                        "(sets TRITON_TPU_SERVE_MESH)")
    args = parser.parse_args()
    if args.serve_mesh is not None:
        os.environ["TRITON_TPU_SERVE_MESH"] = args.serve_mesh
    from ..parallel import initialize_multihost

    if (args.num_processes is not None or args.process_id is not None) \
            and not (args.coordinator_address
                     or os.environ.get("JAX_COORDINATOR_ADDRESS")):
        parser.error("--num-processes/--process-id require "
                     "--coordinator-address (or JAX_COORDINATOR_ADDRESS)")
    if initialize_multihost(args.coordinator_address, args.num_processes,
                            args.process_id):
        import jax

        print(f"multi-host: process {jax.process_index()}/"
              f"{jax.process_count()}, {len(jax.devices())} global devices")
    try:
        tls = maybe_tls(args.ssl_certfile, args.ssl_keyfile)
    except ValueError as e:
        parser.error(str(e))

    registry = ModelRegistry(repository_path=args.model_repository)
    if args.model_repository:
        for entry in registry.index():
            try:
                registry.load(entry["name"])
                print(f"loaded model '{entry['name']}'")
            except Exception as e:
                print(f"failed to load '{entry['name']}': {e}")
    if args.zoo or not args.model_repository:
        from ..models import zoo

        zoo.register_all(registry)
        print(f"registered model zoo: {[e['name'] for e in registry.index()]}")

    core = InferenceCore(registry)
    try:
        core.flight_recorder.configure(
            capacity=args.flight_recorder_size,
            outlier_capacity=args.flight_recorder_outliers,
            capture_slower_than=args.capture_slower_than,
            enabled=not args.no_flight_recorder)
    except Exception as e:  # invalid threshold spec — fail at startup
        parser.error(str(e))

    async def serve():
        warmed = await core.warmup_models()
        if warmed:
            print(f"warmed up: {warmed}")
        # hold the returned handles: a dropped grpc.aio.Server is torn down
        # by its finalizer, silently closing the port
        frontends = await start_frontends(
            core, args.host, args.http_port, args.grpc_port, tls=tls,
            metrics_port=args.metrics_port or None)
        scheme = "https" if tls else "http"
        metrics = (f" metrics={args.host}:{args.metrics_port}"
                   if args.metrics_port else "")
        print(
            f"serving v2 protocol: {scheme}={args.host}:{args.http_port} "
            f"grpc{'s' if tls else ''}={args.host}:{args.grpc_port}{metrics}"
        )
        await asyncio.Event().wait()

    try:
        asyncio.run(serve())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
