"""HTTP/REST v2 frontend (aiohttp).

Endpoint surface mirrors what the reference HTTP client targets (URI builders
surveyed at http/_client.py:364-1474), including the binary-tensor-data
extension: request/response bodies are ``<json header><concatenated raw
buffers>`` with the JSON length in the ``Inference-Header-Content-Length``
header (reference framing: http/_utils.py:137-150).
"""

from __future__ import annotations

import asyncio
import base64
import binascii
import gzip
import json
import math
import time
import zlib

import numpy as np
from aiohttp import web

from ..utils import deserialize_bytes_tensor, triton_to_np_dtype
from .core import InferenceCore
from .log import log_off_loop
from .memory import DEFAULT_MAX_REQUEST_BYTES
from .qos import tenant_from_headers
from .types import (InferError, InferRequest, InputTensor,
                    RequestedOutput, ShmRef, apply_request_deadline,
                    apply_request_priority, reshape_input)
from .wire import encode_http_response, sse_frame

_HEADER_LEN = "Inference-Header-Content-Length"
_REQUEST_ID_HDR = "triton-request-id"
_TRACEPARENT_HDR = "traceparent"
# remaining client deadline budget in microseconds (the HTTP wire form of
# the v2 `timeout` parameter; restamped per retry attempt by the client
# resilience layer)
_TIMEOUT_HDR = "triton-timeout-us"
# QoS tenant id (falls back to the basic-auth username, then "anonymous")
_TENANT_HDR = "triton-tenant"


def _stamp_qos(req: InferRequest, request: web.Request) -> None:
    """Resolve the request's QoS identity: tenant from the triton-tenant
    header / basic-auth username, priority consumed out of the v2
    ``priority`` parameter (0 = highest)."""
    req.tenant = tenant_from_headers(
        request.headers.get(_TENANT_HDR),
        request.headers.get("Authorization"))
    apply_request_priority(req)


def _oversize_response(size, cap: int) -> web.Response:
    """The typed wire-cap rejection: 413 with the limit in the body and
    the machine-readable headers, BEFORE any body materialization.  The
    pushback headers ride along for symmetry with every other shed, but
    the client resilience layer classifies 413 as non-retryable — the
    same payload can only bounce again; the fix is client-side."""
    size_s = f"request of {size} bytes" if size else "request"
    return web.json_response(
        {"error": f"{size_s} exceeds the server's max request size of "
                  f"{cap} bytes (--max-request-bytes)"},
        status=413,
        headers={
            "Retry-After": "1",
            "triton-retry-after-ms": "1000",
            "triton-max-request-bytes": str(cap),
        })


def _ingress_cap(cap: int):
    """Wire ingress cap middleware (server/memory.py layer 1): reject
    oversize requests from their DECLARED sizes — ``Content-Length``, or
    the ``Inference-Header-Content-Length`` a chunked upload still
    announces — before reading a byte of body; bodies that only reveal
    their size while streaming in are cut off by aiohttp's
    ``client_max_size`` (HTTPRequestEntityTooLarge), converted here to
    the same typed 413 instead of the stock HTML error page."""

    @web.middleware
    async def middleware(request: web.Request, handler):
        declared = request.content_length
        if declared is not None and declared > cap:
            return _oversize_response(declared, cap)
        hlen = request.headers.get(_HEADER_LEN)
        if hlen is not None:
            try:
                if int(hlen) > cap:
                    return _oversize_response(int(hlen), cap)
            except ValueError:
                pass  # junk header: the handler 400s it with context
        try:
            return await handler(request)
        except web.HTTPRequestEntityTooLarge as e:
            return _oversize_response(getattr(e, "actual_size", None), cap)

    return middleware


def build_app(core: InferenceCore,
              max_request_bytes: int = DEFAULT_MAX_REQUEST_BYTES
              ) -> web.Application:
    cap = max(0, int(max_request_bytes or 0))
    # client_max_size enforces the cap on bodies whose size is only
    # discovered while streaming; 0 (explicit opt-out) restores the old
    # 1 GiB aiohttp ceiling
    app = web.Application(client_max_size=cap or 1 << 30,
                          middlewares=[_ingress_cap(cap)] if cap else [])
    r = app.router
    r.add_get("/v2/health/live", _h(core, _health_live))
    r.add_get("/v2/health/ready", _h(core, _health_ready))
    r.add_get("/v2/models/{model}/ready", _h(core, _model_ready))
    r.add_get("/v2/models/{model}/versions/{version}/ready", _h(core, _model_ready))
    r.add_get("/v2", _h(core, _server_metadata))
    r.add_get("/v2/models/{model}", _h(core, _model_metadata))
    r.add_get("/v2/models/{model}/versions/{version}", _h(core, _model_metadata))
    r.add_get("/v2/models/{model}/config", _h(core, _model_config))
    r.add_get("/v2/models/{model}/versions/{version}/config", _h(core, _model_config))
    r.add_get("/v2/models/stats", _h(core, _model_stats))
    r.add_get("/v2/models/{model}/stats", _h(core, _model_stats))
    r.add_get("/v2/models/{model}/versions/{version}/stats", _h(core, _model_stats))
    r.add_post("/v2/repository/index", _h(core, _repo_index))
    r.add_post("/v2/repository/models/{model}/load", _h(core, _repo_load))
    r.add_post("/v2/repository/models/{model}/unload", _h(core, _repo_unload))
    r.add_post("/v2/models/{model}/infer", _h(core, _infer))
    r.add_post("/v2/models/{model}/versions/{version}/infer", _h(core, _infer))
    r.add_post("/v2/models/{model}/generate", _h(core, _generate))
    r.add_post("/v2/models/{model}/versions/{version}/generate",
               _h(core, _generate))
    r.add_post("/v2/models/{model}/generate_stream", _h(core, _generate_stream))
    r.add_post("/v2/models/{model}/versions/{version}/generate_stream",
               _h(core, _generate_stream))
    r.add_get("/v2/trace/setting", _h(core, _get_trace))
    r.add_post("/v2/trace/setting", _h(core, _set_trace))
    r.add_get("/v2/models/{model}/trace/setting", _h(core, _get_trace))
    r.add_post("/v2/models/{model}/trace/setting", _h(core, _set_trace))
    r.add_get("/v2/logging", _h(core, _get_logging))
    r.add_post("/v2/logging", _h(core, _set_logging))
    r.add_get("/v2/debug/flight_recorder", _h(core, _flight_recorder))
    r.add_get("/v2/debug/device_stats", _h(core, _device_stats))
    r.add_get("/v2/debug/costs", _h(core, _costs))
    r.add_get("/v2/debug/profile", _h(core, _profile))
    r.add_get("/v2/debug/incident", _h(core, _incident_status))
    r.add_post("/v2/debug/incident", _h(core, _incident_trigger))
    r.add_get("/metrics", _h(core, _metrics))
    for kind in ("systemsharedmemory", "cudasharedmemory"):
        r.add_get(f"/v2/{kind}/status", _h(core, _shm_status))
        r.add_get(f"/v2/{kind}/region/{{name}}/status", _h(core, _shm_status))
        r.add_post(f"/v2/{kind}/region/{{name}}/register", _h(core, _shm_register))
        r.add_post(f"/v2/{kind}/unregister", _h(core, _shm_unregister))
        r.add_post(f"/v2/{kind}/region/{{name}}/unregister", _h(core, _shm_unregister))

    # OpenAI-compatible surface over the generation stack (/v1/models,
    # /v1/completions, /v1/chat/completions)
    from .openai_api import add_openai_routes

    add_openai_routes(app, core)

    # gRPC-Web bridge: the full v2 gRPC service over HTTP/1.1 framing (used
    # by the C++ gRPC client; interops with stock gRPC-Web stubs).
    from .grpc_server import InferenceServicer
    from .grpc_web import add_grpc_web_routes

    add_grpc_web_routes(app, InferenceServicer(core))
    return app


async def _read_json(request: web.Request, default=None, expect_object=True):
    """Parse a JSON body as a client error (400) — a malformed body (or a
    valid one of the wrong top-level type) must never surface as a 500."""
    if default is not None and not request.can_read_body:
        return default
    try:
        body = await request.json()
    except Exception:
        raise InferError("failed to parse request JSON")
    if expect_object and not isinstance(body, dict):
        raise InferError("request body must be a JSON object")
    return body


def build_metrics_app(core: InferenceCore) -> web.Application:
    """Minimal app for the dedicated Prometheus port (Triton convention:
    :8002): ``/metrics`` plus the two debug snapshots.  Under
    ``--frontends N`` each worker gets its own metrics port (base + worker
    index), so this app is the one per-PROCESS observability surface —
    pointing ``triton-top --url`` at each worker's metrics port gives the
    per-process view that the kernel-balanced main port can't (every poll
    there lands on a random worker)."""
    app = web.Application()
    app.router.add_get("/metrics", _h(core, _metrics))
    app.router.add_get("/v2/debug/flight_recorder", _h(core, _flight_recorder))
    app.router.add_get("/v2/debug/device_stats", _h(core, _device_stats))
    app.router.add_get("/v2/debug/costs", _h(core, _costs))
    app.router.add_get("/v2/debug/profile", _h(core, _profile))
    app.router.add_get("/v2/debug/incident", _h(core, _incident_status))
    app.router.add_post("/v2/debug/incident", _h(core, _incident_trigger))
    return app


def _h(core: InferenceCore, fn):
    async def handler(request: web.Request) -> web.Response:
        # propagated correlation id rides every log line for this request
        # (passed explicitly: the executor hop would lose a contextvar)
        rid = request.headers.get(_REQUEST_ID_HDR, "")
        try:
            resp = await fn(core, request)
            if core.log.verbose_enabled():
                log_off_loop(
                    core.log.verbose, 1,
                    f"{request.method} {request.path} -> {resp.status}",
                    rid)
            return resp
        except InferError as e:
            from .chaos import ChaosAbort

            if isinstance(e, ChaosAbort):
                # injected mid-response connection abort: kill the
                # transport so the client sees a protocol error, not a
                # well-formed 5xx — the connection-class failure the
                # retry layer must absorb
                if request.transport is not None:
                    request.transport.close()
                return web.Response(status=503)
            # 5xx are server-side failures (log_error); 4xx are client
            # mistakes — verbose only, or every fuzz/validation request
            # would spam the log
            if e.http_status >= 500:
                log_off_loop(
                    core.log.error,
                    f"{request.method} {request.path} failed: {e}", rid)
            elif core.log.verbose_enabled():
                log_off_loop(
                    core.log.verbose, 1,
                    f"{request.method} {request.path} -> "
                    f"{e.http_status}: {e}", rid)
            headers = None
            if e.retry_after_s is not None:
                # shed load carries the server's pushback horizon; the
                # client retry policy honors it over its own backoff.
                # Retry-After must be integer delta-seconds (RFC 7231) —
                # the precise sub-second horizon travels alongside in
                # triton-retry-after-ms (this framework's clients prefer
                # it; standards-only intermediaries still parse the RFC
                # form)
                headers = {
                    "Retry-After": str(max(1, math.ceil(e.retry_after_s))),
                    "triton-retry-after-ms":
                        str(int(e.retry_after_s * 1000)),
                }
            return web.json_response({"error": str(e)},
                                     status=e.http_status, headers=headers)
        except web.HTTPException:
            raise
        except Exception as e:  # pragma: no cover - defensive
            log_off_loop(
                core.log.error,
                f"{request.method} {request.path} crashed: {e}", rid)
            return web.json_response({"error": str(e)}, status=500)

    return handler


# -- health / metadata -----------------------------------------------------


async def _health_live(core, request):
    return web.Response(status=200 if core.live else 400)


async def _health_ready(core, request):
    # not-ready while startup warmup runs or any model is mid-load: a
    # load balancer must not route at a server that would compile on its
    # first request (Triton semantics: ready = "will serve now")
    return web.Response(status=200 if core.ready() else 400)


async def _model_ready(core, request):
    # registry-ready AND not quarantined after device faults — a load
    # balancer stops routing at a quarantined model while the server
    # itself stays healthy (see InferenceCore.model_ready)
    ok = core.model_ready(
        request.match_info["model"], request.match_info.get("version", "")
    )
    return web.Response(status=200 if ok else 400)


async def _server_metadata(core, request):
    return web.json_response(core.server_metadata())


async def _model_metadata(core, request):
    model = core.registry.get(
        request.match_info["model"], request.match_info.get("version", "")
    )
    return web.json_response(model.metadata())


async def _model_config(core, request):
    from google.protobuf import json_format

    model = core.registry.get(
        request.match_info["model"], request.match_info.get("version", "")
    )
    cfg = json_format.MessageToDict(model.config, preserving_proto_field_name=True)
    cfg.setdefault("name", model.name)
    return web.json_response(cfg)


async def _model_stats(core, request):
    stats = core.statistics(
        request.match_info.get("model"), request.match_info.get("version", "")
    )
    return web.json_response({"model_stats": stats})


# -- repository ------------------------------------------------------------


async def _repo_index(core, request):
    body = await _read_json(request, default={})
    ready = bool(body.get("ready", False))
    return web.json_response(core.registry.index(ready_only=ready))


async def _repo_load(core, request):
    name = request.match_info["model"]
    body = await _read_json(request, default={})
    params = body.get("parameters", {}) or {}
    config_override = params.get("config")
    files = {k: v for k, v in params.items() if k.startswith("file:")}
    await core.load_model(name, config_override=config_override,
                          files=files or None)
    return web.Response(status=200)


async def _repo_unload(core, request):
    name = request.match_info["model"]
    body = await _read_json(request, default={})
    params = body.get("parameters", {}) or {}
    core.registry.unload(name, unload_dependents=bool(params.get("unload_dependents")))
    core.retire_name_caches(name)
    log_off_loop(core.log.info, f"successfully unloaded model '{name}'")
    return web.Response(status=200)


# -- trace / logging -------------------------------------------------------


async def _get_trace(core, request):
    model = request.match_info.get("model")
    if model:
        core.registry.get(model)  # unknown model -> 400
        return web.json_response(core.tracer.effective_settings(model))
    return web.json_response(core.trace_settings)


async def _set_trace(core, request):
    from .trace import TRACE_DEFAULTS, validate_trace_update

    model = request.match_info.get("model")
    body = await _read_json(request, default={})
    if model:
        core.registry.get(model)  # unknown model -> 400
        update, cleared = {}, []
        for k, v in body.items():
            if v is None:
                # null in model scope clears the OVERRIDE — the model goes
                # back to inheriting the global value (reference contract)
                if k not in TRACE_DEFAULTS:
                    raise InferError(f"unknown trace setting '{k}'", 400)
                cleared.append(k)
            else:
                update[k] = v if isinstance(v, list) else [str(v)]
        validate_trace_update(update, model_scope=True)
        if update or cleared:
            core.tracer.update_model(model, update, cleared)
        return web.json_response(core.tracer.effective_settings(model))
    update = {}
    for k, v in body.items():
        if v is None:
            # null clears to default (reference update_trace_settings
            # contract); a typo'd clear flows into the shared validator,
            # which 400s unknown keys — same contract as model scope
            update[k] = list(TRACE_DEFAULTS.get(k, []))
        else:
            update[k] = v if isinstance(v, list) else [str(v)]
    validate_trace_update(update)  # 501 for TENSORS, 400 for junk — pre-apply
    if update:  # an empty body is a read, not an update — counters keep phase
        core.trace_settings.update(update)
        core.tracer.settings_updated()
    return web.json_response(core.trace_settings)


async def _build_generate(core, request):
    """Shared generate prologue: (name, version, model, InferRequest)."""
    from .generate import build_generate_request

    name = request.match_info["model"]
    version = request.match_info.get("version", "")
    model = core.registry.get(name, version)
    # read raw first: the byte ledger needs the ACTUAL body size (a
    # chunked upload has no Content-Length to trust), and an oversize
    # read raises HTTPRequestEntityTooLarge for the ingress-cap
    # middleware — it must not be swallowed into the JSON 400 below
    raw = await request.read()
    try:
        body = json.loads(raw)
    except Exception:
        raise InferError("failed to parse generate request JSON", 400)
    req = build_generate_request(model, name, version, body)
    req.protocol = "http"
    req.wire_bytes = len(raw)
    # trace propagation on the generate surface too (join-key parity with
    # /infer): a traced generate_stream record joins client telemetry on
    # the same correlation id / traceparent unary requests use
    req.client_request_id = request.headers.get(_REQUEST_ID_HDR, "")
    req.traceparent = request.headers.get(_TRACEPARENT_HDR, "")
    _stamp_qos(req, request)
    return name, version, model, req


async def _generate(core, request):
    from .generate import response_to_json

    name, version, model, req = await _build_generate(core, request)
    if model.decoupled:
        raise InferError(
            f"model '{name}' is decoupled: use generate_stream", 400)
    response = await core.infer(req)
    return web.Response(
        text=response_to_json(name, version, response),
        content_type="application/json")


async def sse_stream(request, agen, write_frame, on_error, epilogue=None):
    """Shared SSE lifecycle for streaming endpoints (generate_stream, the
    OpenAI frontend).

    The first response is pulled BEFORE committing the 200/SSE headers so
    request/model errors surface as proper HTTP statuses (__anext__, not the
    anext() builtin: requires-python floor is 3.9).  ``write_frame(stream,
    resp)`` serializes each response; ``on_error(e) -> bytes`` formats a
    mid-stream InferError as an in-band frame; ``epilogue(stream)`` runs
    after a clean drain (e.g. OpenAI's [DONE] terminator).

    Every exit closes ``agen`` deterministically: a consumer disconnect
    must reach the core's stream envelope NOW (cancel accounting, the
    stream trace record, decode-slot reclaim) rather than at GC time."""
    try:
        try:
            first = await agen.__anext__()
        except StopAsyncIteration:
            first = None
        stream = web.StreamResponse()
        stream.headers["Content-Type"] = "text/event-stream"
        stream.headers["Cache-Control"] = "no-cache"
        await stream.prepare(request)
        try:
            if first is not None:
                await write_frame(stream, first)
            async for resp in agen:
                await write_frame(stream, resp)
            if epilogue is not None:
                await epilogue(stream)
        except InferError as e:
            # mid-stream failure: headers are committed, deliver in-band
            await stream.write(on_error(e))
        except (ConnectionError, OSError, asyncio.CancelledError):
            # client went away mid-stream — close quietly; re-raising would
            # make the handler wrapper answer a second response on a
            # transport the StreamResponse owns
            return stream
        await stream.write_eof()
        return stream
    finally:
        await agen.aclose()


async def _generate_stream(core, request):
    from .generate import response_to_json

    name, version, model, req = await _build_generate(core, request)

    async def write_frame(stream, resp):
        if not resp.outputs:
            return  # final-flagged empty frame ends decoupled streams
        tr = resp.trace
        if tr is None:
            # precompiled envelope affixes: only the payload is encoded per
            # event, not the whole "data: ...\n\n" frame re-formatted
            await stream.write(sse_frame(response_to_json(name, version, resp)))
            return
        # traced stream: each flushed chunk's serialize+write window lands
        # as a NETWORK_WRITE span, batched at the token stride inside
        # record_write (per-chunk spans would double the record size)
        t0 = time.monotonic_ns()
        await stream.write(sse_frame(response_to_json(name, version, resp)))
        tr.record_write(t0, time.monotonic_ns())

    return await sse_stream(
        request, core.infer_stream(req), write_frame,
        on_error=lambda e: sse_frame(json.dumps({"error": str(e)})))


async def _flight_recorder(core, request):
    from .flight_recorder import parse_snapshot_limit

    model = request.query.get("model") or None
    # shared validator (also used by the gRPC FlightRecorder RPC): junk or
    # negative ?limit= is a client mistake — 400 with a JSON error body,
    # never an unhandled 500
    limit = parse_snapshot_limit(request.query.get("limit", "0"))
    # snapshot + serialize off-loop: at operator-sized rings (10^4-10^5
    # records) this is a multi-MB json.dumps — done inline it would stall
    # every in-flight inference for the duration of a debug poll
    body = await asyncio.get_running_loop().run_in_executor(
        None, lambda: json.dumps(
            core.flight_recorder.snapshot(model=model, limit=limit)))
    return web.Response(text=body, content_type="application/json")


async def _device_stats(core, request):
    """Debug surface for the device/scheduler observability layer: the
    DeviceStatsCollector snapshot (compute/compile/tick/transfer/HBM)
    with the SLO engine's per-model state alongside under ``"slo"``.
    ``?model=`` filters the per-model sections."""
    model = request.query.get("model") or None

    def _snap():
        out = core.device_stats.snapshot(model=model)
        out["slo"] = core.slo.snapshot(model=model)
        # the byte-admission ledger rides the same debug surface: live
        # budget, in-flight bytes per model/tenant, shed counts
        out["memory"] = core.memory.snapshot()
        # prefix/KV cache block stores: hit/miss/evict counters and
        # pinned bytes per model (server/kvcache.py) — the counters the
        # gen_shared_prefix bench reads back
        from . import kvcache

        out["kv_cache"] = kvcache.snapshot()
        return json.dumps(out)

    body = await asyncio.get_running_loop().run_in_executor(None, _snap)
    return web.Response(text=body, content_type="application/json")


async def _costs(core, request):
    """Debug surface for the per-tenant cost-attribution ledger
    (server/costs.py): device-time, FLOPs, generated tokens, and KV
    byte-seconds per (model, tenant).  ``?model=`` filters to one
    model's tenants.  Off-loop like the other debug snapshots."""
    model = request.query.get("model") or None
    body = await asyncio.get_running_loop().run_in_executor(
        None, lambda: json.dumps(core.cost_ledger.snapshot(model=model)))
    return web.Response(text=body, content_type="application/json")


async def _profile(core, request):
    """Debug surface for the always-on host profiler (server/profiler.py).

    Default output is collapsed-stack text — pipe straight into
    ``flamegraph.pl`` or paste into speedscope.  ``?format=json`` returns
    the structured snapshot (loop-lag series, GC pauses, top stacks);
    ``?role=`` filters the folded stacks to one thread role."""
    role = request.query.get("role") or None
    if request.query.get("format") == "json":
        body = await asyncio.get_running_loop().run_in_executor(
            None, lambda: json.dumps(core.profiler.snapshot()))
        return web.Response(text=body, content_type="application/json")
    text = await asyncio.get_running_loop().run_in_executor(
        None, core.profiler.collapsed, role)
    return web.Response(text=text, content_type="text/plain")


async def _incident_status(core, request):
    body = await asyncio.get_running_loop().run_in_executor(
        None, lambda: json.dumps(core.incidents.snapshot()))
    return web.Response(text=body, content_type="application/json")


async def _incident_trigger(core, request):
    """Manual incident bundle: ``POST /v2/debug/incident`` (optional JSON
    body ``{"reason": ...}``).  Synchronous — the response carries the
    bundle path — but off-loop: the capture window must not stall the
    loop it is trying to observe.  202 with ``"rate_limited"`` when the
    manual class is inside its cool-down."""
    payload = await _read_json(request, default={})
    reason = str(payload.get("reason", "manual trigger"))
    path = await asyncio.get_running_loop().run_in_executor(
        None, lambda: core.incidents.trigger(
            "manual", reason=reason, sync=True))
    if path is None:
        return web.json_response(
            {"status": "rate_limited", "bundle": None}, status=202)
    return web.json_response({"status": "written", "bundle": path})


async def _metrics(core, request):
    from .metrics import render_prometheus

    # off-loop like /v2/debug/*: the device-stats rows sum O(window-events)
    # under the collector lock — a scrape must not stall in-flight requests
    text = await asyncio.get_running_loop().run_in_executor(
        None, render_prometheus, core)
    return web.Response(
        text=text,
        content_type="text/plain",
        charset="utf-8",
    )


async def _get_logging(core, request):
    return web.json_response(core.log_settings)


async def _set_logging(core, request):
    body = await _read_json(request, default={})
    core.log_settings.update(body)
    return web.json_response(core.log_settings)


# -- shared memory ---------------------------------------------------------


def _shm_registry(core: InferenceCore, request: web.Request):
    return core.system_shm if "systemsharedmemory" in request.path else core.xla_shm


async def _shm_status(core, request):
    reg = _shm_registry(core, request)
    status = reg.status(request.match_info.get("name"))
    return web.json_response(list(status.values()))


async def _shm_register(core, request):
    reg = _shm_registry(core, request)
    name = request.match_info["name"]
    body = await _read_json(request)
    needed = (("key", "byte_size") if reg is core.system_shm
              else ("raw_handle", "byte_size"))
    missing = [k for k in needed if k not in body]
    if missing:
        raise InferError(
            f"shared memory registration missing field(s): {missing}")
    try:
        if reg is core.system_shm:
            reg.register(
                name, body["key"], int(body.get("offset", 0)),
                int(body["byte_size"]))
        else:
            handle = body["raw_handle"]
            if not isinstance(handle, dict) or "b64" not in handle:
                raise InferError(
                    "raw_handle must be an object with a 'b64' field")
            raw = base64.b64decode(handle["b64"], validate=True)
            reg.register(name, raw, int(body.get("device_id", 0)),
                         int(body["byte_size"]))
    except InferError:
        raise
    except (TypeError, ValueError, binascii.Error) as e:
        raise InferError(f"invalid shared memory registration: {e}")
    return web.Response(status=200)


async def _shm_unregister(core, request):
    reg = _shm_registry(core, request)
    reg.unregister(request.match_info.get("name"))
    return web.Response(status=200)


# -- infer -----------------------------------------------------------------


async def _infer(core, request: web.Request) -> web.Response:
    t_recv = time.monotonic_ns()
    # aiohttp inflates gzip/deflate request bodies transparently.
    raw = await request.read()

    header_len = request.headers.get(_HEADER_LEN)
    if header_len is not None:
        try:
            hlen = int(header_len)
        except ValueError:
            raise InferError(f"invalid {_HEADER_LEN} header: {header_len!r}")
        json_bytes, binary = raw[:hlen], raw[hlen:]
    else:
        json_bytes, binary = raw, b""
    try:
        body = json.loads(json_bytes)
    except Exception:
        raise InferError("failed to parse inference request JSON")

    req = _decode_request(
        request.match_info["model"], request.match_info.get("version", ""), body, binary
    )
    # trace propagation: record the client's correlation id (headers are
    # case-insensitive in aiohttp) so the tracer can join client and server
    req.client_request_id = request.headers.get(_REQUEST_ID_HDR, "")
    req.traceparent = request.headers.get(_TRACEPARENT_HDR, "")
    # span tracing: the read+parse window becomes the DECODE child span
    # (arrival_ns is left at construction time — queue statistics must not
    # absorb a slow client's body upload), and this frontend finalizes the
    # trace so SERIALIZE/NETWORK_WRITE land in it
    req.decode_start_ns = t_recv
    req.decode_end_ns = time.monotonic_ns()
    req.trace_handoff = True
    req.protocol = "http"
    # the memory governor's ledger entry: what this request actually put
    # on the wire (body bytes as received, post-inflate)
    req.wire_bytes = len(raw)
    # deadline propagation: the triton-timeout-us header (the restamped
    # remaining budget) wins over the body's `timeout` parameter
    apply_request_deadline(req, header_us=request.headers.get(_TIMEOUT_HDR))
    _stamp_qos(req, request)
    resp = await core.infer(req)
    trace = resp.trace
    try:
        t_ser0 = time.monotonic_ns() if trace is not None else 0
        default_binary = bool(
            req.parameters.get("binary_data_output", header_len is not None)
        )
        # wire fast path: per-(model, output-set) response templates stamp
        # only id / batch dims / payload sizes; tensor bytes ride zero-copy
        # memoryview segments into one gather (see server/wire.py)
        payload, json_len = encode_http_response(
            resp, {o.name: o for o in req.outputs}, default_binary,
            cache=core.http_wire_templates,
            generation=core.registry.generation(resp.model_name))
        if trace is not None:
            t_ser1 = time.monotonic_ns()
            trace.add_span("SERIALIZE", t_ser0, t_ser1)
        headers = {_HEADER_LEN: str(json_len)}
        if req.client_request_id:
            headers[_REQUEST_ID_HDR] = req.client_request_id
        accept = request.headers.get("Accept-Encoding", "")
        if "gzip" in accept and len(payload) > 1024:
            payload = gzip.compress(payload)
            headers["Content-Encoding"] = "gzip"
        response = web.Response(
            body=payload, headers=headers,
            content_type="application/octet-stream"
        )
        if trace is not None:
            # compression + response assembly up to the transport handoff
            # (aiohttp writes the socket after the handler returns)
            trace.add_span("NETWORK_WRITE", t_ser1, time.monotonic_ns())
    except BaseException as e:
        # a serialize/compress failure happens after the core reported
        # success — the flight record must still land as a failure
        # ("failures are always captured"), not as outcome="ok"
        if trace is not None:
            trace.mark_failed(e)
        raise
    finally:
        if trace is not None:
            await trace.emit_async()
    return response


def _decode_request(
    model_name: str, version: str, body: dict, binary: bytes
) -> InferRequest:
    # structural validation first: every client-controlled field that the
    # loop below indexes must 400 (not 500) when it has the wrong type
    if not isinstance(body, dict):
        raise InferError("inference request body must be a JSON object")
    if not isinstance(body.get("inputs", []), list) \
            or not isinstance(body.get("outputs", []), list):
        raise InferError("'inputs'/'outputs' must be arrays")
    if not isinstance(body.get("parameters", {}) or {}, dict):
        raise InferError("'parameters' must be an object")
    req = InferRequest(
        model_name=model_name,
        model_version=version,
        id=body.get("id", ""),
        parameters=body.get("parameters", {}) or {},
    )
    offset = 0
    for t in body.get("inputs", []):
        try:
            name, datatype = t["name"], t["datatype"]
            shape = tuple(int(s) for s in t["shape"])
        except (TypeError, KeyError, ValueError, AttributeError) as e:
            raise InferError(f"malformed input specification: {e}")
        params = t.get("parameters", {}) or {}
        if not isinstance(params, dict):
            raise InferError(f"input '{name}' parameters must be an object")
        tensor = InputTensor(name=name, datatype=datatype, shape=shape, parameters=params)
        shm_name = params.get("shared_memory_region")
        bin_size = params.get("binary_data_size")
        try:
            if shm_name:
                tensor.shm = ShmRef(
                    region_name=shm_name,
                    byte_size=int(params["shared_memory_byte_size"]),
                    offset=int(params.get("shared_memory_offset", 0)),
                )
            elif bin_size is not None:
                chunk = binary[offset: offset + int(bin_size)]
                if len(chunk) != int(bin_size):
                    raise InferError(
                        f"unexpected end of binary data for input '{name}'"
                    )
                offset += int(bin_size)
                tensor.data = _bytes_to_array(chunk, datatype, shape, name)
            elif "data" in t:
                tensor.data = _json_to_array(t["data"], datatype, shape, name)
            else:
                raise InferError(f"input '{name}' has no data")
        except (TypeError, KeyError, ValueError, AttributeError) as e:
            raise InferError(f"malformed input '{name}': {e}")
        req.inputs.append(tensor)

    for o in body.get("outputs", []) or []:
        try:
            params = o.get("parameters", {}) or {}
            if not isinstance(params, dict):
                raise InferError("output parameters must be an object")
            out = RequestedOutput(
                name=o["name"],
                binary_data=bool(params.get("binary_data", False)),
                class_count=int(params.get("classification", 0)),
                parameters=params,
            )
            shm_name = params.get("shared_memory_region")
            if shm_name:
                out.shm = ShmRef(
                    region_name=shm_name,
                    byte_size=int(params["shared_memory_byte_size"]),
                    offset=int(params.get("shared_memory_offset", 0)),
                )
        except (TypeError, KeyError, ValueError, AttributeError) as e:
            raise InferError(f"malformed output specification: {e}")
        req.outputs.append(out)
    return req


def _bytes_to_array(chunk: bytes, datatype: str, shape, name: str) -> np.ndarray:
    if datatype == "BYTES":
        try:
            flat = deserialize_bytes_tensor(chunk)
        except Exception as e:
            # the codec raises the CLIENT exception class on a truncated
            # length-prefixed stream — uncaught it would 500 a malformed
            # body instead of 400ing it (same fix as the gRPC decoder)
            raise InferError(
                f"malformed BYTES payload for input '{name}': {e}")
        return reshape_input(flat, shape, name)
    dt = triton_to_np_dtype(datatype)
    if dt is None:
        raise InferError(f"unsupported datatype '{datatype}' for input '{name}'")
    # math.prod over python ints (empty shape -> 1): same hot-path fix as
    # the gRPC decoder (benchmarks/HOTPATH_PROFILE.md)
    count = math.prod(shape)
    expected = count * dt.itemsize
    if len(chunk) != expected:
        raise InferError(
            f"unexpected total byte size {len(chunk)} for input '{name}', expecting {expected}"
        )
    return reshape_input(np.frombuffer(chunk, dtype=dt), shape, name)


def _json_to_array(data, datatype: str, shape, name: str = "") -> np.ndarray:
    if datatype == "BYTES":
        def coerce(x):
            if isinstance(x, str):
                return x.encode("utf-8")
            if isinstance(x, (bytes, bytearray, list)):
                return bytes(x)
            # bytes(int) would ALLOCATE that many zero bytes — a client-
            # controlled memory bomb, not a serialization
            raise InferError(
                f"BYTES input '{name}' elements must be strings or byte "
                f"arrays, got {type(x).__name__}")
        flat = np.array(
            [coerce(x) for x in _flatten(data)], dtype=np.object_)
        return reshape_input(flat, shape, name)
    dt = triton_to_np_dtype(datatype)
    if dt is None:
        raise InferError(f"unsupported datatype '{datatype}' for input '{name}'")
    try:
        arr = np.array(data, dtype=dt)
    except (ValueError, TypeError) as e:
        raise InferError(f"invalid data for input '{name}': {e}")
    return reshape_input(arr, shape, name)


def _flatten(x):
    if isinstance(x, list):
        for item in x:
            yield from _flatten(item)
    else:
        yield x


# Response encoding lives in server/wire.py (shared header builder +
# per-(model, output-set) templates + zero-copy readback segments).
