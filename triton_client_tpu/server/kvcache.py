"""Content-addressed prefix/KV cache: the generation memory hierarchy's
reuse layer (ROADMAP item 3).

Chat fleets are dominated by requests sharing a long system prompt, yet
the decode stack recomputed every shared prefix from scratch.  This
module is the missing layer: a per-model **block store** of K/V cache
segments keyed by the *content* of the token prefix that produced them.

Hashing scheme (content addressing)
-----------------------------------
A prompt window is split into fixed ``block_tokens``-sized blocks
(``TRITON_TPU_KV_BLOCK_TOKENS``, default 64).  Block *i*'s digest chains
its parent's digest with its own token bytes::

    d_0 = blake2b(b"" + tokens[0:B])
    d_i = blake2b(d_{i-1} + tokens[iB:(i+1)B])

so a block key commits to the ENTIRE prefix, not just its own tokens —
two prompts sharing bytes mid-window but diverging earlier can never
collide.  K/V values at position ``p`` of a causal transformer depend
only on tokens ``<= p`` (and the weights), so content addressing over
the token prefix is sound: any sequence whose window starts with the
same bytes reads bit-identical K/V.  The chain is capped at the largest
multiple of ``block_tokens`` STRICTLY below the window length — the
final position's logits always come from a real dispatch, never from
the store, which is what keeps hit-vs-cold token streams bit-identical.

Residency contract (MemoryGovernor ledger)
------------------------------------------
The store's bytes are a *named reservation* in the governor's ledger:
every committed block opens a ``cache_pin`` (the cache-flavored twin of
the per-slot ``kv_pin``), visible as ``nv_mem_cache_pinned_bytes`` and
in the ``/v2/debug/device_stats`` memory snapshot.  Eviction closes the
pin and charges the *pinning* tenant the block's byte-seconds through
the CostLedger — exactly the governor integrator's return, so the
ledger/governor reconciliation holds by construction.  A sequence that
HITS a block is never charged for the block's residency (no double
charge): it pays only its own slot pin, as before.

Refcount / eviction rules
-------------------------
A matched block is refcounted from match until the hitting sequence's
tail prefill has been dispatched (the slab copy owns the bytes from
then on).  Eviction considers only ``refs == 0`` blocks, picks the
LRU/largest hybrid victim (oldest ``last_use`` first, larger block on
ties), and then drops any block whose parent left the store — a broken
chain can never be matched again, so keeping its tail would strand
bytes.  Device faults (PR 19) call :meth:`KVBlockCache.revalidate`,
which drops blocks whose device buffers were deleted — committed blocks
are independent buffers (extracted by ``dynamic_slice``), so a donated
slab's death normally leaves the store intact.

Metric families (declared once in ``metrics.collect_families``)::

    nv_cache_hit_total          counter  {model}
    nv_cache_miss_total         counter  {model}
    nv_cache_evict_total        counter  {model}
    nv_cache_hit_tokens_total   counter  {model}
    nv_cache_pinned_bytes       gauge    {model}

Configuration: ``TRITON_TPU_KV_CACHE_BYTES`` (global per-model budget,
0/unset = cache off) with per-model ``TRITON_TPU_KV_CACHE_BYTES_<MODEL>``
override (``--kv-cache-bytes MODEL=N`` on the server CLI), and
``TRITON_TPU_KV_BLOCK_TOKENS`` for the block granularity.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["KVBlockCache", "for_model", "get", "drop", "drop_all",
           "metric_rows", "snapshot", "resolve_budget_bytes",
           "resolve_block_tokens", "cache_env_key", "DEFAULT_BLOCK_TOKENS"]

#: Default prefix-block granularity (tokens per content-addressed block).
DEFAULT_BLOCK_TOKENS = 64

_ROOT = b""


def cache_env_key(model_name: str) -> str:
    """Per-model budget override variable (same sanitization convention
    as ``TRITON_TPU_QUANT_<MODEL>``)."""
    return "TRITON_TPU_KV_CACHE_BYTES_" + "".join(
        c if c.isalnum() else "_" for c in model_name.upper())


def resolve_budget_bytes(model_name: str) -> int:
    """The model's prefix-cache byte budget: per-model env override, then
    the global ``TRITON_TPU_KV_CACHE_BYTES``; 0/unset disables the cache.
    Malformed values fail loudly with the variable that was set."""
    var = "TRITON_TPU_KV_CACHE_BYTES"
    val = os.environ.get(var, "")
    key = cache_env_key(model_name)
    per_model = os.environ.get(key)
    if per_model is not None:
        var, val = key, per_model
    val = val.strip()
    if not val:
        return 0
    try:
        n = int(val)
    except ValueError:
        raise ValueError(f"{var}={val!r}: expected an integer byte budget")
    return max(0, n)


def resolve_block_tokens() -> int:
    val = os.environ.get("TRITON_TPU_KV_BLOCK_TOKENS", "").strip()
    if not val:
        return DEFAULT_BLOCK_TOKENS
    try:
        n = int(val)
    except ValueError:
        raise ValueError(
            f"TRITON_TPU_KV_BLOCK_TOKENS={val!r}: expected an integer")
    if n <= 0:
        raise ValueError(
            f"TRITON_TPU_KV_BLOCK_TOKENS={n}: must be positive")
    return n


def _leaf_nbytes(c) -> int:
    if isinstance(c, dict):
        return sum(_leaf_nbytes(v) for v in c.values())
    return int(c.size) * int(c.dtype.itemsize)


def _leaf_deleted(c) -> bool:
    """True when a stored device array's buffer is gone (a donated
    dispatch died holding it, or a chaos drill deleted it) — metadata
    check only, never a device sync."""
    if isinstance(c, dict):
        return any(_leaf_deleted(v) for v in c.values())
    try:
        return bool(c.is_deleted())
    except Exception:  # noqa: BLE001 — non-jax leaf (tests): assume live
        return False


class _Block:
    __slots__ = ("digest", "parent", "k", "v", "tokens", "nbytes",
                 "refs", "last_use", "pin", "tenant")

    def __init__(self, digest, parent, k, v, tokens, nbytes, tenant):
        self.digest = digest
        self.parent = parent
        self.k = k
        self.v = v
        self.tokens = tokens
        self.nbytes = nbytes
        self.refs = 0
        self.last_use = 0
        self.pin = None
        self.tenant = tenant


class KVBlockCache:
    """One model's content-addressed K/V block store.

    Thread-safe under one short lock; the decode worker matches/commits,
    admission threads peek, the metrics renderer snapshots.  Device
    arrays are only ever *referenced* here — all slicing/insertion runs
    in the decode model's jitted helpers."""

    def __init__(self, model: str, budget_bytes: int,
                 block_tokens: Optional[int] = None,
                 governor=None, ledger=None) -> None:
        self.model = model
        self.budget_bytes = int(budget_bytes)
        self.block_tokens = int(block_tokens or resolve_block_tokens())
        self.governor = governor
        self.ledger = ledger
        self._lock = threading.Lock()
        self._blocks: Dict[bytes, _Block] = {}
        self._clock = 0
        # counter surface (nv_cache_*): monotonic over the cache lifetime
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.hit_tokens_total = 0
        self.pinned_bytes = 0

    # -- content addressing -------------------------------------------------
    def chain_digests(self, tokens) -> List[bytes]:
        """Chained digests for every COMPLETE block strictly below the
        window's final position (see module docstring).  ``tokens`` is a
        host int array/sequence — hashing is pure host work."""
        import numpy as np

        n = max(0, len(tokens) - 1) // self.block_tokens
        if not n:
            return []
        arr = np.ascontiguousarray(
            tokens[:n * self.block_tokens], dtype=np.int32)
        out: List[bytes] = []
        parent = _ROOT
        bt = self.block_tokens
        for i in range(n):
            h = hashlib.blake2b(parent, digest_size=16)
            h.update(arr[i * bt:(i + 1) * bt].tobytes())
            parent = h.digest()
            out.append(parent)
        return out

    def has(self, digest: bytes) -> bool:
        """Commit-side presence probe: lets the decode worker skip the
        extraction dispatch for blocks already in the store."""
        with self._lock:
            return digest in self._blocks

    def peek(self, tokens) -> int:
        """Longest cached prefix (tokens) WITHOUT acquiring references or
        touching hit/miss counters — the admission-projection probe."""
        digs = self.chain_digests(tokens)
        n = 0
        with self._lock:
            for d in digs:
                if d not in self._blocks:
                    break
                n += 1
        return n * self.block_tokens

    def match(self, tokens) -> Tuple[int, List[_Block], Optional[str]]:
        """Longest cached block chain for this window: returns
        ``(hit_tokens, blocks, prefix_hash)`` with every matched block's
        refcount raised (pair with :meth:`release` once the hitting
        sequence's inserts are dispatched).  One hit or one miss is
        counted per match, hit tokens accumulate."""
        digs = self.chain_digests(tokens)
        got: List[_Block] = []
        with self._lock:
            self._clock += 1
            for d in digs:
                blk = self._blocks.get(d)
                if blk is None:
                    break
                blk.refs += 1
                blk.last_use = self._clock
                got.append(blk)
            if got:
                self.hits += 1
                self.hit_tokens_total += len(got) * self.block_tokens
            else:
                self.misses += 1
        phash = got[-1].digest.hex() if got else None
        return len(got) * self.block_tokens, got, phash

    def release(self, blocks: List[_Block]) -> None:
        """Drop match references; an unreferenced block whose chain broke
        while it was held (parent evicted) is unreachable forever and is
        dropped here rather than stranded."""
        with self._lock:
            for blk in blocks:
                blk.refs = max(0, blk.refs - 1)
            self._drop_orphans_locked()

    # -- commit / evict -----------------------------------------------------
    def put(self, digest: bytes, parent: bytes, k, v,
            tenant: str = "") -> bool:
        """Commit one extracted block under ``digest``.  Returns False
        when the block is already present, exceeds the whole budget, or
        every evictable (unreferenced) byte is exhausted — commit is
        best-effort, correctness never depends on it."""
        nbytes = _leaf_nbytes(k) + _leaf_nbytes(v)
        with self._lock:
            if digest in self._blocks:
                return False
            if nbytes > self.budget_bytes:
                return False
            self._evict_to_locked(self.budget_bytes - nbytes)
            if self.pinned_bytes + nbytes > self.budget_bytes:
                return False
            blk = _Block(digest, parent, k, v, self.block_tokens,
                         nbytes, tenant)
            self._clock += 1
            blk.last_use = self._clock
            if self.governor is not None:
                # the governor lock is a leaf (same ordering contract as
                # _kv_unpin_charge): the block's residency becomes a
                # named reservation in the memory ledger
                blk.pin = self.governor.cache_pin(
                    self.model, nbytes, tenant)
            self._blocks[digest] = blk
            self.pinned_bytes += nbytes
        return True

    def _evict_block_locked(self, blk: _Block) -> None:
        self._blocks.pop(blk.digest, None)
        self.pinned_bytes = max(0, self.pinned_bytes - blk.nbytes)
        self.evictions += 1
        # drop the device refs eagerly — the arrays die now, not at the
        # next gc cycle of a dict the store no longer reaches
        blk.k = blk.v = None
        pin, blk.pin = blk.pin, None
        if pin is not None and self.governor is not None:
            tenant, byte_s = self.governor.cache_unpin(pin)
            ledger = self.ledger
            if ledger is not None and ledger.enabled and byte_s > 0:
                # residency is charged to the PINNING tenant at eviction
                # time — exactly the integrator's return, so the
                # CostLedger reconciles with the governor by construction
                ledger.charge(self.model, tenant, kv_byte_seconds=byte_s)

    def _drop_orphans_locked(self) -> None:
        changed = True
        while changed:
            changed = False
            for blk in list(self._blocks.values()):
                if (blk.refs <= 0 and blk.parent != _ROOT
                        and blk.parent not in self._blocks):
                    self._evict_block_locked(blk)
                    changed = True

    def _evict_to_locked(self, target_bytes: int) -> None:
        """LRU/largest-hybrid eviction of unreferenced chains until the
        store holds at most ``target_bytes``."""
        while self.pinned_bytes > target_bytes:
            candidates = [b for b in self._blocks.values() if b.refs <= 0]
            if not candidates:
                return
            victim = min(candidates,
                         key=lambda b: (b.last_use, -b.nbytes))
            self._evict_block_locked(victim)
            self._drop_orphans_locked()

    def revalidate(self) -> int:
        """Post-fault sweep (donated-bucket rebuild, device_error chaos):
        drop every block whose device buffers are gone.  Returns the
        number of blocks dropped."""
        dropped = 0
        with self._lock:
            for blk in list(self._blocks.values()):
                if _leaf_deleted(blk.k) or _leaf_deleted(blk.v):
                    self._evict_block_locked(blk)
                    dropped += 1
            self._drop_orphans_locked()
        return dropped

    def clear(self) -> None:
        """Evict everything (model shutdown): every pin closes, so the
        governor's cache reservation returns to zero."""
        with self._lock:
            for blk in list(self._blocks.values()):
                self._evict_block_locked(blk)

    # -- export -------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "block_tokens": self.block_tokens,
                "blocks": len(self._blocks),
                "pinned_bytes": self.pinned_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_tokens": self.hit_tokens_total,
            }


# ---------------------------------------------------------------------------
# Registry: one store per model name.  Decode models create/lookup their
# store lazily (budget 0 -> no entry, cache off); the metrics renderer and
# the device_stats debug surface aggregate over whatever is live.
# ---------------------------------------------------------------------------

_registry_lock = threading.Lock()
_registry: Dict[str, KVBlockCache] = {}


def for_model(model: str, governor=None, ledger=None,
              budget_bytes: Optional[int] = None,
              block_tokens: Optional[int] = None) -> Optional[KVBlockCache]:
    """The model's block store, created on first call (``None`` when the
    resolved budget is 0 — cache disabled).  Later calls refresh the
    governor/ledger wiring (attach order is not guaranteed)."""
    if budget_bytes is None:
        budget_bytes = resolve_budget_bytes(model)
    if budget_bytes <= 0:
        return None
    with _registry_lock:
        cache = _registry.get(model)
        if cache is None:
            cache = KVBlockCache(model, budget_bytes,
                                 block_tokens=block_tokens,
                                 governor=governor, ledger=ledger)
            _registry[model] = cache
        else:
            if governor is not None:
                cache.governor = governor
            if ledger is not None:
                cache.ledger = ledger
        return cache


def get(model: str) -> Optional[KVBlockCache]:
    with _registry_lock:
        return _registry.get(model)


def drop(model: str) -> None:
    """Remove a model's store, closing every governor pin (model unload/
    shutdown — the reservation must not outlive the model)."""
    with _registry_lock:
        cache = _registry.pop(model, None)
    if cache is not None:
        cache.clear()


def drop_all() -> None:
    with _registry_lock:
        caches = list(_registry.values())
        _registry.clear()
    for cache in caches:
        cache.clear()


def metric_rows() -> Dict[str, List[Tuple[Dict[str, str], Any]]]:
    """The ``nv_cache_*`` sample rows keyed by short family name — one
    source for the Prometheus renderer and the JSON snapshot (same
    contract as ``MemoryGovernor.metric_rows``)."""
    with _registry_lock:
        caches = sorted(_registry.items())
    rows: Dict[str, List[Tuple[Dict[str, str], Any]]] = {
        "hit": [], "miss": [], "evict": [], "hit_tokens": [],
        "pinned_bytes": [],
    }
    for model, cache in caches:
        s = cache.stats()
        rows["hit"].append(({"model": model}, s["hits"]))
        rows["miss"].append(({"model": model}, s["misses"]))
        rows["evict"].append(({"model": model}, s["evictions"]))
        rows["hit_tokens"].append(({"model": model}, s["hit_tokens"]))
        rows["pinned_bytes"].append(({"model": model}, s["pinned_bytes"]))
    return rows


def snapshot() -> Dict[str, Any]:
    """Debug-surface JSON (rides ``/v2/debug/device_stats`` under
    ``"kv_cache"``)."""
    with _registry_lock:
        caches = sorted(_registry.items())
    return {model: cache.stats() for model, cache in caches}
