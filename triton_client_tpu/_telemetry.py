"""Client-side telemetry: process-wide metrics registry + trace correlation.

The reference client records nothing (SURVEY.md §5: "No Prometheus-style
client metrics"); every observability surface lives server-side.  This module
is the client half of the observability subsystem:

* ``LatencyHistogram`` — log-bucketed latency histogram.  Buckets grow
  geometrically (5% per bucket) from 1 µs to ~100 s, so p50/p90/p99 are
  recoverable to <2.5% relative error without retaining raw samples, at a
  fixed ~3 KB per histogram.  ``observe`` is one lock + two integer adds —
  cheap enough for the perf_analyzer hot loop.
* ``ClientTelemetry`` — a process-wide registry of per-(model, protocol,
  method) request series (success/failure counters, request/response byte
  counters, a latency histogram) plus shared-memory register/transfer
  counters.  All four client entrypoints (``http``/``http.aio``/``grpc``/
  ``grpc.aio``) record into the singleton returned by :func:`telemetry`.
* A pluggable on-request hook (:meth:`ClientTelemetry.set_request_hook`) —
  each completed request invokes it with the event record, so applications
  can bridge into their own metrics pipeline without patching the clients.
* :meth:`ClientTelemetry.render_prometheus` — the client metrics in the
  Prometheus text exposition format (Triton-convention ``nv_*`` names with
  a ``nv_client_`` prefix) for client-side scraping, and
  :meth:`ClientTelemetry.snapshot` for JSON export (perf_analyzer
  ``--export-metrics``, ``bench.py``).
* :func:`new_trace_context` — W3C ``traceparent`` + ``triton-request-id``
  header pairs the clients stamp on every inference; the server's
  ``RequestTracer`` records the propagated id in its trace JSON and echoes
  it back, so client and server traces join on one id (see
  ``server/trace.py``).
"""

from __future__ import annotations

import contextvars
import json
import math
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "AppendFile",
    "ClientTelemetry",
    "ENDPOINT_STATE_CODES",
    "Journey",
    "LatencyHistogram",
    "OTLP_ENDPOINT_ENV",
    "begin_journey",
    "current_journey",
    "end_journey",
    "escape_label",
    "merge_trace_headers",
    "new_trace_context",
    "telemetry",
    "REQUEST_ID_HEADER",
    "TRACEPARENT_HEADER",
]


class AppendFile:
    """Cached append handle, reopened when the configured path changes —
    shared by the client trace recorder, the server log, and the request
    tracer so the open-on-change/close-on-shutdown/failure-drop state
    machine exists once.  A failing write must never raise (the request
    that happened to log/trace must not fail) and must CLOSE the handle
    before dropping it (dropping without close leaks one fd per attempt
    against a full disk until accept() dies with EMFILE).

    Lives here rather than in ``server/log.py`` (which re-exports it)
    because this module is importable with zero optional deps — the server
    package pulls in the whole serving stack."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._file = None
        self._path = None

    def append(self, path: str, data: str) -> None:
        with self._lock:
            try:
                if self._file is None or self._path != path:
                    self._close_locked()
                    self._file = open(path, "a")
                    self._path = path
                self._file.write(data)
                self._file.flush()
            except OSError:
                self._close_locked()

    def _close_locked(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
            self._path = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()

#: Numeric encoding of ``nv_client_endpoint_state`` (Prometheus gauges are
#: numbers; the JSON snapshot carries the string): 0 = closed (healthy),
#: 1 = open (evicted), 2 = half_open (probing recovery).
ENDPOINT_STATE_CODES = {"closed": 0, "open": 1, "half_open": 2}

#: Header / gRPC-metadata key carrying the client-generated request id the
#: server echoes back and records in trace JSON (lowercase: gRPC metadata
#: keys must be lowercase, HTTP headers are case-insensitive).
REQUEST_ID_HEADER = "triton-request-id"
#: W3C Trace Context header stamped alongside (00-<trace16B>-<span8B>-01).
TRACEPARENT_HEADER = "traceparent"


# header/metadata-safe id: visible ASCII without DEL — the wire `id` field
# accepts any string, but HTTP header values and gRPC non-bin metadata
# values do not; an unsafe user id must not turn into a client-side send
# failure, so it stays body-only and a minted id carries the correlation
_HEADER_SAFE = re.compile(r"[\x20-\x7e]+\Z")

#: Env var arming the client-side OTLP exporter: when set to a collector
#: endpoint (``host:4318`` or a full URL), every client trace record also
#: exports as OTLP/HTTP ResourceSpans (see ``otlp.py``).
OTLP_ENDPOINT_ENV = "TRITON_TPU_OTLP_ENDPOINT"


class Journey:
    """One retry-scoped client journey: a single 16-byte trace id spanning
    every attempt (retries, hedged backups, endpoint switches) of one
    logical request.  The resilience layer opens a journey around its
    attempt loop; :func:`new_trace_context` then mints per-attempt
    traceparents that share the journey's trace id with a FRESH span id per
    attempt — so each replica's server trace parents under the attempt
    that actually reached it, while the whole fan-out joins on one id."""

    __slots__ = ("trace_id", "request_id", "attempt", "traceparent")

    def __init__(self, trace_id: str, request_id: str) -> None:
        self.trace_id = trace_id
        self.request_id = request_id
        self.attempt = 0  # stamped by the owning retry loop, 1-based
        self.traceparent = ""  # the latest attempt's on-wire traceparent


_JOURNEY: contextvars.ContextVar[Optional[Journey]] = \
    contextvars.ContextVar("tc_tpu_journey", default=None)


def begin_journey(request_id: str = ""):
    """Open a journey scope for the current context.  Returns an opaque
    scope to pass to :func:`end_journey`, or None when a journey is
    already active — nested retry layers (a cluster retry loop driving a
    single-endpoint client's deadline loop) must not fork the trace id,
    so only the outermost owner numbers attempts and closes the scope."""
    if _JOURNEY.get() is not None:
        return None
    if not request_id or not _HEADER_SAFE.match(request_id):
        request_id = os.urandom(8).hex()
    journey = Journey(os.urandom(16).hex(), request_id)
    return journey, _JOURNEY.set(journey)


def end_journey(scope) -> None:
    """Close a scope returned by :func:`begin_journey` (owner only)."""
    _JOURNEY.reset(scope[1])


def current_journey() -> Optional[Journey]:
    """The active journey of this context, or None."""
    return _JOURNEY.get()


def new_trace_context(request_id: str = "") -> Dict[str, str]:
    """Fresh propagation headers for one inference.  ``request_id`` (the wire
    ``id`` field, when the caller set one) doubles as the correlation id so a
    user-chosen id is greppable across client and server; otherwise — or when
    the id is not header-safe — a random 16-hex id is minted.  Inside a
    journey scope the trace id and correlation id are the journey's (stable
    across attempts) and only the span id is fresh per attempt."""
    journey = _JOURNEY.get()
    if journey is not None:
        traceparent = f"00-{journey.trace_id}-{os.urandom(8).hex()}-01"
        journey.traceparent = traceparent
        if not request_id or not _HEADER_SAFE.match(request_id):
            request_id = journey.request_id
        return {REQUEST_ID_HEADER: request_id,
                TRACEPARENT_HEADER: traceparent}
    if not request_id or not _HEADER_SAFE.match(request_id):
        request_id = os.urandom(8).hex()
    return {
        REQUEST_ID_HEADER: request_id,
        TRACEPARENT_HEADER:
            f"00-{os.urandom(16).hex()}-{os.urandom(8).hex()}-01",
    }


def merge_trace_headers(
    headers: Optional[Dict[str, str]], request_id: str = ""
) -> Tuple[Dict[str, str], str]:
    """Trace headers to add to one HTTP inference: a fresh context minus any
    key the caller already supplies (user headers win).  Returns
    (headers_to_add, correlation id actually in flight).  The gRPC clients
    use the metadata-tuple sibling ``grpc._client._with_trace_metadata``."""
    ctx = new_trace_context(request_id)
    user = ({k.lower(): v for k, v in headers.items()} if headers else {})
    extra = {k: v for k, v in ctx.items() if k not in user}
    return extra, user.get(REQUEST_ID_HEADER, ctx[REQUEST_ID_HEADER])


def traceparent_on_wire(user_headers: Optional[Dict[str, str]],
                        minted_headers: Dict[str, str]) -> str:
    """The traceparent actually sent on one HTTP inference: a user-supplied
    header wins over the minted one (the merge_trace_headers contract), so
    client trace records keep external correlation ids."""
    if user_headers:
        for k, v in user_headers.items():
            if k.lower() == TRACEPARENT_HEADER:
                return v
    return minted_headers.get(TRACEPARENT_HEADER, "")


def traceparent_from_metadata(metadata) -> str:
    """The traceparent in a merged gRPC metadata tuple (user-supplied or
    minted — _with_trace_metadata already applied the precedence)."""
    return next((v for k, v in metadata
                 if k.lower() == TRACEPARENT_HEADER), "")


def escape_label(value: str) -> str:
    """Escape a label value per the Prometheus text exposition format
    (backslash, double-quote, newline).  Shared with the server renderer —
    model names are user-controlled on both sides."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class LatencyHistogram:
    """Log-bucketed latency histogram (seconds in, quantiles out).

    Bucket ``i >= 1`` covers ``[MIN * G**(i-1), MIN * G**i)`` with
    ``MIN = 1 µs`` and growth ``G = 1.05``; bucket 0 is the underflow bucket
    and the last bucket absorbs overflow.  Quantiles report the geometric
    midpoint of the selected bucket, bounding relative error by
    ``sqrt(G) - 1`` (~2.5%) inside the covered range.  The exact sum is kept
    alongside, so ``mean`` is not quantized.
    """

    MIN_S = 1e-6
    GROWTH = 1.05
    # covers MIN_S .. ~130 s: ceil(log(1.3e8)/log(1.05)) interior buckets
    NUM_BUCKETS = 2 + int(math.ceil(math.log(1.3e8) / math.log(1.05)))

    __slots__ = ("_counts", "_count", "_sum_s", "_lock", "_log_growth")

    def __init__(self) -> None:
        self._counts = [0] * self.NUM_BUCKETS
        self._count = 0
        self._sum_s = 0.0
        self._lock = threading.Lock()
        self._log_growth = math.log(self.GROWTH)

    def _index(self, seconds: float) -> int:
        if seconds < self.MIN_S:
            return 0
        i = 1 + int(math.log(seconds / self.MIN_S) / self._log_growth)
        return i if i < self.NUM_BUCKETS else self.NUM_BUCKETS - 1

    def observe(self, seconds: float) -> None:
        i = self._index(seconds)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum_s += seconds

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum_s(self) -> float:
        return self._sum_s

    def mean(self) -> float:
        with self._lock:
            return self._sum_s / self._count if self._count else float("nan")

    def _bucket_value(self, i: int) -> float:
        if i == 0:
            return self.MIN_S / 2.0
        # geometric midpoint of [MIN*G**(i-1), MIN*G**i)
        return self.MIN_S * self.GROWTH ** (i - 0.5)

    def quantile(self, q: float) -> float:
        """The ``q``-quantile (0..1) in seconds; NaN when empty."""
        with self._lock:
            total = self._count
            if not total:
                return float("nan")
            # nearest-rank on the cumulative counts
            rank = max(1, math.ceil(q * total))
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= rank:
                    return self._bucket_value(i)
        return self._bucket_value(self.NUM_BUCKETS - 1)

    def percentile(self, p: float) -> float:
        return self.quantile(p / 100.0)

    def merge(self, other: "LatencyHistogram") -> None:
        with other._lock:
            counts = list(other._counts)
            count, sum_s = other._count, other._sum_s
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._count += count
            self._sum_s += sum_s

    def snapshot_us(self) -> Dict[str, Any]:
        """count/avg/p50/p90/p99 in microseconds.  None (JSON null, not
        NaN — snapshots must stay strict JSON) when empty."""
        if not self.count:
            return {"count": 0, "avg_us": None, "p50_us": None,
                    "p90_us": None, "p99_us": None}
        return {
            "count": self.count,
            "avg_us": self.mean() * 1e6,
            "p50_us": self.quantile(0.50) * 1e6,
            "p90_us": self.quantile(0.90) * 1e6,
            "p99_us": self.quantile(0.99) * 1e6,
        }


class _RequestSeries:
    __slots__ = ("success", "failure", "retries", "request_bytes",
                 "response_bytes", "latency")

    def __init__(self) -> None:
        self.success = 0
        self.failure = 0
        self.retries = 0
        self.request_bytes = 0
        self.response_bytes = 0
        self.latency = LatencyHistogram()


class ClientTelemetry:
    """Process-wide client metrics registry.

    Series are keyed (model, protocol, method): ``protocol`` is one of
    ``http``/``http_aio``/``grpc``/``grpc_aio`` and ``method`` one of
    ``infer``/``async_infer``/``stream_infer``.  For ``stream_infer`` the
    success counter counts *submitted* stream requests (completion arrives
    on the stream callback, decoupled from the send) and no latency is
    observed.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._requests: Dict[Tuple[str, str, str], _RequestSeries] = {}
        # (protocol, kind) -> [registrations, bytes]; kind: system | cuda
        self._shm_register: Dict[Tuple[str, str], List[int]] = {}
        # (kind, direction) -> [transfers, bytes]; direction: write | read
        self._shm_transfer: Dict[Tuple[str, str], List[int]] = {}
        # cluster layer: per-endpoint routing counters.  Keyed by endpoint
        # URL (not by model) — the question these answer is "where did the
        # traffic go", which the per-(model, protocol, method) series above
        # cannot: a ClusterClient fans one model across N endpoints.
        # (endpoint, outcome) -> count; outcome: success | failure
        self._endpoint_requests: Dict[Tuple[str, str], int] = {}
        # endpoint -> breaker/health state name (closed | open | half_open)
        self._endpoint_state: Dict[str, str] = {}
        # (model, protocol) -> [hedges issued, hedges won by the hedge]
        self._hedges: Dict[Tuple[str, str], List[int]] = {}
        self._hook: Optional[Callable[[Dict[str, Any]], None]] = None
        # client-side span tracing: when a path is set, every instrumented
        # inference appends one JSON line (request id + SERIALIZE/NETWORK/
        # DESERIALIZE spans) — the client half of the trace join.  The
        # handle is cached via AppendFile (open-per-record syscalls would
        # serialize concurrent client threads during a perf sweep — the
        # very workload client tracing exists to measure).
        self._trace_path: Optional[str] = None
        self._trace_lock = threading.Lock()
        self._trace_out = AppendFile()
        # OTLP/HTTP export of the same records (otlp.OtlpExporter); armed
        # by enable_otlp() or the TRITON_TPU_OTLP_ENDPOINT env var.  The
        # exporter thread is lazy — nothing spawns until a record exports.
        self._otlp = None
        endpoint = os.environ.get(OTLP_ENDPOINT_ENV, "").strip()
        if endpoint:
            try:
                self.enable_otlp(endpoint)
            except ValueError:
                pass  # a malformed env endpoint must not break imports

    # -- recording ---------------------------------------------------------
    def _series(self, key: Tuple[str, str, str]) -> _RequestSeries:
        s = self._requests.get(key)
        if s is None:
            with self._lock:
                s = self._requests.setdefault(key, _RequestSeries())
        return s

    def record_request(
        self,
        model: str,
        protocol: str,
        method: str,
        latency_s: Optional[float],
        ok: bool,
        request_bytes: int = 0,
        response_bytes: int = 0,
        request_id: str = "",
    ) -> None:
        """Record one completed (or failed) request.  ``latency_s=None``
        counts without a histogram observation (streaming submits)."""
        s = self._series((model, protocol, method))
        # counters + histogram under ONE lock round-trip per request
        with s.latency._lock:
            self._apply_outcome_locked(s, ok, latency_s, request_bytes,
                                       response_bytes)
        self._fire_hook(model, protocol, method, ok, latency_s,
                        request_bytes, response_bytes, request_id,
                        time.time())

    @staticmethod
    def _apply_outcome_locked(s, ok: bool, latency_s: Optional[float],
                              request_bytes: int,
                              response_bytes: int) -> None:
        """Move one request's counters + histogram observation.  Caller
        holds ``s.latency._lock`` — the ONE recording contract shared by
        the per-call and batch paths so they cannot drift."""
        h = s.latency
        if ok:
            s.success += 1
        else:
            s.failure += 1
        s.request_bytes += request_bytes
        s.response_bytes += response_bytes
        if latency_s is not None:
            h._counts[h._index(latency_s)] += 1
            h._count += 1
            h._sum_s += latency_s

    def _fire_hook(self, model, protocol, method, ok, latency_s,
                   request_bytes, response_bytes, request_id, ts) -> None:
        hook = self._hook
        if hook is None:
            return
        try:
            hook({
                "model": model, "protocol": protocol, "method": method,
                "ok": ok, "latency_s": latency_s,
                "request_bytes": request_bytes,
                "response_bytes": response_bytes,
                "request_id": request_id,
                "ts": ts,
            })
        except Exception:
            pass  # a broken hook must never fail the request path

    def record_request_batch(self, model: str, protocol: str, method: str,
                             outcomes) -> None:
        """Record one batch-submit flight's outcomes under ONE lock
        round-trip — the ``infer_many`` amortization.  ``outcomes`` is an
        iterable of ``(ok, latency_s or None, request_bytes,
        response_bytes, request_id)``; every counter still moves once per
        request (via the same locked update as ``record_request``), so
        the per-request metrics contract is unchanged."""
        outcomes = list(outcomes)
        if not outcomes:
            return
        s = self._series((model, protocol, method))
        with s.latency._lock:
            for ok, latency_s, request_bytes, response_bytes, _rid \
                    in outcomes:
                self._apply_outcome_locked(s, ok, latency_s,
                                           request_bytes, response_bytes)
        now = time.time()
        for ok, latency_s, request_bytes, response_bytes, rid in outcomes:
            self._fire_hook(model, protocol, method, ok, latency_s,
                            request_bytes, response_bytes, rid, now)

    def record_retry(self, model: str, protocol: str, method: str) -> None:
        """Count one retried attempt (the resilience layer calls this per
        backoff, BEFORE the retry runs — a retry that then succeeds still
        counted, which is the point: nv_client_retries_total measures how
        hard the client is working, not how often it loses)."""
        s = self._series((model, protocol, method))
        with s.latency._lock:
            s.retries += 1

    # -- cluster routing ---------------------------------------------------
    def record_endpoint_request(self, endpoint: str, ok: bool) -> None:
        """Count one request routed to ``endpoint`` by the cluster layer
        (``nv_client_endpoint_requests_total``) — per-endpoint traffic
        distribution is what proves rebalancing after a failover."""
        key = (endpoint, "success" if ok else "failure")
        with self._lock:
            self._endpoint_requests[key] = \
                self._endpoint_requests.get(key, 0) + 1

    def set_endpoint_state(self, endpoint: str, state: str) -> None:
        """Record an endpoint's breaker/health state (``closed`` /
        ``open`` / ``half_open``) — rendered numerically as
        ``nv_client_endpoint_state`` (0/1/2).  A closed→open transition
        during an active journey also drops a ``BREAKER_OPEN`` event on
        the journey's trace — the moment a replica fell out of rotation
        is exactly what explains the endpoint switch that follows."""
        with self._lock:
            self._endpoint_state[endpoint] = state
        if state == "open":
            self.record_journey_event("BREAKER_OPEN", endpoint=endpoint,
                                      ok=False)

    def record_hedge(self, model: str, protocol: str,
                     won: bool = False) -> None:
        """Count one hedged request (``won=False`` at issue time); call
        again with ``won=True`` when the hedge beat the primary —
        ``nv_client_hedges_total`` / ``nv_client_hedge_wins_total``."""
        with self._lock:
            c = self._hedges.setdefault((model, protocol), [0, 0])
            if won:
                c[1] += 1
            else:
                c[0] += 1

    def record_shm_register(self, protocol: str, kind: str,
                            byte_size: int) -> None:
        with self._lock:
            c = self._shm_register.setdefault((protocol, kind), [0, 0])
            c[0] += 1
            c[1] += int(byte_size)

    def record_shm_transfer(self, kind: str, direction: str,
                            nbytes: int) -> None:
        with self._lock:
            c = self._shm_transfer.setdefault((kind, direction), [0, 0])
            c[0] += 1
            c[1] += int(nbytes)

    # -- client-side span tracing ------------------------------------------
    def enable_tracing(self, path: str) -> None:
        """Start recording per-request client span sets to ``path`` (JSON
        Lines, one object per completed inference).  Each record carries the
        ``triton-request-id`` this process stamped on the wire, so it joins
        with the server's trace file on that key
        (``triton_client_tpu.tools.trace_summary --client``)."""
        with self._trace_lock:
            self._trace_path = path

    def disable_tracing(self) -> None:
        with self._trace_lock:
            self._trace_path = None
            self._trace_out.close()

    def enable_otlp(self, endpoint: str):
        """Arm OTLP/HTTP export of client trace records to ``endpoint``
        (``host:4318`` or a full collector URL).  Works with or without a
        JSONL trace file — OTLP alone is enough to light the span
        recording paths up.  Returns the exporter (its ``flush`` is the
        test/shutdown hook)."""
        from .otlp import OtlpExporter, encode_client_record

        exporter = OtlpExporter(endpoint, "triton-tpu-client",
                                encode_client_record)
        with self._trace_lock:
            old, self._otlp = self._otlp, exporter
        if old is not None:
            old.shutdown(0.0)
        return exporter

    def disable_otlp(self) -> None:
        with self._trace_lock:
            exporter, self._otlp = self._otlp, None
        if exporter is not None:
            exporter.shutdown()

    @property
    def otlp_exporter(self):
        """The active client OTLP exporter, or None."""
        return self._otlp

    @property
    def tracing_enabled(self) -> bool:
        return self._trace_path is not None or self._otlp is not None

    def record_infer_spans(
        self,
        request_id: str,
        model: str,
        protocol: str,
        method: str,
        start_ns: int,
        serialize_end_ns: int,
        network_end_ns: int,
        traceparent: str = "",
        ok: bool = True,
    ) -> None:
        """The one span taxonomy every instrumented client records — a
        REQUEST root closing now, with SERIALIZE (request build +
        compression), NETWORK (wire round trip), and DESERIALIZE (result
        construction) children.  One definition so the four clients cannot
        drift per protocol.  ``ok=False`` records a FAILED attempt — the
        journeys report needs every attempt on file, not just the winner,
        to count attempts-per-success and cross-replica hops."""
        t_end = time.monotonic_ns()
        self.record_client_trace(
            request_id, model, protocol, method,
            spans=[("REQUEST", start_ns, t_end),
                   ("SERIALIZE", start_ns, serialize_end_ns),
                   ("NETWORK", serialize_end_ns, network_end_ns),
                   ("DESERIALIZE", network_end_ns, t_end)],
            ok=ok, traceparent=traceparent)

    def record_client_trace(
        self,
        request_id: str,
        model: str,
        protocol: str,
        method: str,
        spans,
        ok: bool = True,
        traceparent: str = "",
        attempt: int = 0,
        endpoint: str = "",
    ) -> None:
        """Append one client trace record.  ``spans`` is an iterable of
        ``(name, start_ns, end_ns)`` tuples (monotonic clock of THIS
        process: durations are meaningful, absolute values do not align
        with the server's clock — the join compares durations only).
        Inside a journey scope the record is stamped with the attempt
        number and (absent an explicit one) the journey's traceparent, so
        every attempt of one logical request shares one trace id."""
        path = self._trace_path
        otlp = self._otlp
        if path is None and otlp is None:
            return
        journey = _JOURNEY.get()
        if journey is not None:
            attempt = attempt or journey.attempt
            traceparent = traceparent or journey.traceparent
        record: Dict[str, Any] = {
            "request_id": request_id,
            "model": model,
            "protocol": protocol,
            "method": method,
            "ok": ok,
            "spans": [
                {"name": n, "start_ns": int(s), "end_ns": int(e)}
                for n, s, e in spans
            ],
        }
        if traceparent:
            record["traceparent"] = traceparent
        if attempt:
            record["attempt"] = int(attempt)
        if endpoint:
            record["endpoint"] = endpoint
        if otlp is not None:
            otlp.submit(record)
        if path is None:
            return
        line = json.dumps(record)
        with self._trace_lock:
            # re-checked under the lock: a concurrent disable_tracing()
            # closed the handle, and a stale in-flight record must not
            # reopen the file after it (leaking the fd and writing past
            # the disable).  AppendFile swallows OSError itself.
            if self._trace_path != path:
                return
            self._trace_out.append(path, line + "\n")

    def record_journey_event(
        self,
        name: str,
        model: str = "",
        protocol: str = "",
        endpoint: str = "",
        request_id: str = "",
        ok: bool = True,
    ) -> None:
        """One zero-duration journey event (``ENDPOINT_SWITCH``,
        ``BREAKER_OPEN``, ...): a point-in-time marker on the active
        journey's trace, attributed to ``endpoint``.  No-op when tracing
        is off or no journey is active — events only mean something
        relative to the attempts around them."""
        if not self.tracing_enabled:
            return
        journey = _JOURNEY.get()
        if journey is None:
            return
        now = time.monotonic_ns()
        self.record_client_trace(
            request_id or journey.request_id, model, protocol, "event",
            spans=[(name, now, now)], ok=ok,
            traceparent=journey.traceparent, endpoint=endpoint)

    # -- hook --------------------------------------------------------------
    def set_request_hook(
        self, hook: Optional[Callable[[Dict[str, Any]], None]]
    ) -> None:
        """Install (or clear, with None) the on-request hook.  Called after
        each recorded request with the event dict; exceptions are swallowed."""
        self._hook = hook

    # -- export ------------------------------------------------------------
    def reset(self) -> None:
        with self._lock:
            self._requests.clear()
            self._shm_register.clear()
            self._shm_transfer.clear()
            self._endpoint_requests.clear()
            self._endpoint_state.clear()
            self._hedges.clear()

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able snapshot of every series (perf_analyzer
        ``--export-metrics`` / bench.py)."""
        with self._lock:
            # retain the series OBJECTS under the lock: a concurrent reset()
            # clears the dict, and a post-release dict lookup would KeyError
            series = sorted(self._requests.items())
            shm_reg = {k: list(v) for k, v in self._shm_register.items()}
            shm_tx = {k: list(v) for k, v in self._shm_transfer.items()}
            ep_req = dict(self._endpoint_requests)
            ep_state = dict(self._endpoint_state)
            hedges = {k: list(v) for k, v in self._hedges.items()}
        requests = []
        for key, s in series:
            entry = {
                "model": key[0], "protocol": key[1], "method": key[2],
                "success": s.success, "failure": s.failure,
                "retries": s.retries,
                "request_bytes": s.request_bytes,
                "response_bytes": s.response_bytes,
            }
            entry.update(s.latency.snapshot_us())
            requests.append(entry)
        endpoint_urls = sorted({e for e, _ in ep_req} | set(ep_state))
        otlp = self._otlp
        return {
            "requests": requests,
            "otlp": otlp.counters() if otlp is not None else None,
            "endpoints": [
                {"endpoint": e,
                 "success": ep_req.get((e, "success"), 0),
                 "failure": ep_req.get((e, "failure"), 0),
                 "state": ep_state.get(e)}
                for e in endpoint_urls
            ],
            "hedges": [
                {"model": m, "protocol": p, "hedges": c[0], "wins": c[1]}
                for (m, p), c in sorted(hedges.items())
            ],
            "shared_memory": {
                "register": [
                    {"protocol": p, "kind": k,
                     "registrations": c[0], "bytes": c[1]}
                    for (p, k), c in sorted(shm_reg.items())
                ],
                "transfer": [
                    {"kind": k, "direction": d,
                     "transfers": c[0], "bytes": c[1]}
                    for (k, d), c in sorted(shm_tx.items())
                ],
            },
        }

    def render_prometheus(self) -> str:
        """All client series in the Prometheus text exposition format."""
        with self._lock:
            # same reset()-race discipline as snapshot(): hold the series
            # objects, not just their keys
            series = dict(sorted(self._requests.items()))
            shm_reg = {k: list(v) for k, v in self._shm_register.items()}
            shm_tx = {k: list(v) for k, v in self._shm_transfer.items()}
            ep_req = dict(sorted(self._endpoint_requests.items()))
            ep_state = dict(sorted(self._endpoint_state.items()))
            hedges = {k: list(v)
                      for k, v in sorted(self._hedges.items())}
        req_keys = list(series)

        def labels(key: Tuple[str, str, str]) -> str:
            return (f'model="{escape_label(key[0])}",'
                    f'protocol="{escape_label(key[1])}",'
                    f'method="{escape_label(key[2])}"')

        lines: List[str] = []

        def family(name: str, help_text: str, kind: str, rows: List[str]):
            if not rows:
                return
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            lines.extend(rows)

        family(
            "nv_client_inference_request_success",
            "Number of successful client inference requests",
            "counter",
            [f"nv_client_inference_request_success{{{labels(k)}}} "
             f"{series[k].success}" for k in req_keys])
        family(
            "nv_client_inference_request_failure",
            "Number of failed client inference requests",
            "counter",
            [f"nv_client_inference_request_failure{{{labels(k)}}} "
             f"{series[k].failure}" for k in req_keys])
        family(
            "nv_client_retries_total",
            "Number of retried client request attempts (resilience layer)",
            "counter",
            [f"nv_client_retries_total{{{labels(k)}}} "
             f"{series[k].retries}" for k in req_keys])
        family(
            "nv_client_request_bytes_total",
            "Cumulative serialized request payload bytes sent",
            "counter",
            [f"nv_client_request_bytes_total{{{labels(k)}}} "
             f"{series[k].request_bytes}" for k in req_keys])
        family(
            "nv_client_response_bytes_total",
            "Cumulative serialized response payload bytes received",
            "counter",
            [f"nv_client_response_bytes_total{{{labels(k)}}} "
             f"{series[k].response_bytes}" for k in req_keys])

        summary_rows: List[str] = []
        name = "nv_client_inference_request_duration_us"
        for k in req_keys:
            h = series[k].latency
            if not h.count:
                continue
            lbl = labels(k)
            for q in ("0.5", "0.9", "0.99"):
                v = h.quantile(float(q)) * 1e6
                summary_rows.append(
                    f'{name}{{{lbl},quantile="{q}"}} {v:.1f}')
            summary_rows.append(f"{name}_sum{{{lbl}}} {h.sum_s * 1e6:.1f}")
            summary_rows.append(f"{name}_count{{{lbl}}} {h.count}")
        family(name, "Client-observed inference request duration in "
                     "microseconds", "summary", summary_rows)

        family(
            "nv_client_endpoint_requests_total",
            "Number of client requests routed to each cluster endpoint",
            "counter",
            [f'nv_client_endpoint_requests_total{{'
             f'endpoint="{escape_label(e)}",outcome="{escape_label(o)}"}} '
             f"{n}" for (e, o), n in ep_req.items()])
        family(
            "nv_client_endpoint_state",
            "Cluster endpoint breaker state (0=closed, 1=open, 2=half_open)",
            "gauge",
            [f'nv_client_endpoint_state{{endpoint="{escape_label(e)}"}} '
             f"{ENDPOINT_STATE_CODES.get(s, -1)}"
             for e, s in ep_state.items()])
        family(
            "nv_client_hedges_total",
            "Number of hedged requests issued by the cluster client",
            "counter",
            [f'nv_client_hedges_total{{model="{escape_label(m)}",'
             f'protocol="{escape_label(p)}"}} {c[0]}'
             for (m, p), c in hedges.items()])
        family(
            "nv_client_hedge_wins_total",
            "Number of hedged requests where the hedge beat the primary",
            "counter",
            [f'nv_client_hedge_wins_total{{model="{escape_label(m)}",'
             f'protocol="{escape_label(p)}"}} {c[1]}'
             for (m, p), c in hedges.items()])
        family(
            "nv_client_shared_memory_register_total",
            "Number of shared-memory regions registered by this client "
            "process", "counter",
            [f'nv_client_shared_memory_register_total{{'
             f'protocol="{escape_label(p)}",kind="{escape_label(k)}"}} {c[0]}'
             for (p, k), c in sorted(shm_reg.items())])
        family(
            "nv_client_shared_memory_register_bytes_total",
            "Cumulative byte size of shared-memory regions registered",
            "counter",
            [f'nv_client_shared_memory_register_bytes_total{{'
             f'protocol="{escape_label(p)}",kind="{escape_label(k)}"}} {c[1]}'
             for (p, k), c in sorted(shm_reg.items())])
        family(
            "nv_client_shared_memory_transfer_bytes_total",
            "Cumulative bytes copied into/out of shared-memory regions",
            "counter",
            [f'nv_client_shared_memory_transfer_bytes_total{{'
             f'kind="{escape_label(k)}",direction="{escape_label(d)}"}} '
             f"{c[1]}" for (k, d), c in sorted(shm_tx.items())])
        otlp = self._otlp
        if otlp is not None:
            c = otlp.counters()
            family(
                "nv_client_otlp_export_total",
                "Number of OTLP export batches sent by this client process",
                "counter",
                [f'nv_client_otlp_export_total{{outcome="ok"}} {c["ok"]}',
                 f'nv_client_otlp_export_total{{outcome="error"}} '
                 f'{c["error"]}'])
            family(
                "nv_client_otlp_dropped_total",
                "Number of client trace records dropped by the bounded "
                "OTLP export queue", "counter",
                [f'nv_client_otlp_dropped_total {c["dropped"]}'])
        return "\n".join(lines) + ("\n" if lines else "")


_TELEMETRY = ClientTelemetry()


def telemetry() -> ClientTelemetry:
    """The process-wide client telemetry registry."""
    return _TELEMETRY
