"""triton_client_tpu — a TPU-native inference client framework.

A brand-new implementation of the capabilities of the Triton Inference Server
client libraries (reference: ksmooi/triton_client), designed TPU-first:

* Python ``InferenceServerClient`` for HTTP/REST and gRPC speaking the
  KServe/Triton **v2 inference protocol** (sync, async, asyncio, bidirectional
  streaming with sequence support) — ``triton_client_tpu.http`` / ``.grpc``.
* Full tensor request/response model (``InferInput`` /
  ``InferRequestedOutput`` / ``InferResult``) with BYTES and native-BF16
  handling — per-protocol modules.
* System shared memory utilities (POSIX shm via a C shim) —
  ``triton_client_tpu.utils.shared_memory``.
* ``xla_shared_memory`` — the TPU replacement for the reference's CUDA-IPC
  data path: regions are XLA/PjRt device buffers (``jax.Array``) exported via
  DLPack, registered with a co-located TPU-backend server so tensor data never
  crosses the wire — ``triton_client_tpu.utils.xla_shared_memory``.
* A JAX/pjit serving harness + model zoo for hermetic end-to-end testing —
  ``triton_client_tpu.server`` / ``.models``.
* A perf_analyzer-equivalent load generator — ``triton_client_tpu.perf``.
"""

__version__ = "0.1.0"

from ._auth import BasicAuth
from ._client import InferenceServerClientBase
from ._plugin import InferenceServerClientPlugin
from ._request import Request
from ._telemetry import ClientTelemetry, LatencyHistogram, telemetry

__all__ = [
    "BasicAuth",
    "ClientTelemetry",
    "InferenceServerClientBase",
    "InferenceServerClientPlugin",
    "LatencyHistogram",
    "Request",
    "telemetry",
    "__version__",
]
