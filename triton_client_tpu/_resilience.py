"""Client-side resilience: retry policy, backoff, and deadline budgets.

The reference client exposes a full timeout surface (client_timeout on every
API; the gRPC path serializes a per-request ``timeout`` int64 parameter) but
recovers from nothing: one flaky connection or one overloaded model surfaces
straight to every caller.  This module is the client half of the resilience
layer, shared by all four clients (``http``, ``http.aio``, ``grpc``,
``grpc.aio``):

* :class:`RetryPolicy` — max attempts, exponential backoff with **full
  jitter** (Dean & Barroso, "The Tail at Scale": synchronized retries are
  how one hiccup becomes an outage), gated on *retryable* failures only:
  connection errors, HTTP 429/503, gRPC UNAVAILABLE/RESOURCE_EXHAUSTED.
  Server pushback (HTTP ``Retry-After`` / gRPC ``retry-after-ms`` trailing
  metadata) overrides the computed backoff, per the gRPC A6 retry design.
* **Idempotency-aware defaults** — health/metadata calls are always safe to
  retry; ``infer`` is retried only when the caller opts in
  (``retry_infer=True``), because a request that timed out may still have
  executed.
* A per-request **deadline budget** (``deadline_s``): one wall-clock budget
  capping the *total* time across every attempt (not per attempt), the
  remainder of which is propagated to the server — as the v2 ``timeout``
  parameter (microseconds) on gRPC and the ``triton-timeout-us`` header on
  HTTP — so the server can drop a request whose client already gave up
  instead of burning compute on it.

Every retry is observable: ``nv_client_retries_total`` in the client
telemetry registry and a ``RETRY`` span (covering the failed attempt) in the
client trace file when tracing is enabled.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional

from ._telemetry import begin_journey, current_journey, end_journey, telemetry
from .utils import InferenceServerException

__all__ = [
    "RetryPolicy",
    "call_with_retry",
    "call_with_retry_async",
    "deadline_exceeded_error",
    "is_connection_error",
    "is_oversize_error",
    "is_quarantine_error",
    "normalized_status",
]

#: Statuses a policy retries by default: HTTP overload/unavailable and their
#: gRPC siblings.  DEADLINE_EXCEEDED is deliberately absent — retrying a
#: blown deadline only blows it further.
DEFAULT_RETRYABLE_STATUSES = frozenset(
    {"429", "503", "UNAVAILABLE", "RESOURCE_EXHAUSTED"})

#: Message markers of a server wire-size rejection.  The gRPC transport
#: refuses an oversize message with RESOURCE_EXHAUSTED — the SAME status a
#: retryable overload shed carries — so the status alone cannot
#: distinguish "try again later" from "this payload can never fit"; the
#: transport's message ("Received message larger than max (N vs. M)") and
#: the server's 413 body text can.
_OVERSIZE_MSG_MARKERS = (
    "larger than max",            # gRPC max_receive_message_length
    "message length",             # grpc-core variants of the same check
    "max request size",           # this server's typed 413 body
    "max-request-bytes",          # ... and its flag spelling
    "request entity too large",   # stock HTTP 413 reason phrase
)


def is_oversize_error(exc: BaseException) -> bool:
    """True when ``exc`` is a wire-size rejection (HTTP 413, or a gRPC
    RESOURCE_EXHAUSTED raised by the message-length check).  NEVER
    retryable, whatever the policy's status set says: re-sending the same
    payload is doomed to the same rejection N times over — the fix is
    client-side (shrink, chunk, or use shared memory)."""
    status = normalized_status(exc)
    if status == "413":
        return True
    if status in ("RESOURCE_EXHAUSTED", "429"):
        msg = str(exc).lower()
        return any(marker in msg for marker in _OVERSIZE_MSG_MARKERS)
    return False


#: Message markers of a device-fault quarantine refusal (the server's
#: typed 503 / gRPC UNAVAILABLE while a model is quarantined after
#: repeated device faults — server/core.py stamps the message).
_QUARANTINE_MSG_MARKERS = (
    "quarantined",
)


def is_quarantine_error(exc: BaseException) -> bool:
    """True when ``exc`` is a device-fault quarantine refusal: the server
    shed the request BEFORE any compute because the model's device is
    sick (503 / UNAVAILABLE whose message carries the ``quarantined``
    marker).  Always safe to retry — even for non-idempotent ``infer``
    calls, since nothing executed — and the right retry is on ANOTHER
    endpoint: the cluster client's failure hook excludes the quarantined
    replica so the next attempt reroutes (the mirror image of
    :func:`is_oversize_error`, which is never retryable anywhere)."""
    status = normalized_status(exc)
    if status not in ("503", "UNAVAILABLE"):
        return False
    msg = str(exc).lower()
    return any(marker in msg for marker in _QUARANTINE_MSG_MARKERS)

#: Exception class names (anywhere in the MRO) classified as connection-level
#: failures — retryable without a status code.  Name-based so this module
#: needs neither urllib3 nor aiohttp nor grpc imported.
_CONNECTION_EXC_NAMES = frozenset({
    "ConnectionError", "ConnectionResetError", "ConnectionRefusedError",
    "ConnectionAbortedError", "BrokenPipeError",
    # urllib3
    "ProtocolError", "NewConnectionError", "MaxRetryError",
    "NameResolutionError",
    # aiohttp
    "ClientConnectionError", "ClientConnectorError", "ClientOSError",
    "ServerDisconnectedError",
})


#: Exception class names classified as transport timeouts.  A deadline-
#: budgeted attempt whose transport timed out surfaces as the typed
#: deadline error, not a protocol-specific timeout class.
_TIMEOUT_EXC_NAMES = frozenset({
    "TimeoutError",             # builtin, socket.timeout, asyncio (3.11+),
                                # concurrent.futures (distinct pre-3.11)
    "ReadTimeoutError", "ConnectTimeoutError",   # urllib3
    "ServerTimeoutError",                        # aiohttp
})


def is_connection_error(exc: BaseException) -> bool:
    """True when ``exc`` is a transport/connection-level failure (the server
    may never have seen the request)."""
    if isinstance(exc, (ConnectionError, BrokenPipeError)):
        return True
    return any(k.__name__ in _CONNECTION_EXC_NAMES
               for k in type(exc).__mro__)


def is_timeout_error(exc: BaseException) -> bool:
    """True when ``exc`` is a transport-timeout failure."""
    if isinstance(exc, TimeoutError):
        return True
    return any(k.__name__ in _TIMEOUT_EXC_NAMES
               for k in type(exc).__mro__)


def normalized_status(exc: BaseException) -> Optional[str]:
    """The status carried by a client exception, normalized across
    protocols: ``"429"``/``"503"`` (HTTP) or the bare gRPC code name
    (``"UNAVAILABLE"``, stripped of the ``StatusCode.`` prefix)."""
    status = getattr(exc, "_status", None)
    if status is None:
        return None
    status = str(status)
    if status.startswith("StatusCode."):
        status = status[len("StatusCode."):]
    return status


def deadline_exceeded_error(msg: str = "deadline exceeded before the "
                            "request completed") -> InferenceServerException:
    """The typed client-side deadline failure (same status spelling as the
    gRPC mapping so callers match one string on either protocol)."""
    return InferenceServerException(
        msg=msg, status="StatusCode.DEADLINE_EXCEEDED")


class RetryPolicy:
    """Retry/backoff policy shared by all four clients.

    Parameters
    ----------
    max_attempts:
        Total attempts including the first (1 = no retries).
    initial_backoff_s / max_backoff_s / backoff_multiplier:
        Exponential backoff envelope.  The actual delay before attempt
        ``n+1`` is drawn uniformly from ``[0, min(max, initial * mult**n)]``
        (full jitter).
    retry_infer:
        Whether ``infer`` calls may retry.  Off by default: an inference
        that timed out may have executed, and re-running it is only safe
        when the caller knows the model is idempotent.  Health/metadata
        calls are always retryable.
    retryable_statuses:
        Normalized statuses (see :func:`normalized_status`) that gate a
        retry.  Connection-level failures are always retryable.
    deadline_s:
        Default per-request deadline (seconds, total across attempts)
        applied when the call site doesn't pass its own.
    seed:
        Seeds the jitter RNG — deterministic backoff sequences for tests.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        initial_backoff_s: float = 0.05,
        max_backoff_s: float = 2.0,
        backoff_multiplier: float = 2.0,
        retry_infer: bool = False,
        retryable_statuses=DEFAULT_RETRYABLE_STATUSES,
        deadline_s: Optional[float] = None,
        seed: Optional[int] = None,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.initial_backoff_s = float(initial_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.backoff_multiplier = float(backoff_multiplier)
        self.retry_infer = bool(retry_infer)
        self.retryable_statuses = frozenset(retryable_statuses)
        self.deadline_s = deadline_s
        self._rng = random.Random(seed)

    # -- decisions ---------------------------------------------------------
    def should_retry(self, exc: BaseException, method: str,
                     attempt: int) -> bool:
        """Whether a failed ``attempt`` (1-based) of a ``method``-class call
        ("infer" / "health" / "metadata") may be retried."""
        if attempt >= self.max_attempts:
            return False
        if is_quarantine_error(exc):
            # checked BEFORE the retry_infer gate: a quarantine refusal
            # is a pre-compute shed (nothing executed server-side), so
            # retrying is safe even for non-idempotent infer calls — and
            # the cluster client's on_failure exclusion makes the retry
            # land on a healthy replica instead of the sick device
            return True
        if method == "infer" and not self.retry_infer:
            return False
        if is_oversize_error(exc):
            # a 413 / transport message-size rejection is deterministic:
            # the identical payload bounces identically, so a retry only
            # re-uploads a doomed giant N times (and a gRPC oversize
            # arrives as RESOURCE_EXHAUSTED — inside the default
            # retryable set — which is exactly how this loop used to
            # re-send it)
            return False
        if is_connection_error(exc) or is_timeout_error(exc):
            # a per-attempt transport timeout with budget left is as
            # transient as a connection drop — retryable (a timeout whose
            # DEADLINE budget is spent never reaches this: the retry loop
            # converts it to the terminal typed deadline failure first)
            return True
        status = normalized_status(exc)
        return status is not None and status in self.retryable_statuses

    def backoff_s(self, attempt: int,
                  retry_after_s: Optional[float] = None) -> float:
        """Delay before the next attempt.  Server pushback (``Retry-After``
        / gRPC ``retry-after-ms``) overrides the computed backoff outright
        (gRPC A6 semantics: the server knows its own recovery horizon)."""
        if retry_after_s is not None and retry_after_s >= 0:
            return float(retry_after_s)
        cap = min(self.max_backoff_s,
                  self.initial_backoff_s
                  * self.backoff_multiplier ** (attempt - 1))
        return self._rng.uniform(0.0, cap)


def _record_retry(model: str, protocol: str, method_name: str,
                  request_id: str, attempt_start_ns: int) -> None:
    """One retry's observability: the ``nv_client_retries_total`` counter
    plus (when client tracing is on) a ``RETRY`` span covering the failed
    attempt — so a trace join shows *why* a request's client latency
    dwarfs its server latency.  Under a journey scope the span carries the
    journey's traceparent and attempt number (record_client_trace stamps
    both), so the failed attempt stays on the journey's trace id."""
    tel = telemetry()
    tel.record_retry(model, protocol, method_name)
    if tel.tracing_enabled:
        journey = current_journey()
        tel.record_client_trace(
            request_id or (journey.request_id if journey else ""),
            model, protocol, method_name,
            spans=[("RETRY", attempt_start_ns, time.monotonic_ns())],
            ok=False)


def call_with_retry(
    policy: Optional[RetryPolicy],
    attempt_fn: Callable[[Optional[float], int], Any],
    method: str = "infer",
    deadline_s: Optional[float] = None,
    retry_meta=None,
    on_failure: Optional[Callable[[BaseException, int], None]] = None,
    journey: bool = False,
) -> Any:
    """Run ``attempt_fn(remaining_s, attempt)`` under ``policy``.

    ``remaining_s`` is what's left of the deadline budget (None when no
    deadline) — the call site folds it into its transport timeout and
    propagates it to the server.  ``retry_meta`` is ``(model, protocol,
    method_name, request_id)`` for retry telemetry, or None to skip it.
    With ``policy=None`` this is a single attempt under the deadline.
    ``on_failure(exc, attempt)`` fires for EVERY failed attempt (terminal
    ones included, before the failure classification) — the cluster layer
    hangs its endpoint-exclusion set off this hook so a retry lands on a
    *different* replica than the attempt that just failed.

    ``journey=True`` (single-request inference call sites only — a batch
    flight's requests must each keep their own trace id) opens a journey
    scope around the loop: every attempt mints a traceparent sharing ONE
    trace id, with the attempt number stamped into client trace records.
    A call already inside a journey never opens a nested one.
    """
    if deadline_s is None and policy is not None:
        deadline_s = policy.deadline_s
    deadline = (time.monotonic() + deadline_s
                if deadline_s is not None else None)
    rid = retry_meta[3] if retry_meta else ""
    scope = begin_journey(rid) if journey else None
    attempt = 0
    try:
        while True:
            attempt += 1
            if scope is not None:
                scope[0].attempt = attempt
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise deadline_exceeded_error()
            t0_ns = time.monotonic_ns()
            try:
                return attempt_fn(remaining, attempt)
            except BaseException as e:
                if on_failure is not None:
                    on_failure(e, attempt)
                if deadline is not None and is_timeout_error(e) \
                        and time.monotonic() >= deadline - 1e-3:
                    # the deadline budget (not a shorter per-attempt
                    # client/pool timeout) drove this transport timeout —
                    # surface the typed deadline failure, uniform across all
                    # four transports, instead of the raw urllib3/aiohttp/
                    # futures timeout class.  A timeout with budget left
                    # falls through to normal retry classification.
                    raise deadline_exceeded_error() from e
                if policy is None \
                        or not policy.should_retry(e, method, attempt):
                    raise
                delay = policy.backoff_s(
                    attempt, retry_after_s=getattr(e, "retry_after_s", None))
                if deadline is not None \
                        and time.monotonic() + delay >= deadline:
                    raise  # the budget can't cover another attempt
                # recorded only once the retry is actually committed — an
                # abandoned retry must not inflate nv_client_retries_total
                if retry_meta is not None:
                    _record_retry(*retry_meta, t0_ns)
                time.sleep(delay)
    finally:
        if scope is not None:
            end_journey(scope)


async def call_with_retry_async(
    policy: Optional[RetryPolicy],
    attempt_fn,
    method: str = "infer",
    deadline_s: Optional[float] = None,
    retry_meta=None,
    on_failure: Optional[Callable[[BaseException, int], None]] = None,
    journey: bool = False,
) -> Any:
    """Async sibling of :func:`call_with_retry` — ``attempt_fn`` is an
    async callable; backoff awaits instead of blocking the loop.
    ``on_failure`` is a plain (non-async) callback, as in the sync loop;
    ``journey`` opens the same one-trace-id-across-attempts scope (the
    contextvar is task-local, so concurrent journeys don't cross)."""
    import asyncio

    if deadline_s is None and policy is not None:
        deadline_s = policy.deadline_s
    deadline = (time.monotonic() + deadline_s
                if deadline_s is not None else None)
    rid = retry_meta[3] if retry_meta else ""
    scope = begin_journey(rid) if journey else None
    attempt = 0
    try:
        while True:
            attempt += 1
            if scope is not None:
                scope[0].attempt = attempt
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise deadline_exceeded_error()
            t0_ns = time.monotonic_ns()
            try:
                return await attempt_fn(remaining, attempt)
            except BaseException as e:
                if on_failure is not None:
                    on_failure(e, attempt)
                if deadline is not None and (
                        is_timeout_error(e)
                        or isinstance(e, asyncio.TimeoutError)) \
                        and time.monotonic() >= deadline - 1e-3:
                    # same budget-spent typed-deadline normalization as the
                    # sync loop (asyncio.TimeoutError is distinct pre-3.11)
                    raise deadline_exceeded_error() from e
                if policy is None \
                        or not policy.should_retry(e, method, attempt):
                    raise
                delay = policy.backoff_s(
                    attempt, retry_after_s=getattr(e, "retry_after_s", None))
                if deadline is not None \
                        and time.monotonic() + delay >= deadline:
                    raise
                # committed-retries only, as in the sync loop
                if retry_meta is not None:
                    _record_retry(*retry_meta, t0_ns)
                await asyncio.sleep(delay)
    finally:
        if scope is not None:
            end_journey(scope)


def min_timeout(client_timeout: Optional[float],
                remaining_s: Optional[float]) -> Optional[float]:
    """The effective per-attempt transport timeout: the caller's
    client_timeout capped by what's left of the deadline budget."""
    if remaining_s is None:
        return client_timeout
    if client_timeout is None:
        return remaining_s
    return min(client_timeout, remaining_s)


def remaining_us(remaining_s: float) -> int:
    """The remaining deadline budget in the v2 wire unit (microseconds,
    floor 1 so an about-to-expire budget still propagates as expired-on-
    arrival rather than vanishing).  One definition for all four clients —
    the gRPC ``timeout`` parameter and the HTTP ``triton-timeout-us``
    header must never drift apart on unit or clamp."""
    return max(1, int(remaining_s * 1e6))
