"""Shared client base with plugin support.

Reference: ``tritonclient/_client.py`` (:35-86) — a registered plugin is
invoked (via ``_call_plugin``) before every request so it can mutate headers
(e.g. inject auth).  Exactly one plugin may be registered at a time.
"""

from __future__ import annotations

from typing import Optional

from ._plugin import InferenceServerClientPlugin
from ._request import Request


class InferenceServerClientBase:
    def __init__(self):
        self._plugin: Optional[InferenceServerClientPlugin] = None

    def _call_plugin(self, request: Request) -> None:
        if self._plugin is not None:
            self._plugin(request)

    def register_plugin(self, plugin: InferenceServerClientPlugin) -> None:
        """Register ``plugin``; raises if one is already registered
        (reference _client.py:42-66)."""
        if self._plugin is not None:
            raise RuntimeError("A plugin is already registered. Unregister it first.")
        if not isinstance(plugin, InferenceServerClientPlugin):
            raise ValueError("plugin must be an InferenceServerClientPlugin")
        self._plugin = plugin

    def plugin(self) -> Optional[InferenceServerClientPlugin]:
        """Return the registered plugin, or None (reference _client.py:68-75)."""
        return self._plugin

    def unregister_plugin(self) -> None:
        """Unregister the plugin; raises if none registered (reference :77-86)."""
        if self._plugin is None:
            raise RuntimeError("No plugin is registered.")
        self._plugin = None
