"""Flash attention as a pallas TPU kernel.

The serving models' attention (``models/transformer.py:_attn_apply``) is the
hottest non-matmul op in the framework: a naive implementation materialises
the [S, S] score matrix in fp32 through HBM. This kernel keeps scores in
VMEM, tiles queries onto the MXU, and accumulates the softmax online
(the standard flash recipe), so HBM traffic stays O(S·D).

Grid: one program per (batch·head, q-block). Each program holds its
q-block plus the head's full K/V in VMEM and loops over k-blocks with a
``fori_loop`` carrying the online (m, l, acc) state — the in-VMEM mirror of
the cross-device ring in ``_ring_attention`` (same math, one chip).

``flash_attention`` pads S to the block size and masks the padding away, so
any sequence length works. On non-TPU backends it falls back to the jnp
reference implementation unless ``interpret=True`` (used by tests to run
the kernel itself on CPU).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def flash_attention_reference(q, k, v, *, causal: bool = True, sm_scale=None):
    """Plain-jnp attention with the same signature/semantics as the kernel.

    q, k, v: [B, H, S, D]; returns [B, H, S, D] in q.dtype.
    """
    B, H, S, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        idx = jnp.arange(S)
        mask = idx[:, None] >= idx[None, :]
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


def _kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q, block_k,
            seq_len, n_kblocks):
    """One (batch·head, q-block) program. Refs carry a leading length-1
    block dim; k/v refs hold the head's full (padded) sequence."""
    qi = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    ki = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    q_start = _pl().program_id(1) * block_q

    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, _pl().ds(j * block_k, block_k), :]  # [block_k, D]
        v_blk = v_ref[0, _pl().ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        q_idx = q_start + qi
        k_idx = j * block_k + ki
        valid = k_idx < seq_len  # mask the S-padding keys
        if causal:
            valid = jnp.logical_and(valid, q_idx >= k_idx)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        # a fully-masked row would exp(-inf - -inf)=exp(0); zero it instead
        p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1)
        acc_new = corr[:, None] * acc + jax.lax.dot_general(
            p, v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    D = q_ref.shape[-1]
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    a0 = jnp.zeros((block_q, D), jnp.float32)
    if causal:
        # skip k-blocks that lie entirely above the diagonal: the last key
        # this q-block may attend to is q_start + block_q - 1, so only
        # ceil((q_start + block_q) / block_k) blocks carry any work — the
        # causal early exit that halves the FLOPs vs masking everything
        n_iter = (q_start + block_q + block_k - 1) // block_k
        n_iter = jnp.minimum(n_iter, n_kblocks)
    else:
        n_iter = n_kblocks
    _, l, acc = jax.lax.fori_loop(0, n_iter, body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)


def _pl():
    from jax.experimental import pallas as pl

    return pl


@functools.partial(
    jax.jit,
    static_argnames=("causal", "sm_scale", "block_q", "block_k", "interpret"))
def _flash_call(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    from jax.experimental import pallas as pl

    B, H, S, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    bq = min(block_q, max(S, 8))
    bk = min(block_k, max(S, 8))
    s_pad_q = -S % bq
    s_pad_k = -S % bk
    pad = max(s_pad_q, s_pad_k)
    if pad:
        zeros = [(0, 0), (0, 0), (0, pad), (0, 0)]
        qp = jnp.pad(q, zeros)
        kp = jnp.pad(k, zeros)
        vp = jnp.pad(v, zeros)
    else:
        qp, kp, vp = q, k, v
    Sp = S + pad
    qp = qp.reshape(B * H, Sp, D)
    kp = kp.reshape(B * H, Sp, D)
    vp = vp.reshape(B * H, Sp, D)

    kernel = functools.partial(
        _kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_len=S, n_kblocks=Sp // bk)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, Sp, D), q.dtype),
        grid=(B * H, Sp // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, Sp, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, Sp, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
        interpret=interpret,
    )(qp, kp, vp)
    out = out.reshape(B, H, Sp, D)
    return out[:, :, :S, :] if pad else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    return _flash_call(q, k, v, causal, sm_scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = _flash_call(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    # Backward recomputes attention through the jnp reference and takes its
    # VJP — the standard flash trade (no stored [S,S] probabilities costs a
    # recompute); XLA fuses it into one fp32 pass.
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: flash_attention_reference(
            q_, k_, v_, causal=causal, sm_scale=sm_scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q, k, v, *, causal: bool = True, sm_scale=None, block_q: int = 0,
    block_k: int = 0, interpret: bool = False, force: bool = False):
    """Flash attention over [B, H, S, D] tensors; differentiable.

    On TPU backends this runs the pallas kernel; elsewhere it falls back to
    :func:`flash_attention_reference` unless ``interpret`` (run the kernel
    in the pallas interpreter — slow, for tests) or ``force`` is set.

    ``block_q``/``block_k`` of 0 pick measured-good defaults: 256/512 for
    long sequences (3-4x faster than XLA's fused attention at S>=2048 on
    v5e), 128/128 when the sequence is short enough that block padding
    would dominate.
    """
    S = q.shape[2]
    if block_q == 0:
        block_q = 256 if S >= 1024 else 128
    if block_k == 0:
        block_k = 512 if S >= 1024 else 128
    if not (interpret or force) and jax.default_backend() != "tpu":
        return flash_attention_reference(q, k, v, causal=causal,
                                         sm_scale=sm_scale)
    return _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret)
