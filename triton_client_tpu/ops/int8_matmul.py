"""Fused dynamic-quantize + int8 matmul as a pallas TPU kernel.

The int8 MXU serving path (``models/transformer.py``) dynamically
quantizes activations per token, runs s8xs8->s32 einsums, and rescales.
Under XLA that is three HBM passes per matmul: an amax reduce over the
activation, a quantize pass that writes the int8 copy, and the GEMM that
reads it back.  This kernel folds all three into the GEMM's own pipeline:
each activation tile is loaded once (bf16), amax-reduced and quantized in
VMEM, fed to the MXU int8 datapath, and the s32->bf16 scale epilogue is
applied before the tile is written — the quantized activation never
touches HBM.  benchmarks/BERT_PROFILE.md §5 named this fusion as the
remaining layout-level lever on the int8 encoder; §6 records what it
measured.

Grid: 2-D over (row blocks, col blocks) with the full contraction K
resident per program — the serving shapes (K = d_model 1024 or d_ff
4096) fit VMEM comfortably, which buys exact per-row amax (identical
numerics to the XLA path: same scale, same round/clip) without a
cross-block reduction.  Two schedules, selected by which operand should
stay VMEM-resident across the inner sweep: the default iterates N
innermost (activation block resident, weights stream; degenerates to a
weight-resident 1-D grid when block_n == N), and ``m_inner`` iterates M
innermost (weight block resident, activations stream and re-quantize per
visit — measured a loss on the BERT shapes, kept for other geometries;
benchmarks/BERT_PROFILE.md §6).

Like the flash kernel (``ops/flash_attention.py``) this falls back to the
plain-jnp reference off-TPU; ``interpret=True`` runs the kernel itself on
CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# VMEM ceiling per program is ~16 MB; beyond this K the full-row design
# would not fit and the caller gets the XLA path instead.
_MAX_RESIDENT_K = 8192


def int8_matmul_reference(x, w_q, w_scale):
    """Plain-jnp dynamic-quantized matmul (the XLA serving path).

    x: [..., K] float; w_q: [K, N] int8; w_scale: [N] or [1, N] f32
    (per-output-channel).  Returns [..., N] in x.dtype.
    """
    xs = jnp.maximum(
        jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True),
        1e-12) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / xs),
                 -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        q, w_q, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    ws = w_scale.reshape((1,) * (x.ndim - 1) + (-1,)).astype(jnp.float32)
    return (acc.astype(jnp.float32) * xs * ws).astype(x.dtype)


def _kernel(x_ref, w_ref, ws_ref, o_ref):
    """One (m-block, n-block) program: quantize the row block in VMEM,
    int8 MXU dot, fused dequant epilogue."""
    x = x_ref[:].astype(jnp.float32)                      # [bm, K]
    amax = jnp.max(jnp.abs(x), axis=1, keepdims=True)     # [bm, 1]
    xs = jnp.maximum(amax, 1e-12) / 127.0
    # true divide, not reciprocal-multiply: bit-identical codes to the
    # XLA path (_int8_quant) even on round-to-nearest ties
    q = jnp.clip(jnp.round(x / xs), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        q, w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)                 # [bm, bn] s32
    o_ref[:] = (acc.astype(jnp.float32) * xs * ws_ref[:]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_m", "block_n",
                                              "m_inner", "interpret"))
def _call(x2d, w_q, ws_row, block_m, block_n, m_inner, interpret):
    from jax.experimental import pallas as pl

    M, K = x2d.shape
    N = w_q.shape[1]
    pad_m = -M % block_m
    if pad_m:
        x2d = jnp.pad(x2d, ((0, pad_m), (0, 0)))
    Mp = M + pad_m
    if m_inner:
        # grid (n, m): the row index varies innermost, so each WEIGHT
        # block stays VMEM-resident across the full row sweep and the
        # activation streams N/bn times — the right trade when the weight
        # is the bigger stream (x re-reads cost less than w re-reads)
        grid = (N // block_n, Mp // block_m)
        x_map = lambda j, i: (i, 0)
        w_map = lambda j, i: (0, j)
        o_map = lambda j, i: (i, j)
    else:
        grid = (Mp // block_m, N // block_n)
        x_map = lambda i, j: (i, 0)
        w_map = lambda i, j: (0, j)
        o_map = lambda i, j: (i, j)
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((Mp, N), x2d.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, K), x_map),
            pl.BlockSpec((K, block_n), w_map),
            pl.BlockSpec((1, block_n), w_map),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), o_map),
        interpret=interpret,
    )(x2d, w_q, ws_row)
    return out[:M] if pad_m else out


def int8_matmul(x, w_q, w_scale, *, block_m: int = 0, block_n: int = 0,
                m_inner: bool = False, interpret: bool = False,
                force: bool = False):
    """Dynamically-quantized int8 matmul: [..., K] @ [K, N] -> [..., N].

    On TPU backends runs the fused pallas kernel; elsewhere falls back to
    :func:`int8_matmul_reference` unless ``interpret`` (pallas interpreter,
    for tests) or ``force``.  Also falls back when the shape doesn't fit
    the kernel's full-K-resident design (K > 8192 or K/N not lane-aligned).

    ``block_m``/``block_n`` of 0 pick measured defaults: the
    weight-resident schedule (bm=256, bn=N) when the whole weight fits
    VMEM at K>=2048, else output tiles sized to the VMEM budget
    (benchmarks/BERT_PROFILE.md §6 has the measured matrix).
    """
    K = x.shape[-1]
    N = w_q.shape[1]
    on_tpu = interpret or force or jax.default_backend() == "tpu"
    if not on_tpu or K > _MAX_RESIDENT_K or K % 128 or N % 128:
        return int8_matmul_reference(x, w_q, w_scale)
    import os
    blocks_env = os.environ.get("TRITON_TPU_INT8_BLOCKS", "")
    if blocks_env and block_m == 0 and block_n == 0:
        # experimentation knob (benchmarks): "bm:bn", bn may equal N for a
        # weight-resident 1-D grid
        bm_s, bn_s = blocks_env.split(":")
        block_m, block_n = int(bm_s), int(bn_s)
    sched_env = os.environ.get("TRITON_TPU_INT8_SCHED", "")
    if sched_env == "m_inner":
        m_inner = True
    elif sched_env:
        # same loud-rejection policy as TRITON_TPU_INT8_FUSED: a typo'd
        # schedule must not silently measure the default one
        raise ValueError(
            f"TRITON_TPU_INT8_SCHED={sched_env!r}: expected 'm_inner' "
            "or unset")
    if block_n and N % block_n:
        # the grid floors N/block_n — a non-dividing explicit block would
        # leave trailing output columns unwritten.  Explicitly-requested
        # configs fail loudly (a silent XLA fallback would mis-attribute
        # benchmark numbers to the kernel); auto selection below always
        # picks a divisor.
        raise ValueError(
            f"int8_matmul: block_n={block_n} does not divide N={N}")
    if block_m == 0 and block_n == 0 and K >= 2048 and K * N <= 4 * 2**20:
        # weight-resident schedule: the whole [K, N] int8 weight stays in
        # VMEM across the 1-D row grid, so it streams from HBM once per
        # matmul instead of once per row block — the config that beats
        # XLA's unfused path on the FFN-down shape (K=4096, N=1024:
        # 58.4 vs 59.8 ms/forward in-model, benchmarks/BERT_PROFILE.md §6)
        block_m, block_n = 256, N
    if block_m == 0:
        # VMEM budget: the program holds the x row block in bf16 + an f32
        # working copy + the int8 quantized tiles (~7 bytes/elem) plus the
        # w block and s32 accumulator inside the ~16 MB scoped limit —
        # 512 rows fits K<=2048; K=4096 needs 256
        block_m = 512 if K <= 2048 else 256
    if block_n == 0:
        # largest lane-aligned divisor of N up to 512 (N % 128 == 0 was
        # gated above, so 128 always qualifies)
        block_n = next(bn for bn in (512, 384, 256, 128) if N % bn == 0)
    lead = x.shape[:-1]
    M = 1
    for d in lead:
        M *= d
    x2d = x.reshape(M, K)
    # clamp to M, then round up to a sublane multiple: a small unaligned M
    # (e.g. 50) must not produce a Mosaic block like (50, K) — _call's
    # pad_m already covers M < block_m, so rounding up is always safe
    block_m = min(block_m, max(8, M))
    block_m = -(-block_m // 8) * 8
    ws_row = w_scale.reshape(1, N).astype(jnp.float32)
    out = _call(x2d, w_q, ws_row, block_m, block_n, m_inner, interpret)
    return out.reshape(*lead, N)
