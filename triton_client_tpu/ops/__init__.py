"""TPU-native kernels for the hot ops (pallas).

The reference client has no compute kernels of its own — its models run
inside Triton's backends (cuDNN/cuBLAS/TensorRT). This framework serves
models directly, so the hot inner ops live here, written as pallas TPU
kernels with jnp fallbacks for non-TPU backends.
"""

from .flash_attention import flash_attention, flash_attention_reference
from .int8_matmul import int8_matmul, int8_matmul_reference

__all__ = ["flash_attention", "flash_attention_reference",
           "int8_matmul", "int8_matmul_reference"]
