"""HTTP client helpers (reference ``tritonclient/http/_utils.py``, 151 LoC)."""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

from ..utils import InferenceServerException, raise_error


def raise_if_error(status: int, body: bytes, headers=None) -> None:
    """Raise InferenceServerException for non-2xx responses, extracting the
    v2 ``{"error": msg}`` payload when present (reference _get_error/
    _raise_if_error, _utils.py:33-75).  ``headers`` (when the call site has
    them) supplies the ``Retry-After`` pushback a shed 429/503 carries —
    the resilience layer's backoff honors it over its own jitter."""
    if 200 <= status < 300:
        return
    msg = None
    try:
        msg = json.loads(body).get("error")
    except Exception:
        msg = body.decode("utf-8", errors="replace") if body else None
    exc = InferenceServerException(
        msg=msg or f"[{status}] inference request failed", status=str(status)
    )
    if headers is not None:
        # the precise sub-second horizon wins over the RFC 7231 integer
        # Retry-After it rides alongside
        for key, scale in (("triton-retry-after-ms", 1e-3),
                           ("Triton-Retry-After-Ms", 1e-3),
                           ("Retry-After", 1.0), ("retry-after", 1.0)):
            if key in headers:
                try:
                    exc.retry_after_s = float(headers[key]) * scale
                except (TypeError, ValueError):
                    continue  # HTTP-date form: backoff jitter covers it
                break
    raise exc


def build_infer_request_dict(
    inputs,
    request_id: str,
    outputs,
    sequence_id,
    sequence_start: bool,
    sequence_end: bool,
    priority: int,
    timeout: Optional[int],
    custom_parameters: Optional[dict],
) -> dict:
    """The v2 infer request JSON header as a dict — shared by the per-call
    body builder below and the fast-path template compiler
    (``_template.py``), so the two can never drift on key order or
    reserved-parameter policy."""
    infer_request = {}
    parameters = {}
    if request_id:
        infer_request["id"] = request_id
    if sequence_id:
        parameters["sequence_id"] = sequence_id
        parameters["sequence_start"] = sequence_start
        parameters["sequence_end"] = sequence_end
    if priority:
        parameters["priority"] = priority
    if timeout is not None:
        parameters["timeout"] = timeout

    infer_request["inputs"] = [i._get_tensor() for i in inputs]
    if outputs:
        infer_request["outputs"] = [o._get_tensor() for o in outputs]
    else:
        # No outputs requested => return all, binary by default
        parameters["binary_data_output"] = True

    if custom_parameters:
        for key, value in custom_parameters.items():
            if key in (
                "sequence_id",
                "sequence_start",
                "sequence_end",
                "priority",
                "binary_data_output",
            ):
                raise_error(
                    f"Parameter {key!r} is a reserved parameter and cannot be specified."
                )
            parameters[key] = value
    if parameters:
        infer_request["parameters"] = parameters
    return infer_request


def assemble_body(header: bytes, raws) -> Tuple[bytes, Optional[int]]:
    """Gather the JSON header + raw tensor payloads into the wire body with
    ONE copy (a single join over the header and the memoryview/bytearray
    payloads).  Returns (body, json_size), json_size None for JSON-only
    bodies — matching the reference's framing contract."""
    total = 0
    for raw in raws:
        total += len(raw)
    if total:
        # tpu-lint: disable=WIRE-COPY the single required gather into the wire body
        return b"".join([header, *raws]), len(header)
    return header, None


def get_inference_request_body(
    inputs,
    request_id: str,
    outputs,
    sequence_id,
    sequence_start: bool,
    sequence_end: bool,
    priority: int,
    timeout: Optional[int],
    custom_parameters: Optional[dict],
) -> Tuple[bytes, Optional[int]]:
    """Build the infer request body: JSON header + concatenated raw buffers.
    Returns (body, json_size) where json_size is None for JSON-only bodies
    (reference _get_inference_request, _utils.py:85-150)."""
    infer_request = build_infer_request_dict(
        inputs, request_id, outputs, sequence_id, sequence_start,
        sequence_end, priority, timeout, custom_parameters)
    header = json.dumps(infer_request).encode()
    raws = []
    for input_tensor in inputs:
        raw = input_tensor._get_binary_data()
        if raw is not None:
            raws.append(raw)
    return assemble_body(header, raws)
