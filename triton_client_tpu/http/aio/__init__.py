"""asyncio HTTP ``InferenceServerClient``.

Parity target: reference ``tritonclient/http/aio/__init__.py`` (775 LoC) —
the sync HTTP surface as ``async def`` over an aiohttp ``ClientSession``
with ``TCPConnector(limit=conn_limit)`` and ``auto_decompress=False``
(reference :92-120); same URI scheme and binary-over-HTTP framing.
"""

from __future__ import annotations

import asyncio
import gzip
import json
import time
import zlib
from typing import Any, Dict, List, Optional
from urllib.parse import quote, urlencode

import aiohttp

from ..._client import InferenceServerClientBase
from ..._request import Request
from ..._resilience import (RetryPolicy, call_with_retry_async,
                            deadline_exceeded_error, min_timeout,
                            normalized_status, remaining_us)
from ..._telemetry import (merge_trace_headers, telemetry,
                           traceparent_on_wire)
from ..._uvloop import maybe_install_uvloop
from ...utils import InferenceServerException, raise_error
from .._infer_result import InferResult
from .._template import RequestTemplate
from .._utils import get_inference_request_body, raise_if_error

__all__ = ["InferenceServerClient", "PreparedRequest"]

# optional uvloop (TRITON_TPU_UVLOOP=1; stdlib loop otherwise) — must run
# before any session/loop is created by this module's callers
maybe_install_uvloop()


class PreparedRequest:
    """Async sibling of the sync client's fast-path handle: a compiled
    :class:`RequestTemplate` bound to an aio client (same template class —
    it is immutable and loop-agnostic)."""

    def __init__(self, client, template: RequestTemplate):
        self._client = client
        self.template = template
        path = f"v2/models/{quote(template.model_name)}"
        if template.model_version:
            path += f"/versions/{template.model_version}"
        self.infer_path = path + "/infer"

    async def infer(self, request_id="", headers=None, query_params=None,
                    tenant=None,
                    retry_policy: Optional[RetryPolicy] = None,
                    deadline_s: Optional[float] = None) -> InferResult:
        client = self._client
        policy = retry_policy if retry_policy is not None \
            else client._retry_policy
        if policy is None and deadline_s is None:
            return await client._infer_prepared(
                self, request_id, headers, query_params, tenant)
        return await call_with_retry_async(
            policy,
            lambda remaining, _attempt: client._infer_prepared(
                self, request_id, headers, query_params, tenant,
                _remaining_s=remaining),
            method="infer", deadline_s=deadline_s,
            retry_meta=(self.template.model_name, "http_aio", "infer",
                        request_id), journey=True)


class InferenceServerClient(InferenceServerClientBase):
    """v2 protocol over aiohttp (reference aio client :92)."""

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        conn_limit: int = 100,
        conn_timeout: float = 60.0,
        ssl: bool = False,
        ssl_context=None,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        super().__init__()
        # client-level resilience default (see the sync client): health/
        # metadata retry unconditionally, infer per its retry_infer opt-in
        self._retry_policy = retry_policy
        if url.startswith("http://") or url.startswith("https://"):
            raise_error("url should not include the scheme")
        self._url = url
        scheme = "https://" if ssl else "http://"
        self._base_uri = (scheme + url).rstrip("/")
        self._verbose = verbose
        connector = aiohttp.TCPConnector(limit=conn_limit, ssl=ssl_context if ssl else False)
        self._conn_timeout = conn_timeout
        self._session = aiohttp.ClientSession(
            connector=connector,
            timeout=aiohttp.ClientTimeout(total=conn_timeout),
            auto_decompress=False,
        )

    @property
    def url(self) -> str:
        """The scheme-less ``host:port`` this client talks to — the
        endpoint label the cluster layer keys its routing counters by."""
        return self._url

    # -- lifecycle ---------------------------------------------------------
    async def close(self) -> None:
        await self._session.close()

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    # -- low-level ---------------------------------------------------------
    def _build_headers(self, headers: Optional[dict]) -> dict:
        request = Request(dict(headers) if headers else {})
        self._call_plugin(request)
        # reference aio client :122-134: hop-by-hop framing headers would
        # corrupt the binary-over-HTTP body; reject rather than forward
        bad = [
            k
            for k in request.headers
            if k.lower() in ("transfer-encoding",)
        ]
        if bad:
            raise_error(
                f"Unsupported headers {bad}; use a different client or "
                "remove them."
            )
        return request.headers

    def _uri(self, path: str, query_params: Optional[dict]) -> str:
        uri = f"{self._base_uri}/{path}"
        if query_params:
            uri += "?" + urlencode(query_params, doseq=True)
        return uri

    async def _get(self, path, headers, query_params,
                   timeout_s=None) -> tuple:
        uri = self._uri(path, query_params)
        if self._verbose:
            print(f"GET {uri}")
        kwargs = {}
        if timeout_s is not None:
            # deadline budget caps (never replaces) the session timeout
            kwargs["timeout"] = aiohttp.ClientTimeout(
                total=min_timeout(self._conn_timeout, timeout_s))
        async with self._session.get(
                uri, headers=self._build_headers(headers),
                **kwargs) as resp:
            body = await resp.read()
            return resp.status, dict(resp.headers), _decompress(resp.headers, body)

    async def _post(self, path, body, headers, query_params,
                    extra_headers=None, timeout_s=None) -> tuple:
        uri = self._uri(path, query_params)
        hdrs = self._build_headers(headers)
        if extra_headers:
            hdrs.update(extra_headers)
        if self._verbose:
            print(f"POST {uri}")
        kwargs = {}
        if timeout_s is not None:
            # the deadline budget CAPS the configured session timeout —
            # a deliberately short conn_timeout keeps guarding each
            # attempt even under a generous budget
            kwargs["timeout"] = aiohttp.ClientTimeout(
                total=min_timeout(self._conn_timeout, timeout_s))
        async with self._session.post(
                uri, data=body, headers=hdrs, **kwargs) as resp:
            data = await resp.read()
            return resp.status, dict(resp.headers), _decompress(resp.headers, data)

    async def _with_retry(self, method_kind: str, fn):
        """Run an idempotent (health/metadata) call under the client-level
        retry policy, if one is configured.  ``fn(timeout_s)`` receives
        the remaining deadline budget so each attempt is capped."""
        if self._retry_policy is None:
            return await fn(None)

        async def _attempt(remaining, _att):
            return await fn(remaining)

        return await call_with_retry_async(
            self._retry_policy, _attempt, method=method_kind,
            retry_meta=("", "http_aio", method_kind, ""))

    async def _health_get(self, path, headers, query_params) -> bool:
        """Health probe with 429/503 retry under a policy, degrading to
        the no-raise boolean once retries are exhausted (see the sync
        client)."""
        async def _call(remaining):
            status, hdrs, body = await self._get(
                path, headers, query_params, timeout_s=remaining)
            if self._retry_policy is not None and status in (429, 503):
                raise_if_error(status, body, hdrs)
            return status

        try:
            status = await self._with_retry("health", _call)
        except InferenceServerException as e:
            if normalized_status(e) in ("429", "503"):
                return False  # still overloaded after every retry
            raise
        return status == 200

    # -- health / metadata -------------------------------------------------
    async def is_server_live(self, headers=None, query_params=None) -> bool:
        return await self._health_get("v2/health/live", headers,
                                      query_params)

    async def is_server_ready(self, headers=None, query_params=None) -> bool:
        return await self._health_get("v2/health/ready", headers,
                                      query_params)

    async def is_model_ready(
        self, model_name, model_version="", headers=None, query_params=None
    ) -> bool:
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        return await self._health_get(f"{path}/ready", headers,
                                      query_params)

    async def get_server_metadata(self, headers=None, query_params=None) -> dict:
        async def _call(remaining):
            status, hdrs, body = await self._get(
                "v2", headers, query_params, timeout_s=remaining)
            raise_if_error(status, body, hdrs)
            return body

        return json.loads(await self._with_retry("metadata", _call))

    async def get_model_metadata(
        self, model_name, model_version="", headers=None, query_params=None
    ) -> dict:
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"

        async def _call(remaining):
            status, hdrs, body = await self._get(
                path, headers, query_params, timeout_s=remaining)
            raise_if_error(status, body, hdrs)
            return body

        return json.loads(await self._with_retry("metadata", _call))

    async def get_model_config(
        self, model_name, model_version="", headers=None, query_params=None
    ) -> dict:
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"

        async def _call(remaining):
            status, hdrs, body = await self._get(
                f"{path}/config", headers, query_params,
                timeout_s=remaining)
            raise_if_error(status, body, hdrs)
            return body

        return json.loads(await self._with_retry("metadata", _call))

    # -- repository --------------------------------------------------------
    async def get_model_repository_index(self, headers=None, query_params=None) -> list:
        status, _, body = await self._post("v2/repository/index", b"", headers, query_params)
        raise_if_error(status, body)
        return json.loads(body)

    async def load_model(
        self, model_name, headers=None, query_params=None,
        config: Optional[str] = None, files: Optional[Dict[str, bytes]] = None,
    ) -> None:
        import base64

        load_request: Dict[str, Any] = {}
        if config is not None or files:
            load_request["parameters"] = {}
        if config is not None:
            load_request["parameters"]["config"] = config
        if files:
            for path, content in files.items():
                load_request["parameters"][path] = base64.b64encode(content).decode()
        status, _, body = await self._post(
            f"v2/repository/models/{quote(model_name)}/load",
            json.dumps(load_request).encode() if load_request else b"",
            headers, query_params,
        )
        raise_if_error(status, body)

    async def unload_model(
        self, model_name, headers=None, query_params=None, unload_dependents=False
    ) -> None:
        body = {"parameters": {"unload_dependents": unload_dependents}}
        status, _, data = await self._post(
            f"v2/repository/models/{quote(model_name)}/unload",
            json.dumps(body).encode(), headers, query_params,
        )
        raise_if_error(status, data)

    # -- statistics / trace / logging --------------------------------------
    async def get_inference_statistics(
        self, model_name="", model_version="", headers=None, query_params=None
    ) -> dict:
        if model_name:
            path = f"v2/models/{quote(model_name)}"
            if model_version:
                path += f"/versions/{model_version}"
            path += "/stats"
        else:
            path = "v2/models/stats"
        status, _, body = await self._get(path, headers, query_params)
        raise_if_error(status, body)
        return json.loads(body)

    async def update_trace_settings(
        self, model_name=None, settings=None, headers=None, query_params=None
    ) -> dict:
        path = (
            f"v2/models/{quote(model_name)}/trace/setting" if model_name else "v2/trace/setting"
        )
        status, _, body = await self._post(
            path, json.dumps(settings or {}).encode(), headers, query_params
        )
        raise_if_error(status, body)
        return json.loads(body)

    async def get_trace_settings(self, model_name=None, headers=None, query_params=None) -> dict:
        path = (
            f"v2/models/{quote(model_name)}/trace/setting" if model_name else "v2/trace/setting"
        )
        status, _, body = await self._get(path, headers, query_params)
        raise_if_error(status, body)
        return json.loads(body)

    async def update_log_settings(self, settings, headers=None, query_params=None) -> dict:
        status, _, body = await self._post(
            "v2/logging", json.dumps(settings).encode(), headers, query_params
        )
        raise_if_error(status, body)
        return json.loads(body)

    async def get_log_settings(self, headers=None, query_params=None) -> dict:
        status, _, body = await self._get("v2/logging", headers, query_params)
        raise_if_error(status, body)
        return json.loads(body)

    async def get_flight_recorder(self, model_name=None, limit=0,
                                  headers=None, query_params=None) -> dict:
        """The server's flight-recorder debug snapshot (always-on recent
        ring + pinned tail-latency/failure outliers with span trees)."""
        params = dict(query_params or {})
        if model_name:
            params["model"] = model_name
        if limit:
            params["limit"] = limit
        status, _, body = await self._get(
            "v2/debug/flight_recorder", headers, params or None)
        raise_if_error(status, body)
        return json.loads(body)

    async def get_device_stats(self, model_name=None, headers=None,
                               query_params=None) -> dict:
        """The server's device/scheduler observability snapshot (duty
        cycle / live MFU / compiles / ticks / transfers / HBM + SLO
        state) — same JSON as GET /v2/debug/device_stats."""
        params = dict(query_params or {})
        if model_name:
            params["model"] = model_name
        status, _, body = await self._get(
            "v2/debug/device_stats", headers, params or None)
        raise_if_error(status, body)
        return json.loads(body)

    async def get_costs(self, model_name=None, headers=None,
                        query_params=None) -> dict:
        """The server's per-tenant cost-attribution ledger: device-time,
        FLOPs, generated tokens, and KV byte-seconds per (model, tenant)
        — GET /v2/debug/costs."""
        params = dict(query_params or {})
        if model_name:
            params["model"] = model_name
        status, _, body = await self._get(
            "v2/debug/costs", headers, params or None)
        raise_if_error(status, body)
        return json.loads(body)

    # -- shared memory -----------------------------------------------------
    async def get_system_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ) -> list:
        path = "v2/systemsharedmemory"
        if region_name:
            path += f"/region/{quote(region_name)}"
        status, _, body = await self._get(f"{path}/status", headers, query_params)
        raise_if_error(status, body)
        return json.loads(body)

    async def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, query_params=None
    ) -> None:
        body = {"key": key, "offset": offset, "byte_size": byte_size}
        status, _, data = await self._post(
            f"v2/systemsharedmemory/region/{quote(name)}/register",
            json.dumps(body).encode(), headers, query_params,
        )
        raise_if_error(status, data)
        telemetry().record_shm_register("http_aio", "system", byte_size)

    async def unregister_system_shared_memory(
        self, name="", headers=None, query_params=None
    ) -> None:
        if name:
            path = f"v2/systemsharedmemory/region/{quote(name)}/unregister"
        else:
            path = "v2/systemsharedmemory/unregister"
        status, _, data = await self._post(path, b"", headers, query_params)
        raise_if_error(status, data)

    async def get_cuda_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ) -> list:
        path = "v2/cudasharedmemory"
        if region_name:
            path += f"/region/{quote(region_name)}"
        status, _, body = await self._get(f"{path}/status", headers, query_params)
        raise_if_error(status, body)
        return json.loads(body)

    async def register_cuda_shared_memory(
        self, name, raw_handle: bytes, device_id: int, byte_size: int,
        headers=None, query_params=None,
    ) -> None:
        import base64

        body = {
            "raw_handle": {"b64": base64.b64encode(raw_handle).decode()},
            "device_id": device_id,
            "byte_size": byte_size,
        }
        status, _, data = await self._post(
            f"v2/cudasharedmemory/region/{quote(name)}/register",
            json.dumps(body).encode(), headers, query_params,
        )
        raise_if_error(status, data)
        telemetry().record_shm_register("http_aio", "cuda", byte_size)

    register_xla_shared_memory = register_cuda_shared_memory
    get_xla_shared_memory_status = get_cuda_shared_memory_status

    async def unregister_cuda_shared_memory(self, name="", headers=None, query_params=None) -> None:
        if name:
            path = f"v2/cudasharedmemory/region/{quote(name)}/unregister"
        else:
            path = "v2/cudasharedmemory/unregister"
        status, _, data = await self._post(path, b"", headers, query_params)
        raise_if_error(status, data)

    unregister_xla_shared_memory = unregister_cuda_shared_memory

    # -- inference ---------------------------------------------------------
    # store-and-forward statics (reference aio :661-689): same contract as
    # the sync client's — aliased so the two cannot drift
    from .._client import InferenceServerClient as _Sync

    generate_request_body = staticmethod(_Sync.generate_request_body)
    parse_response_body = staticmethod(_Sync.parse_response_body)
    del _Sync

    # -- wire fast path ----------------------------------------------------
    def prepare(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        priority=0,
        timeout=None,
        parameters=None,
    ) -> PreparedRequest:
        """Compile the invariant request skeleton once (sync client's
        ``prepare`` contract; the template is shared machinery)."""
        return PreparedRequest(self, RequestTemplate(
            model_name, inputs, outputs, model_version, priority, timeout,
            parameters))

    async def _infer_prepared(self, prep: PreparedRequest, request_id,
                              headers, query_params, tenant,
                              _remaining_s=None, raws=None, _sink=None):
        """One stamped-request round trip (see the sync client's sibling
        for the ``_sink`` batch-telemetry contract)."""
        tel = telemetry()
        t_ser0 = time.monotonic_ns()
        body, json_size = prep.template.stamp(request_id, raws)
        extra_headers = {}
        if tenant:
            extra_headers["triton-tenant"] = str(tenant)
        if json_size is not None:
            extra_headers["Inference-Header-Content-Length"] = str(json_size)
        trace_headers, rid = merge_trace_headers(headers, request_id)
        extra_headers.update(trace_headers)
        if _remaining_s is not None:
            extra_headers["triton-timeout-us"] = str(
                remaining_us(_remaining_s))
        t_ser1 = time.monotonic_ns()
        t0 = time.perf_counter()
        try:
            status, resp_headers, data = await self._post(
                prep.infer_path, body, headers, query_params, extra_headers,
                timeout_s=_remaining_s)
            raise_if_error(status, data, resp_headers)
        except Exception:
            if _sink is not None:
                _sink.append((False, time.perf_counter() - t0, len(body),
                              0, rid))
            else:
                tel.record_request(
                    prep.template.model_name, "http_aio", "infer",
                    time.perf_counter() - t0, ok=False,
                    request_bytes=len(body), request_id=rid)
                if tel.tracing_enabled:
                    tel.record_infer_spans(
                        rid, prep.template.model_name, "http_aio", "infer",
                        t_ser0, t_ser1, time.monotonic_ns(),
                        traceparent=traceparent_on_wire(
                            headers, trace_headers),
                        ok=False)
            raise
        t_net1 = time.monotonic_ns()
        if _sink is not None:
            _sink.append((True, time.perf_counter() - t0, len(body),
                          len(data), rid))
        else:
            tel.record_request(
                prep.template.model_name, "http_aio", "infer",
                time.perf_counter() - t0, ok=True, request_bytes=len(body),
                response_bytes=len(data), request_id=rid)
        header_length = resp_headers.get("Inference-Header-Content-Length")
        result = InferResult(
            data, self._verbose,
            int(header_length) if header_length is not None else None,
            None, headers=resp_headers)
        if tel.tracing_enabled:
            tel.record_infer_spans(
                rid, prep.template.model_name, "http_aio", "infer",
                t_ser0, t_ser1, t_net1,
                traceparent=traceparent_on_wire(headers, trace_headers))
        return result

    async def infer_many(
        self,
        model_name,
        requests,
        model_version="",
        outputs=None,
        priority=0,
        timeout=None,
        parameters=None,
        request_ids=None,
        headers=None,
        query_params=None,
        tenant: Optional[str] = None,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
        window: int = 32,
    ) -> List[InferResult]:
        """Batch submit with a bounded-concurrency gather (``window``
        in-flight at once): one compiled template, one retry/deadline
        envelope, one locked telemetry batch per flight.  Results keep
        submission order and equal N sequential ``infer`` calls; a retry
        re-runs only the items that had not completed."""
        items = list(requests)
        if not items:
            return []
        template = RequestTemplate(
            model_name, items[0], outputs, model_version, priority, timeout,
            parameters)
        prep = PreparedRequest(self, template)
        raws_list = [template.raws_for(item) for item in items]
        ids = list(request_ids) if request_ids else [""] * len(items)
        if len(ids) != len(items):
            raise_error("request_ids length must match requests")
        results: List[Optional[InferResult]] = [None] * len(items)
        done = [False] * len(items)
        tel = telemetry()

        async def flight(remaining, _attempt):
            # ONE deadline for the whole flight, re-derived as each item
            # acquires a window slot (a slow batch raises instead of
            # granting every window the full budget)
            deadline = (time.monotonic() + remaining
                        if remaining is not None else None)
            sem = asyncio.Semaphore(max(1, window))
            sink: list = []

            async def one(i):
                async with sem:
                    rem_i = None
                    if deadline is not None:
                        rem_i = deadline - time.monotonic()
                        if rem_i <= 0:
                            raise deadline_exceeded_error()
                    results[i] = await self._infer_prepared(
                        prep, ids[i], headers, query_params, tenant,
                        _remaining_s=rem_i, raws=raws_list[i],
                        _sink=sink)
                    done[i] = True

            pending = [i for i, d in enumerate(done) if not d]
            try:
                outcomes = await asyncio.gather(
                    *(one(i) for i in pending), return_exceptions=True)
            finally:
                tel.record_request_batch(
                    model_name, "http_aio", "infer", sink)
            for out in outcomes:
                if isinstance(out, BaseException):
                    raise out
            return results

        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        if policy is None and deadline_s is None:
            return await flight(None, 1)
        return await call_with_retry_async(
            policy, flight, method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, "http_aio", "infer", ""))

    async def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> InferResult:
        """Async inference (reference aio :694).  ``retry_policy`` /
        ``deadline_s``: same resilience contract as the sync client;
        ``priority``/``tenant``: the QoS identity, re-stamped per
        attempt so retries carry it."""
        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        if policy is None and deadline_s is None:
            return await self._infer_once(
                model_name, inputs, model_version, outputs, request_id,
                sequence_id, sequence_start, sequence_end, priority, timeout,
                headers, query_params, request_compression_algorithm,
                response_compression_algorithm, parameters, tenant)
        return await call_with_retry_async(
            policy,
            lambda remaining, _attempt: self._infer_once(
                model_name, inputs, model_version, outputs, request_id,
                sequence_id, sequence_start, sequence_end, priority, timeout,
                headers, query_params, request_compression_algorithm,
                response_compression_algorithm, parameters, tenant,
                _remaining_s=remaining),
            method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, "http_aio", "infer", request_id),
            journey=True)

    async def _infer_once(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
        tenant=None,
        _remaining_s=None,
    ) -> InferResult:
        tel = telemetry()
        t_ser0 = time.monotonic_ns()
        body, json_size = get_inference_request_body(
            inputs, request_id, outputs, sequence_id, sequence_start, sequence_end,
            priority, timeout, parameters,
        )
        extra_headers = {}
        if tenant:
            # QoS identity: same header contract as the sync client
            extra_headers["triton-tenant"] = str(tenant)
        if request_compression_algorithm == "gzip":
            body = gzip.compress(body)
            extra_headers["Content-Encoding"] = "gzip"
        elif request_compression_algorithm == "deflate":
            body = zlib.compress(body)
            extra_headers["Content-Encoding"] = "deflate"
        if response_compression_algorithm in ("gzip", "deflate"):
            extra_headers["Accept-Encoding"] = response_compression_algorithm
        if json_size is not None:
            extra_headers["Inference-Header-Content-Length"] = str(json_size)
        # trace propagation: same contract as the sync client (server
        # records the id in trace JSON and echoes it back)
        trace_headers, rid = merge_trace_headers(headers, request_id)
        extra_headers.update(trace_headers)
        if _remaining_s is not None:
            # remaining deadline budget, restamped per attempt
            extra_headers["triton-timeout-us"] = str(
                remaining_us(_remaining_s))
        t_ser1 = time.monotonic_ns()

        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        path += "/infer"
        t0 = time.perf_counter()
        try:
            status, resp_headers, data = await self._post(
                path, body, headers, query_params, extra_headers,
                timeout_s=_remaining_s
            )
            raise_if_error(status, data, resp_headers)
        except Exception:
            tel.record_request(
                model_name, "http_aio", "infer", time.perf_counter() - t0,
                ok=False, request_bytes=len(body),
                request_id=rid)
            if tel.tracing_enabled:
                # failed attempts stay on the journey's trace (see the
                # sync client) — the journeys report counts every attempt
                tel.record_infer_spans(
                    rid, model_name, "http_aio", "infer", t_ser0, t_ser1,
                    time.monotonic_ns(),
                    traceparent=traceparent_on_wire(headers, trace_headers),
                    ok=False)
            raise
        t_net1 = time.monotonic_ns()
        tel.record_request(
            model_name, "http_aio", "infer", time.perf_counter() - t0,
            ok=True, request_bytes=len(body), response_bytes=len(data),
            request_id=rid)
        header_length = resp_headers.get("Inference-Header-Content-Length")
        result = InferResult(
            data, self._verbose,
            int(header_length) if header_length is not None else None, None,
            headers=resp_headers,
        )
        if tel.tracing_enabled:
            tel.record_infer_spans(
                rid, model_name, "http_aio", "infer", t_ser0, t_ser1, t_net1,
                traceparent=traceparent_on_wire(headers, trace_headers))
        return result


def _decompress(headers, body: bytes) -> bytes:
    """The session runs with auto_decompress=False (reference :92-120), so
    undo Content-Encoding here where the framing header is interpretable."""
    encoding = headers.get("Content-Encoding", "")
    if encoding == "gzip":
        return gzip.decompress(body)
    if encoding == "deflate":
        return zlib.decompress(body)
    return body
