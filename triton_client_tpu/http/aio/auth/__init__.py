"""Auth plugins for the aio HTTP client (reference ``tritonclient/http/aio/auth``)."""

from ...._auth import BasicAuth

__all__ = ["BasicAuth"]
