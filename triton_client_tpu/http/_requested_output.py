"""HTTP-protocol ``InferRequestedOutput``.

Parity target: reference ``tritonclient/http/_requested_output.py`` (118
LoC): binary_data flag, classification count, shm params mutually exclusive
with binary_data (:69-104).
"""

from __future__ import annotations


class InferRequestedOutput:
    def __init__(self, name: str, binary_data: bool = True, class_count: int = 0):
        self._name = name
        self._parameters: dict = {}
        self._binary = binary_data
        if class_count != 0:
            self._parameters["classification"] = class_count
        self._parameters["binary_data"] = binary_data

    def name(self) -> str:
        return self._name

    def set_shared_memory(self, region_name: str, byte_size: int, offset: int = 0):
        """Request the output be written into a registered shm region; clears
        the binary_data flag (they are mutually exclusive, reference :69-96)."""
        self._parameters.pop("binary_data", None)
        self._parameters["shared_memory_region"] = region_name
        self._parameters["shared_memory_byte_size"] = byte_size
        if offset != 0:
            self._parameters["shared_memory_offset"] = offset
        return self

    def unset_shared_memory(self):
        """Clear shm params, restoring the binary_data flag (reference
        :98-110)."""
        self._parameters.pop("shared_memory_region", None)
        self._parameters.pop("shared_memory_byte_size", None)
        self._parameters.pop("shared_memory_offset", None)
        self._parameters["binary_data"] = self._binary
        return self

    def _get_tensor(self) -> dict:
        tensor = {"name": self._name}
        if self._parameters:
            tensor["parameters"] = dict(self._parameters)
        return tensor
