"""HTTP-protocol ``InferResult``.

Parity target: reference ``tritonclient/http/_infer_result.py`` (242 LoC):
decompress body (:71-76), parse header JSON, slice binary segments by
cumulative ``binary_data_size`` (:95-106), ``as_numpy`` deserializing
BYTES/BF16 (:157-210).
"""

from __future__ import annotations

import gzip
import json
import zlib
from typing import Optional

import numpy as np

from ..utils import (
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    raise_error,
    triton_to_np_dtype,
)


class InferResult:
    def __init__(self, response_body: bytes, verbose: bool = False,
                 header_length: Optional[int] = None,
                 content_encoding: Optional[str] = None,
                 headers=None):
        """Parse a v2 infer response body (optionally compressed).
        ``headers`` carries the HTTP response headers (trace-correlation:
        the server echoes ``triton-request-id`` there)."""
        self._headers = ({k.lower(): v for k, v in dict(headers).items()}
                         if headers else {})
        if content_encoding == "gzip":
            response_body = gzip.decompress(response_body)
        elif content_encoding == "deflate":
            response_body = zlib.decompress(response_body)

        self._buffer_map = {}
        if header_length is None:
            content = response_body
            self._result = json.loads(content)
        else:
            body_view = memoryview(response_body)
            # json.loads does not take memoryviews; the header slice is
            # small and must be parsed anyway
            self._result = json.loads(response_body[:header_length])
            offset = header_length
            for output in self._result.get("outputs", []):
                params = output.get("parameters", {})
                size = params.get("binary_data_size")
                if size is not None:
                    # zero-copy: memoryview slices over the response body;
                    # as_numpy wraps them with np.frombuffer (still no
                    # copy), keeping the one response buffer as backing
                    # store for every fixed-dtype output
                    self._buffer_map[output["name"]] = \
                        body_view[offset:offset + size]
                    offset += size
        if verbose:
            print(self._result)

    @classmethod
    def from_response_body(cls, response_body, verbose=False, header_length=None,
                           content_encoding=None):
        """Static constructor matching the reference's store-and-forward path
        (parse_response_body, http/_client.py:1300-1329)."""
        return cls(response_body, verbose, header_length, content_encoding)

    def as_numpy(self, name: str) -> Optional[np.ndarray]:
        """Decode the named output to numpy; None if absent.  BYTES → object
        array of bytes; BF16 → native bfloat16 array (TPU-first; the
        reference shims through float32)."""
        for output in self._result.get("outputs", []):
            if output["name"] != name:
                continue
            shape = [int(s) for s in output["shape"]]
            datatype = output["datatype"]
            if name in self._buffer_map:
                buf = self._buffer_map[name]
                if datatype == "BYTES":
                    return deserialize_bytes_tensor(buf).reshape(shape)
                if datatype == "BF16":
                    return deserialize_bf16_tensor(buf).reshape(shape)
                return np.frombuffer(buf, dtype=triton_to_np_dtype(datatype)).reshape(shape)
            if "data" not in output:
                return None  # shm output: data lives in the region
            data = output["data"]
            if datatype == "BYTES":
                flat = np.array(
                    [x.encode("utf-8") if isinstance(x, str) else bytes(x) for x in data],
                    dtype=np.object_,
                )
                return flat.reshape(shape)
            return np.array(data, dtype=triton_to_np_dtype(datatype)).reshape(shape)
        return None

    def get_output(self, name: str) -> Optional[dict]:
        """The output's JSON dict, or None (reference :212-231)."""
        for output in self._result.get("outputs", []):
            if output["name"] == name:
                return output
        return None

    def get_response(self) -> dict:
        """The full response JSON dict (reference :233-241)."""
        return self._result

    def get_headers(self) -> dict:
        """HTTP response headers (lowercased keys); empty for results parsed
        from a stored body.  ``triton-request-id`` holds the echoed
        trace-correlation id."""
        return self._headers
