"""HTTP-protocol ``InferInput``.

Parity target: reference ``tritonclient/http/_infer_input.py`` (272 LoC):
JSON-or-binary encoding (binary default), UTF-8 validation on the JSON BYTES
path (:166-196), shared-memory params mutually exclusive with data
(:216-242).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..utils import (
    as_wire_memoryview,
    np_to_triton_dtype,
    raise_error,
    serialize_bf16_tensor,
    serialize_byte_tensor_raw,
    wire_length,
)


class InferInput:
    """An input tensor for an inference request.

    Zero-copy contract (binary path): ``set_data_from_numpy`` stores a
    *view* over the source array for fixed-size dtypes — no byte copy
    happens until the request body is gathered.  The caller must not
    mutate the array between attaching it and the request being sent
    (ARCHITECTURE.md "Client wire fast path" has the ownership rules).
    """

    def __init__(self, name: str, shape: List[int], datatype: str):
        self._name = name
        self._shape = list(shape)
        self._datatype = datatype
        self._parameters: dict = {}
        self._data = None  # JSON path: flat python list
        # binary path: bytes, bytearray (BYTES codec buffer) or a
        # B-format memoryview over the caller's array (zero-copy)
        self._raw_data = None
        # bumped by set_shape: lets a template detect a shape change
        # with one int compare on the stamp hot path
        self._shape_epoch = 0

    def name(self) -> str:
        return self._name

    def datatype(self) -> str:
        return self._datatype

    def shape(self) -> List[int]:
        return self._shape

    def set_shape(self, shape: List[int]) -> "InferInput":
        self._shape = list(shape)
        self._shape_epoch += 1
        return self

    def set_data_from_numpy(self, input_tensor: np.ndarray, binary_data: bool = True):
        """Attach tensor data, binary (default) or JSON.

        Matches reference semantics (:94-214): shape is validated against the
        tensor, BYTES handled per representation, BF16 requires binary (the
        reference rejects JSON BF16 too — no portable JSON encoding).
        """
        if not isinstance(input_tensor, np.ndarray):
            raise_error("input_tensor must be a numpy array")
        dtype = np_to_triton_dtype(input_tensor.dtype)
        if self._datatype != dtype:
            if self._datatype == "BF16" and dtype == "FP32":
                pass  # allow f32 staging for BF16 wire dtype (truncating)
            else:
                raise_error(
                    f"got unexpected datatype {dtype} from numpy array, "
                    f"expected {self._datatype}"
                )
        valid_shape = list(input_tensor.shape) == list(self._shape)
        if not valid_shape:
            raise_error(
                f"got unexpected numpy array shape [{str(input_tensor.shape)[1:-1]}], "
                f"expected [{str(self._shape)[1:-1]}]"
            )

        self._parameters.pop("shared_memory_region", None)
        self._parameters.pop("shared_memory_byte_size", None)
        self._parameters.pop("shared_memory_offset", None)

        if not binary_data:
            if self._datatype == "BF16":
                raise_error("BF16 inputs must use binary_data=True")
            self._parameters.pop("binary_data_size", None)
            self._raw_data = None
            if self._datatype == "BYTES":
                try:
                    if input_tensor.size > 0:
                        self._data = [
                            val.item().decode("utf-8") if isinstance(val.item(), bytes) else str(val.item())
                            for val in np.nditer(input_tensor, flags=["refs_ok"], order="C")
                        ]
                    else:
                        self._data = []
                except UnicodeDecodeError:
                    raise_error(
                        f'Failed to encode "{self._name}" using UTF-8. Please use '
                        "binary_data=True, if you want to pass a byte array."
                    )
            else:
                self._data = [val.item() for val in input_tensor.flatten(order="C")]
        else:
            self._data = None
            if self._datatype == "BYTES":
                # one preallocated buffer; the body gather reads it as-is
                self._raw_data = serialize_byte_tensor_raw(input_tensor)
            elif self._datatype == "BF16":
                # uint8 view (zero-copy for native bf16 arrays)
                self._raw_data = as_wire_memoryview(
                    serialize_bf16_tensor(input_tensor))
            else:
                # zero-copy: a view over the caller's array
                self._raw_data = as_wire_memoryview(input_tensor)
            self._parameters["binary_data_size"] = wire_length(self._raw_data)
        return self

    def set_shared_memory(self, region_name: str, byte_size: int, offset: int = 0):
        """Reference the tensor data in a registered shm region (:216-242) —
        clears any inline data."""
        self._data = None
        self._raw_data = None
        self._parameters.pop("binary_data_size", None)
        self._parameters["shared_memory_region"] = region_name
        self._parameters["shared_memory_byte_size"] = byte_size
        if offset != 0:
            self._parameters["shared_memory_offset"] = offset
        return self

    # -- wire building (used by the client; reference :244-271) -----------
    def _get_tensor(self) -> dict:
        tensor = {
            "name": self._name,
            "shape": self._shape,
            "datatype": self._datatype,
        }
        if self._parameters:
            tensor["parameters"] = dict(self._parameters)
        if self._data is not None:
            tensor["data"] = self._data
        return tensor

    def _get_binary_data(self):
        """The wire payload: ``bytes``/``bytearray``/B-format
        ``memoryview`` (the body gather accepts all three), or None on
        the JSON/shm paths."""
        return self._raw_data

    def _freeze_raw(self) -> None:
        """Snapshot a zero-copy view into owned bytes.  ``async_infer``
        calls this before handing the request to its worker thread: the
        body is gathered after control returns to the caller, so the
        fast path's "don't mutate between attach and send" ownership rule
        is unsatisfiable there — the submit-time snapshot restores the
        pre-fast-path copy semantics for exactly that path."""
        if isinstance(self._raw_data, memoryview):
            self._raw_data = self._raw_data.tobytes()
