"""Synchronous HTTP/REST ``InferenceServerClient``.

Parity target: reference ``tritonclient/http/_client.py`` (1659 LoC) — same
~30-method surface and URI scheme (builders surveyed at :364-1474), same
binary-over-HTTP framing (``Inference-Header-Content-Length``), same
async_infer future semantics (:46-99, :1486-1659).

Transport re-design (TPU-VM-idiomatic, not a port): the reference rides
gevent greenlets + geventhttpclient; this client uses a ``urllib3``
connection pool (``concurrency`` pooled connections) plus a thread pool for
``async_infer`` — no monkey-patching, plays nicely with jax host threads.
"""

from __future__ import annotations

import gzip
import time
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Dict, List, Optional
from urllib.parse import quote, urlencode

import urllib3

from .._client import InferenceServerClientBase
from .._request import Request
from .._resilience import (RetryPolicy, call_with_retry,
                           deadline_exceeded_error, min_timeout,
                           normalized_status, remaining_us)
from .._telemetry import merge_trace_headers, telemetry, traceparent_on_wire
from ..utils import InferenceServerException, raise_error
from ._infer_result import InferResult
from ._template import RequestTemplate
from ._utils import get_inference_request_body, raise_if_error


class PreparedRequest:
    """Handle for the wire fast path: a compiled :class:`RequestTemplate`
    bound to a client.  ``infer()`` re-stamps only the request id, the
    deadline header and the raw tensor bytes — update data by calling
    ``set_data_from_numpy`` on the SAME ``InferInput`` objects that were
    passed to ``prepare()`` (the reuse-infer-objects idiom).  That default
    data path makes the handle single-thread: concurrent mutate+infer on
    one handle interleaves into torn requests — build one PreparedRequest
    per worker thread (the perf_analyzer session model; only the
    compiled template itself is immutable and shareable)."""

    def __init__(self, client, template: RequestTemplate):
        self._client = client
        self.template = template
        path = f"v2/models/{quote(template.model_name)}"
        if template.model_version:
            path += f"/versions/{template.model_version}"
        self.infer_path = path + "/infer"

    def infer(self, request_id="", headers=None, query_params=None,
              tenant=None, retry_policy: Optional[RetryPolicy] = None,
              deadline_s: Optional[float] = None) -> InferResult:
        """Fast-path inference — same resilience/telemetry/trace contract
        as ``client.infer`` (retries re-stamp the deadline header per
        attempt; spans still pair)."""
        client = self._client
        policy = retry_policy if retry_policy is not None \
            else client._retry_policy
        if policy is None and deadline_s is None:
            return client._infer_prepared(
                self, request_id, headers, query_params, tenant)
        return call_with_retry(
            policy,
            lambda remaining, _attempt: client._infer_prepared(
                self, request_id, headers, query_params, tenant,
                _remaining_s=remaining),
            method="infer", deadline_s=deadline_s,
            retry_meta=(self.template.model_name, "http", "infer",
                        request_id), journey=True)


class InferAsyncRequest:
    """Handle for an in-flight async_infer (reference class :46-99)."""

    def __init__(self, future: Future, verbose: bool = False):
        self._future = future
        self._verbose = verbose

    def get_result(self, block: bool = True, timeout: Optional[float] = None) -> InferResult:
        """Block (by default) until the response arrives and return the
        InferResult; raises InferenceServerException on error, with a
        "deadline exceeded" status on timeout."""
        try:
            return self._future.result(timeout=timeout if block else 0)
        except InferenceServerException:
            raise
        except (TimeoutError, FuturesTimeoutError):
            # concurrent.futures.TimeoutError is a distinct class pre-3.11
            raise InferenceServerException(
                msg="timed out waiting for inference response",
                status="StatusCode.DEADLINE_EXCEEDED") from None
        except Exception as e:
            raise_error(f"failed to obtain inference response: {e}")

    def cancel(self) -> bool:
        return self._future.cancel()


class InferenceServerClient(InferenceServerClientBase):
    """Client for the v2 protocol over HTTP/REST.

    This client is **not thread-safe for concurrent calls on one instance's
    sequence state**, but the underlying pool is; `async_infer` may be issued
    concurrently up to ``concurrency`` in-flight requests (the reference's
    contract: http/_client.py:103-108 single-stream; pooled connections
    :182-191).
    """

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        concurrency: int = 1,
        connection_timeout: float = 60.0,
        network_timeout: float = 60.0,
        max_greenlets: Optional[int] = None,  # accepted for API compat
        ssl: bool = False,
        ssl_options: Optional[dict] = None,
        ssl_context_factory=None,  # accepted for API compat
        insecure: bool = False,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        super().__init__()
        # client-level resilience default: health/metadata calls retry
        # under it unconditionally; infer honors it per its retry_infer
        # opt-in (a per-call retry_policy= overrides)
        self._retry_policy = retry_policy
        if url.startswith("http://") or url.startswith("https://"):
            raise_error("url should not include the scheme")
        self._url = url
        scheme = "https://" if ssl else "http://"
        self._parsed_url = scheme + url
        self._base_uri = self._parsed_url.rstrip("/")
        self._verbose = verbose
        self._concurrency = concurrency
        self._timeout = urllib3.Timeout(connect=connection_timeout, read=network_timeout)
        pool_kwargs: Dict[str, Any] = dict(
            num_pools=1,
            maxsize=max(concurrency, 1),
            block=False,
            timeout=self._timeout,
        )
        if ssl:
            if insecure:
                pool_kwargs["cert_reqs"] = "CERT_NONE"
                urllib3.disable_warnings()
            if ssl_options:
                for k in ("ca_certs", "cert_file", "key_file", "cert_reqs", "ssl_version"):
                    if k in ssl_options:
                        pool_kwargs[k] = ssl_options[k]
        self._pool = urllib3.PoolManager(**pool_kwargs)
        self._executor: Optional[ThreadPoolExecutor] = None

    @property
    def url(self) -> str:
        """The scheme-less ``host:port`` this client talks to — the
        endpoint label the cluster layer keys its routing counters by."""
        return self._url

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Close the client; blocks until in-flight async requests finish
        (reference :257-266)."""
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None
        self._pool.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- low-level ---------------------------------------------------------
    def _build_headers(self, headers: Optional[dict]) -> dict:
        request = Request(dict(headers) if headers else {})
        self._call_plugin(request)
        bad = [
            k
            for k in request.headers
            if k.lower() in ("transfer-encoding",)
        ]
        if bad:
            raise_error(
                f"Unsupported headers {bad}; use a different client or remove them."
            )
        return request.headers

    def _uri(self, path: str, query_params: Optional[dict]) -> str:
        uri = f"{self._base_uri}/{path}"
        if query_params:
            uri += "?" + urlencode(query_params, doseq=True)
        return uri

    def _attempt_timeout(self, timeout_s: Optional[float]) -> dict:
        """Request kwargs for one deadline-budgeted attempt: the budget
        CAPS the pool's configured connect/read timeouts (a deliberately
        short network_timeout keeps guarding each attempt) and also sets
        urllib3's ``total`` so connect + every socket read share ONE
        budget — per-read timeouts alone would let a trickling response
        stretch an attempt far past deadline_s."""
        if timeout_s is None:
            return {}
        return {"timeout": urllib3.Timeout(
            total=timeout_s,
            connect=min_timeout(self._timeout.connect_timeout, timeout_s),
            read=min_timeout(self._timeout.read_timeout, timeout_s))}

    def _get(self, path: str, headers: Optional[dict],
             query_params: Optional[dict],
             timeout_s: Optional[float] = None):
        uri = self._uri(path, query_params)
        if self._verbose:
            print(f"GET {uri}, headers {headers}")
        response = self._pool.request(
            "GET", uri, headers=self._build_headers(headers),
            **self._attempt_timeout(timeout_s))
        if self._verbose:
            print(response.status)
        return response

    def _post(
        self,
        path: str,
        body: bytes,
        headers: Optional[dict],
        query_params: Optional[dict],
        extra_headers: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ):
        uri = self._uri(path, query_params)
        hdrs = self._build_headers(headers)
        if extra_headers:
            hdrs.update(extra_headers)
        if self._verbose:
            print(f"POST {uri}, headers {hdrs}\n{body[:256]!r}")
        response = self._pool.request(
            "POST", uri, body=body, headers=hdrs, preload_content=True,
            **self._attempt_timeout(timeout_s),
        )
        if self._verbose:
            print(response.status)
        return response

    def _with_retry(self, method_kind: str, fn):
        """Run an idempotent (health/metadata) call under the client-level
        retry policy, if one is configured.  ``fn(timeout_s)`` receives the
        remaining deadline budget (None without one) so each attempt's
        transport time is capped like the gRPC clients'."""
        if self._retry_policy is None:
            return fn(None)
        return call_with_retry(
            self._retry_policy, lambda remaining, _attempt: fn(remaining),
            method=method_kind,
            retry_meta=("", "http", method_kind, ""))

    def _health_get(self, path: str, headers, query_params) -> bool:
        """One health probe under the client-level policy.  Health GETs
        normally never raise on status, which would make the 429/503
        retry gate unreachable — so under a policy those statuses are
        raised for the retry loop, and when every retry is exhausted the
        verdict degrades back to the API's no-raise boolean (False)."""
        def _call(remaining):
            response = self._get(path, headers, query_params,
                                 timeout_s=remaining)
            if self._retry_policy is not None \
                    and response.status in (429, 503):
                raise_if_error(response.status, response.data,
                               response.headers)
            return response

        try:
            response = self._with_retry("health", _call)
        except InferenceServerException as e:
            if normalized_status(e) in ("429", "503"):
                return False  # still overloaded after every retry
            raise
        return response.status == 200

    # -- health / metadata (reference :340-580) ----------------------------
    def is_server_live(self, headers=None, query_params=None) -> bool:
        return self._health_get("v2/health/live", headers, query_params)

    def is_server_ready(self, headers=None, query_params=None) -> bool:
        return self._health_get("v2/health/ready", headers, query_params)

    def is_model_ready(self, model_name, model_version="", headers=None, query_params=None) -> bool:
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        return self._health_get(f"{path}/ready", headers, query_params)

    def get_server_metadata(self, headers=None, query_params=None) -> dict:
        def _call(remaining):
            response = self._get("v2", headers, query_params,
                                 timeout_s=remaining)
            raise_if_error(response.status, response.data, response.headers)
            return response

        import json

        return json.loads(self._with_retry("metadata", _call).data)

    def get_model_metadata(
        self, model_name, model_version="", headers=None, query_params=None
    ) -> dict:
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"

        def _call(remaining):
            response = self._get(path, headers, query_params,
                                 timeout_s=remaining)
            raise_if_error(response.status, response.data, response.headers)
            return response

        import json

        return json.loads(self._with_retry("metadata", _call).data)

    def get_model_config(
        self, model_name, model_version="", headers=None, query_params=None
    ) -> dict:
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"

        def _call(remaining):
            response = self._get(f"{path}/config", headers, query_params,
                                 timeout_s=remaining)
            raise_if_error(response.status, response.data, response.headers)
            return response

        import json

        return json.loads(self._with_retry("metadata", _call).data)

    # -- repository (reference :582-707) -----------------------------------
    def get_model_repository_index(self, headers=None, query_params=None) -> list:
        response = self._post("v2/repository/index", b"", headers, query_params)
        raise_if_error(response.status, response.data)
        import json

        return json.loads(response.data)

    def load_model(
        self,
        model_name,
        headers=None,
        query_params=None,
        config: Optional[str] = None,
        files: Optional[Dict[str, bytes]] = None,
    ) -> None:
        """Request the server to load/reload a model; ``config`` is a JSON
        config override, ``files`` maps "file:<path>" to raw bytes sent
        base64'd (reference :620-671)."""
        import base64
        import json

        load_request: Dict[str, Any] = {}
        if config is not None or files:
            load_request["parameters"] = {}
        if config is not None:
            load_request["parameters"]["config"] = config
        if files:
            for path, content in files.items():
                load_request["parameters"][path] = base64.b64encode(content).decode()
        response = self._post(
            f"v2/repository/models/{quote(model_name)}/load",
            json.dumps(load_request).encode() if load_request else b"",
            headers,
            query_params,
        )
        raise_if_error(response.status, response.data)

    def unload_model(
        self, model_name, headers=None, query_params=None, unload_dependents: bool = False
    ) -> None:
        import json

        body = {"parameters": {"unload_dependents": unload_dependents}}
        response = self._post(
            f"v2/repository/models/{quote(model_name)}/unload",
            json.dumps(body).encode(),
            headers,
            query_params,
        )
        raise_if_error(response.status, response.data)

    # -- statistics / trace / logging (reference :709-943) -----------------
    def get_inference_statistics(
        self, model_name="", model_version="", headers=None, query_params=None
    ) -> dict:
        if model_name:
            path = f"v2/models/{quote(model_name)}"
            if model_version:
                path += f"/versions/{model_version}"
            path += "/stats"
        else:
            path = "v2/models/stats"
        response = self._get(path, headers, query_params)
        raise_if_error(response.status, response.data)
        import json

        return json.loads(response.data)

    def update_trace_settings(
        self, model_name=None, settings: Optional[dict] = None, headers=None, query_params=None
    ) -> dict:
        import json

        path = (
            f"v2/models/{quote(model_name)}/trace/setting" if model_name else "v2/trace/setting"
        )
        response = self._post(
            path, json.dumps(settings or {}).encode(), headers, query_params
        )
        raise_if_error(response.status, response.data)
        return json.loads(response.data)

    def get_trace_settings(self, model_name=None, headers=None, query_params=None) -> dict:
        path = (
            f"v2/models/{quote(model_name)}/trace/setting" if model_name else "v2/trace/setting"
        )
        response = self._get(path, headers, query_params)
        raise_if_error(response.status, response.data)
        import json

        return json.loads(response.data)

    def update_log_settings(self, settings: dict, headers=None, query_params=None) -> dict:
        import json

        response = self._post("v2/logging", json.dumps(settings).encode(), headers, query_params)
        raise_if_error(response.status, response.data)
        return json.loads(response.data)

    def get_log_settings(self, headers=None, query_params=None) -> dict:
        response = self._get("v2/logging", headers, query_params)
        raise_if_error(response.status, response.data)
        import json

        return json.loads(response.data)

    def get_flight_recorder(self, model_name=None, limit=0, headers=None,
                            query_params=None) -> dict:
        """The server's flight-recorder debug snapshot (always-on recent
        ring + pinned tail-latency/failure outliers with span trees)."""
        params = dict(query_params or {})
        if model_name:
            params["model"] = model_name
        if limit:
            params["limit"] = limit
        response = self._get(
            "v2/debug/flight_recorder", headers, params or None)
        raise_if_error(response.status, response.data)
        import json

        return json.loads(response.data)

    def get_device_stats(self, model_name=None, headers=None,
                         query_params=None) -> dict:
        """The server's device/scheduler observability snapshot: per-model
        duty cycle / live MFU / compile events, batcher tick aggregates,
        host<->device transfers, HBM, and SLO burn-rate state (under
        ``"slo"``)."""
        params = dict(query_params or {})
        if model_name:
            params["model"] = model_name
        response = self._get(
            "v2/debug/device_stats", headers, params or None)
        raise_if_error(response.status, response.data)
        import json

        return json.loads(response.data)

    def get_costs(self, model_name=None, headers=None,
                  query_params=None) -> dict:
        """The server's per-tenant cost-attribution ledger: device-time,
        FLOPs, generated tokens, and KV byte-seconds per (model, tenant)
        — GET /v2/debug/costs."""
        params = dict(query_params or {})
        if model_name:
            params["model"] = model_name
        response = self._get("v2/debug/costs", headers, params or None)
        raise_if_error(response.status, response.data)
        import json

        return json.loads(response.data)

    # -- shared memory (reference :945-1203) -------------------------------
    def get_system_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ) -> list:
        path = "v2/systemsharedmemory"
        if region_name:
            path += f"/region/{quote(region_name)}"
        response = self._get(f"{path}/status", headers, query_params)
        raise_if_error(response.status, response.data)
        import json

        return json.loads(response.data)

    def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, query_params=None
    ) -> None:
        import json

        body = {"key": key, "offset": offset, "byte_size": byte_size}
        response = self._post(
            f"v2/systemsharedmemory/region/{quote(name)}/register",
            json.dumps(body).encode(),
            headers,
            query_params,
        )
        raise_if_error(response.status, response.data)
        telemetry().record_shm_register("http", "system", byte_size)

    def unregister_system_shared_memory(
        self, name="", headers=None, query_params=None
    ) -> None:
        if name:
            path = f"v2/systemsharedmemory/region/{quote(name)}/unregister"
        else:
            path = "v2/systemsharedmemory/unregister"
        response = self._post(path, b"", headers, query_params)
        raise_if_error(response.status, response.data)

    def get_cuda_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ) -> list:
        path = "v2/cudasharedmemory"
        if region_name:
            path += f"/region/{quote(region_name)}"
        response = self._get(f"{path}/status", headers, query_params)
        raise_if_error(response.status, response.data)
        import json

        return json.loads(response.data)

    def register_cuda_shared_memory(
        self, name, raw_handle: bytes, device_id: int, byte_size: int,
        headers=None, query_params=None
    ) -> None:
        """Register a device-buffer region.  ``raw_handle`` is the
        base64-encodable handle from ``xla_shared_memory.get_raw_handle``
        (reference cudashm flow: :1111-1165, handle b64 at :1153)."""
        import base64
        import json

        body = {
            "raw_handle": {"b64": base64.b64encode(raw_handle).decode()},
            "device_id": device_id,
            "byte_size": byte_size,
        }
        response = self._post(
            f"v2/cudasharedmemory/region/{quote(name)}/register",
            json.dumps(body).encode(),
            headers,
            query_params,
        )
        raise_if_error(response.status, response.data)
        telemetry().record_shm_register("http", "cuda", byte_size)

    # TPU-native alias: same RPC, honest name.
    register_xla_shared_memory = register_cuda_shared_memory

    def unregister_cuda_shared_memory(self, name="", headers=None, query_params=None) -> None:
        if name:
            path = f"v2/cudasharedmemory/region/{quote(name)}/unregister"
        else:
            path = "v2/cudasharedmemory/unregister"
        response = self._post(path, b"", headers, query_params)
        raise_if_error(response.status, response.data)

    unregister_xla_shared_memory = unregister_cuda_shared_memory
    get_xla_shared_memory_status = get_cuda_shared_memory_status

    # -- inference (reference :1205-1659) ----------------------------------
    @staticmethod
    def generate_request_body(
        inputs,
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        parameters=None,
    ):
        """Build (body, json_size) for store-and-forward use (reference static
        :1218-1298)."""
        return get_inference_request_body(
            inputs, request_id, outputs, sequence_id, sequence_start, sequence_end,
            priority, timeout, parameters,
        )

    @staticmethod
    def parse_response_body(
        response_body, verbose=False, header_length=None, content_encoding=None
    ) -> InferResult:
        """Parse a stored response body (reference static :1300-1329)."""
        return InferResult.from_response_body(
            response_body, verbose, header_length, content_encoding
        )

    def _infer_request(
        self,
        model_name,
        inputs,
        model_version,
        outputs,
        request_id,
        sequence_id,
        sequence_start,
        sequence_end,
        priority,
        timeout,
        headers,
        query_params,
        request_compression_algorithm,
        response_compression_algorithm,
        parameters,
        tenant=None,
        _method="infer",
        _remaining_s=None,
    ):
        tel = telemetry()
        t_ser0 = time.monotonic_ns()
        body, json_size = get_inference_request_body(
            inputs, request_id, outputs, sequence_id, sequence_start, sequence_end,
            priority, timeout, parameters,
        )
        extra_headers = {}
        if tenant:
            # QoS identity: the server's per-tenant token bucket and the
            # tenant-labeled metrics key off this header
            extra_headers["triton-tenant"] = str(tenant)
        if request_compression_algorithm == "gzip":
            body = gzip.compress(body)
            extra_headers["Content-Encoding"] = "gzip"
        elif request_compression_algorithm == "deflate":
            body = zlib.compress(body)
            extra_headers["Content-Encoding"] = "deflate"
        if response_compression_algorithm in ("gzip", "deflate"):
            extra_headers["Accept-Encoding"] = response_compression_algorithm
        if json_size is not None:
            extra_headers["Inference-Header-Content-Length"] = str(json_size)
        # trace propagation: every inference carries a correlation id the
        # server records in trace JSON and echoes back (user-supplied
        # headers of the same name win)
        trace_headers, rid = merge_trace_headers(headers, request_id)
        extra_headers.update(trace_headers)
        if _remaining_s is not None:
            # remaining deadline budget, restamped per attempt: the server
            # drops the request (zero compute) once this expires
            extra_headers["triton-timeout-us"] = str(
                remaining_us(_remaining_s))
        t_ser1 = time.monotonic_ns()  # body built + compressed = SERIALIZE

        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        path += "/infer"
        t0 = time.perf_counter()
        try:
            response = self._post(path, body, headers, query_params,
                                  extra_headers, timeout_s=_remaining_s)
            raise_if_error(response.status, response.data, response.headers)
        except Exception:
            tel.record_request(
                model_name, "http", _method, time.perf_counter() - t0,
                ok=False, request_bytes=len(body),
                request_id=rid)
            if tel.tracing_enabled:
                # failed attempts stay on the journey's trace: without this
                # record the journeys report would undercount attempts and
                # miss the replicas the failures actually landed on
                tel.record_infer_spans(
                    rid, model_name, "http", _method, t_ser0, t_ser1,
                    time.monotonic_ns(),
                    traceparent=traceparent_on_wire(headers, trace_headers),
                    ok=False)
            raise
        t_net1 = time.monotonic_ns()
        tel.record_request(
            model_name, "http", _method, time.perf_counter() - t0,
            ok=True, request_bytes=len(body),
            response_bytes=len(response.data),
            request_id=rid)
        header_length = response.headers.get("Inference-Header-Content-Length")
        # urllib3 decodes gzip/deflate transparently, so no content_encoding.
        result = InferResult(
            response.data,
            self._verbose,
            int(header_length) if header_length is not None else None,
            None,
            headers=response.headers,
        )
        if tel.tracing_enabled:
            tel.record_infer_spans(
                rid, model_name, "http", _method, t_ser0, t_ser1, t_net1,
                traceparent=traceparent_on_wire(headers, trace_headers))
        return result

    # -- wire fast path ----------------------------------------------------
    def prepare(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        priority=0,
        timeout=None,
        parameters=None,
    ) -> PreparedRequest:
        """Compile the invariant request skeleton once (see
        ``_template.py``); the returned handle's ``infer()`` re-stamps only
        id/deadline/tensor bytes.  ``inputs`` must already carry binary
        data; changing their shape/dtype/outputs/params afterwards
        invalidates the template (``stamp`` raises — re-``prepare``)."""
        return PreparedRequest(self, RequestTemplate(
            model_name, inputs, outputs, model_version, priority, timeout,
            parameters))

    def _infer_prepared(self, prep: PreparedRequest, request_id, headers,
                        query_params, tenant, _method="infer",
                        _remaining_s=None, raws=None, _sink=None):
        """One stamped-request round trip.  With ``_sink`` (a list), the
        telemetry record is deferred to the caller's per-flight batch
        (``infer_many``): the outcome tuple is appended instead — counters
        still count per request, the lock is taken once per flight."""
        tel = telemetry()
        t_ser0 = time.monotonic_ns()
        body, json_size = prep.template.stamp(request_id, raws)
        extra_headers = {}
        if tenant:
            extra_headers["triton-tenant"] = str(tenant)
        if json_size is not None:
            extra_headers["Inference-Header-Content-Length"] = str(json_size)
        trace_headers, rid = merge_trace_headers(headers, request_id)
        extra_headers.update(trace_headers)
        if _remaining_s is not None:
            extra_headers["triton-timeout-us"] = str(
                remaining_us(_remaining_s))
        t_ser1 = time.monotonic_ns()
        t0 = time.perf_counter()
        try:
            response = self._post(prep.infer_path, body, headers,
                                  query_params, extra_headers,
                                  timeout_s=_remaining_s)
            raise_if_error(response.status, response.data, response.headers)
        except Exception:
            if _sink is not None:
                _sink.append((False, time.perf_counter() - t0, len(body),
                              0, rid))
            else:
                tel.record_request(
                    prep.template.model_name, "http", _method,
                    time.perf_counter() - t0, ok=False,
                    request_bytes=len(body), request_id=rid)
                if tel.tracing_enabled:
                    tel.record_infer_spans(
                        rid, prep.template.model_name, "http", _method,
                        t_ser0, t_ser1, time.monotonic_ns(),
                        traceparent=traceparent_on_wire(
                            headers, trace_headers),
                        ok=False)
            raise
        t_net1 = time.monotonic_ns()
        if _sink is not None:
            _sink.append((True, time.perf_counter() - t0, len(body),
                          len(response.data), rid))
        else:
            tel.record_request(
                prep.template.model_name, "http", _method,
                time.perf_counter() - t0, ok=True, request_bytes=len(body),
                response_bytes=len(response.data), request_id=rid)
        header_length = response.headers.get("Inference-Header-Content-Length")
        result = InferResult(
            response.data, self._verbose,
            int(header_length) if header_length is not None else None,
            None, headers=response.headers)
        if tel.tracing_enabled:
            tel.record_infer_spans(
                rid, prep.template.model_name, "http", _method,
                t_ser0, t_ser1, t_net1,
                traceparent=traceparent_on_wire(headers, trace_headers))
        return result

    def infer_many(
        self,
        model_name,
        requests,
        model_version="",
        outputs=None,
        priority=0,
        timeout=None,
        parameters=None,
        request_ids=None,
        headers=None,
        query_params=None,
        tenant: Optional[str] = None,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
    ) -> List[InferResult]:
        """Batch submit: run every item in ``requests`` (each a list of
        data-carrying ``InferInput``, all matching the first item's specs)
        through ONE compiled template and ONE retry/deadline/telemetry
        envelope.  Results keep submission order and equal N sequential
        ``infer`` calls; telemetry counters still count per request (one
        locked batch record per flight), and a mid-batch retry resumes at
        the failed item instead of replaying completed ones."""
        items = list(requests)
        if not items:
            return []
        template = RequestTemplate(
            model_name, items[0], outputs, model_version, priority, timeout,
            parameters)
        prep = PreparedRequest(self, template)
        raws_list = [template.raws_for(item) for item in items]
        ids = list(request_ids) if request_ids else [""] * len(items)
        if len(ids) != len(items):
            raise_error("request_ids length must match requests")
        results: List[Optional[InferResult]] = [None] * len(items)
        next_idx = [0]
        tel = telemetry()

        def flight(remaining, _attempt):
            # ONE deadline for the whole flight: re-derived before every
            # item, so a slow batch raises instead of granting each item
            # the full remaining budget (N-fold overrun)
            deadline = (time.monotonic() + remaining
                        if remaining is not None else None)
            sink: list = []
            try:
                while next_idx[0] < len(items):
                    i = next_idx[0]
                    rem_i = None
                    if deadline is not None:
                        rem_i = deadline - time.monotonic()
                        if rem_i <= 0:
                            raise deadline_exceeded_error()
                    results[i] = self._infer_prepared(
                        prep, ids[i], headers, query_params, tenant,
                        _remaining_s=rem_i, raws=raws_list[i],
                        _sink=sink)
                    next_idx[0] += 1
            finally:
                # one lock round-trip per flight; per-request counts
                tel.record_request_batch(model_name, "http", "infer", sink)
            return results

        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        if policy is None and deadline_s is None:
            return flight(None, 1)
        return call_with_retry(
            policy, flight, method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, "http", "infer", ""))

    def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> InferResult:
        """Run a synchronous inference (reference :1331-1484).

        ``retry_policy`` (or the client-level one) retries retryable
        failures when ``retry_infer`` is opted in; ``deadline_s`` caps
        total wall-clock across attempts and propagates the remaining
        budget to the server via the ``triton-timeout-us`` header.
        ``priority`` (0 = highest) and ``tenant`` are the QoS identity —
        stamped per attempt, so retries re-carry them."""
        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        if policy is None and deadline_s is None:
            return self._infer_request(
                model_name, inputs, model_version, outputs, request_id,
                sequence_id, sequence_start, sequence_end, priority, timeout,
                headers, query_params, request_compression_algorithm,
                response_compression_algorithm, parameters, tenant,
            )
        return call_with_retry(
            policy,
            lambda remaining, _attempt: self._infer_request(
                model_name, inputs, model_version, outputs, request_id,
                sequence_id, sequence_start, sequence_end, priority, timeout,
                headers, query_params, request_compression_algorithm,
                response_compression_algorithm, parameters, tenant,
                _remaining_s=remaining,
            ),
            method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, "http", "infer", request_id),
            journey=True)

    def async_infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> InferAsyncRequest:
        """Submit an inference to the client's worker pool and return a
        handle (reference :1486-1659; greenlet pool → thread pool here).
        The resilience contract matches ``infer`` — retries/deadline run
        on the worker thread, invisible to the returned handle."""
        # the body is gathered on the worker thread AFTER this returns, so
        # zero-copy views over caller arrays must be snapshotted now — a
        # caller mutating its array post-submit would otherwise tear the
        # in-flight payload (pre-fast-path attach-time-copy semantics)
        for inp in inputs:
            inp._freeze_raw()
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=self._concurrency, thread_name_prefix="tc-tpu-http"
            )

        def _task():
            policy = retry_policy if retry_policy is not None \
                else self._retry_policy
            if policy is None and deadline_s is None:
                return self._infer_request(
                    model_name, inputs, model_version, outputs, request_id,
                    sequence_id, sequence_start, sequence_end, priority,
                    timeout, headers, query_params,
                    request_compression_algorithm,
                    response_compression_algorithm, parameters, tenant,
                    _method="async_infer",
                )
            return call_with_retry(
                policy,
                lambda remaining, _attempt: self._infer_request(
                    model_name, inputs, model_version, outputs, request_id,
                    sequence_id, sequence_start, sequence_end, priority,
                    timeout, headers, query_params,
                    request_compression_algorithm,
                    response_compression_algorithm, parameters, tenant,
                    _method="async_infer", _remaining_s=remaining,
                ),
                method="infer", deadline_s=deadline_s,
                retry_meta=(model_name, "http", "async_infer", request_id),
                journey=True)

        future = self._executor.submit(_task)
        return InferAsyncRequest(future, self._verbose)
