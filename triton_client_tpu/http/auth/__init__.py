"""Auth plugins for the HTTP client (reference ``tritonclient/http/auth``)."""

from ..._auth import BasicAuth

__all__ = ["BasicAuth"]
