"""HTTP/REST client for the v2 inference protocol.

Mirrors the reference's ``tritonclient.http`` package surface."""

from .._auth import BasicAuth  # noqa: F401 (re-export parity)
from ._client import (InferAsyncRequest, InferenceServerClient,
                      PreparedRequest)
from ._infer_input import InferInput
from ._infer_result import InferResult
from ._requested_output import InferRequestedOutput

__all__ = [
    "InferenceServerClient",
    "InferAsyncRequest",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "PreparedRequest",
]
