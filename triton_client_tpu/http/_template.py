"""Pre-serialized HTTP request templates — the client wire fast path.

The slow path rebuilds the whole v2 JSON header per ``infer()``: tensor
dicts, parameter dicts, ``json.dumps``, plus a per-input ``bytes``
concatenation.  For the perf-tool workloads (same model, same tensor specs,
thousands of calls) everything but the request id, the deadline header and
the raw tensor bytes is invariant — so :class:`RequestTemplate` serializes
the header ONCE and splits it into literal byte segments around the
variable slots:

* the optional ``"id": "...", `` chunk (omitted when no request id, exactly
  like the slow path),
* one ``binary_data_size`` integer per BYTES input (their payload length
  varies per call; fixed-size dtypes freeze their size and stamp-time
  validates it).

Compilation runs the REAL slow-path builder (``build_infer_request_dict`` +
``json.dumps``) with sentinel values and splits its output, so a stamped
request is byte-identical to the slow path by construction — pinned by
``tests/test_wire_fastpath.py``'s equality matrix.

What invalidates a template: changing an input's shape/dtype/name set, the
requested outputs, priority/timeout/parameters, or switching an input
between binary/JSON/shm representation.  ``stamp()`` cheaply re-validates
the frozen sizes each call and raises rather than emit a corrupt body;
callers then re-``prepare()``.

Thread-safety: a template is immutable after compile; ``stamp()`` builds a
fresh parts list per call, so one template may be shared across threads and
asyncio tasks.
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

from ..utils import raise_error, wire_length
from ._utils import build_infer_request_dict

__all__ = ["RequestTemplate"]

#: Improbable literals the compiler plants, then locates, in the dumped
#: header.  The int base is re-derived on collision (shape dims could in
#: principle collide), the id string never legitimately appears.
_SENTINEL_ID = "tmpl-rid-9f3a71c5e2d04b88"
_SENTINEL_INT_BASE = 9_090_909_090_001


class RequestTemplate:
    """Compiled invariant skeleton of one (model, inputs-spec, outputs,
    params) request shape.  Build via ``client.prepare(...)``."""

    def __init__(self, model_name: str, inputs, outputs=None,
                 model_version: str = "", priority: int = 0,
                 timeout: Optional[int] = None, parameters=None):
        self.model_name = model_name
        self.model_version = model_version
        self._inputs = list(inputs)
        self._outputs = list(outputs) if outputs else None
        self._priority = priority
        self._timeout = timeout
        self._parameters = dict(parameters) if parameters else None
        # (input index, frozen size or None-for-BYTES-slot) in input order
        self._binary_idx: List[int] = []
        self._frozen_sizes: List[Optional[int]] = []
        # shm/no-data inputs are header-only: their parameters (region
        # name/size/offset) are FROZEN into the compiled header, so their
        # compile-time state is snapshotted and re-validated every stamp —
        # a representation or region switch after prepare() must raise,
        # never silently send the stale header
        self._static_inputs: List[Tuple[int, dict]] = []
        # requested outputs are header-only too (their shm routing is
        # compiled in): snapshot and re-validate like static inputs, so
        # an output shm rebind after prepare() raises instead of
        # silently routing results to the stale region
        self._frozen_outputs: List[dict] = [
            dict(o._parameters) for o in (self._outputs or [])]
        # the compiled header also freezes every input's SHAPE; sizes
        # alone can't catch a same-byte-count reshape (or any BYTES
        # reshape), so shapes are re-validated per stamp — one int
        # (epoch) compare on the hot path, full compare only on change
        self._frozen_shapes: List[List[int]] = []
        self._frozen_epochs: List[int] = []
        for i, inp in enumerate(self._inputs):
            self._frozen_epochs.append(inp._shape_epoch)
            raw = inp._get_binary_data()
            if inp._data is not None:
                raise_error(
                    "RequestTemplate requires binary inputs; "
                    f"input {inp.name()!r} carries JSON data")
            self._frozen_shapes.append(list(inp.shape()))
            if raw is None:
                self._static_inputs.append((i, dict(inp._parameters)))
                continue
            self._binary_idx.append(i)
            self._frozen_sizes.append(
                None if inp.datatype() == "BYTES" else wire_length(raw))
        self._segments = self._compile()

    # -- compile -----------------------------------------------------------
    def _compile(self) -> List[Tuple[str, object]]:
        """Dump the header with sentinel values and split it into
        ``("lit", bytes) / ("id", None) / ("bsize", slot_index)`` ops."""
        bytes_slots = [i for i, inp_i in enumerate(self._binary_idx)
                       if self._frozen_sizes[i] is None]
        base = _SENTINEL_INT_BASE
        for _attempt in range(16):
            sentinels = {s: base + 7 * s for s in bytes_slots}
            saved = {}
            for s, val in sentinels.items():
                inp = self._inputs[self._binary_idx[s]]
                saved[s] = inp._parameters.get("binary_data_size")
                inp._parameters["binary_data_size"] = val
            try:
                header = json.dumps(build_infer_request_dict(
                    self._inputs, _SENTINEL_ID, self._outputs, 0, False,
                    False, self._priority, self._timeout, self._parameters))
            finally:
                for s, old in saved.items():
                    inp = self._inputs[self._binary_idx[s]]
                    if old is None:
                        inp._parameters.pop("binary_data_size", None)
                    else:
                        inp._parameters["binary_data_size"] = old
            marks = [(f'"id": "{_SENTINEL_ID}", ', "id", None)]
            marks += [(str(val), "bsize", s) for s, val in sentinels.items()]
            if all(header.count(m) == 1 for m, _k, _s in marks):
                return self._split(header.encode(),
                                   [(m.encode(), k, s) for m, k, s in marks])
            base += 1_010_101  # a real value collided; shift and re-plant
        raise_error("could not compile request template "
                    "(sentinel collision)")  # pragma: no cover - 16 shifts

    @staticmethod
    def _split(header: bytes, marks) -> List[Tuple[str, object]]:
        # order marks by position, then cut literals between them
        placed = sorted((header.index(m), m, kind, slot)
                        for m, kind, slot in marks)
        ops: List[Tuple[str, object]] = []
        pos = 0
        for at, m, kind, slot in placed:
            if at > pos:
                ops.append(("lit", header[pos:at]))
            ops.append((kind, slot))
            pos = at + len(m)
        if pos < len(header):
            ops.append(("lit", header[pos:]))
        return ops

    # -- stamp -------------------------------------------------------------
    def stamp(self, request_id: str = "",
              raws=None) -> Tuple[bytes, Optional[int]]:
        """Re-stamp the variable fields and gather the body.

        ``raws`` overrides the tensor payloads (``infer_many`` stamps other
        requests' data through one template); default is the bound inputs'
        current data.  Returns (body, json_size) byte-identical to the
        slow path for the same arguments.
        """
        if raws is None:
            self._check_static(self._inputs)
            self._check_shapes(self._inputs)
            raws = []
            for i in self._binary_idx:
                raw = self._inputs[i]._get_binary_data()
                if raw is None:
                    raise_error(
                        "template invalidated: input "
                        f"{self._inputs[i].name()!r} no longer carries "
                        "binary data (representation changed after "
                        "prepare — re-prepare)")
                raws.append(raw)
        elif len(raws) != len(self._binary_idx):
            raise_error(
                f"template expects {len(self._binary_idx)} tensor "
                f"payloads, got {len(raws)}")
        sizes = [len(r) for r in raws]
        for slot, frozen in enumerate(self._frozen_sizes):
            if frozen is not None and sizes[slot] != frozen:
                raise_error(
                    "template invalidated: input "
                    f"{self._inputs[self._binary_idx[slot]].name()!r} "
                    f"payload is {sizes[slot]} bytes, template froze "
                    f"{frozen} (re-prepare after a shape change)")
        parts: List[bytes] = []
        for kind, val in self._segments:
            if kind == "lit":
                parts.append(val)
            elif kind == "id":
                if request_id:
                    parts.append(b'"id": ' + json.dumps(request_id).encode()
                                 + b", ")
            else:  # bsize
                parts.append(str(sizes[val]).encode())
        json_size = sum(len(p) for p in parts)
        if sum(sizes):
            parts.extend(raws)
            # tpu-lint: disable=WIRE-COPY the single required gather into the wire body
            return b"".join(parts), json_size
        # tpu-lint: disable=WIRE-COPY header-only join, no tensor payload
        return b"".join(parts), None

    def _check_shapes(self, inputs) -> None:
        """The header declares the compile-time shapes — a post-prepare
        ``set_shape`` (even byte-size-preserving) must raise, never send
        the stale declaration.  Hot path: one epoch int compare per
        input; the full shape compare runs only when an epoch moved
        (re-synced if the shape round-tripped back)."""
        for i, epoch in enumerate(self._frozen_epochs):
            inp = inputs[i]
            if inp._shape_epoch != epoch:
                if inp._shape != self._frozen_shapes[i]:
                    raise_error(
                        f"template invalidated: input {inp.name()!r} "
                        f"shape changed to {list(inp.shape())} after "
                        f"prepare froze {self._frozen_shapes[i]} "
                        "(re-prepare)")
                self._frozen_epochs[i] = inp._shape_epoch

    def _check_static(self, inputs) -> None:
        """Header-only (shm/no-data) inputs are frozen into the compiled
        header — the given request's state must still match it exactly.
        Requested outputs are validated the same way (their parameters
        are header-only by nature)."""
        for i, frozen in self._static_inputs:
            inp = inputs[i]
            if inp._get_binary_data() is not None \
                    or inp._data is not None \
                    or inp._parameters != frozen:
                raise_error(
                    f"template invalidated: input {inp.name()!r} changed "
                    "representation or shm parameters after prepare (its "
                    "header fields are compiled in — re-prepare)")
        for o, frozen in zip(self._outputs or [], self._frozen_outputs):
            if o._parameters != frozen:
                raise_error(
                    f"template invalidated: output {o.name()!r} "
                    "parameters changed after prepare (its header fields "
                    "are compiled in — re-prepare)")

    def _check_spec(self, tpl_inp, inp) -> None:
        if inp.name() != tpl_inp.name() \
                or inp.datatype() != tpl_inp.datatype() \
                or list(inp.shape()) != list(tpl_inp.shape()):
            raise_error(
                f"infer_many item input {inp.name()!r} does not match "
                "the template spec (name/dtype/shape must be identical; "
                "re-prepare for a new shape)")

    def raws_for(self, inputs) -> List[object]:
        """Extract (and spec-validate) another request's payloads in this
        template's slot order — the ``infer_many`` per-item path.  Every
        input is validated: payload slots for spec+data, header-only
        (shm) inputs against the frozen header state, so an item whose
        shm region differs from the template's cannot silently ride the
        compiled one."""
        if len(inputs) != len(self._inputs):
            raise_error("infer_many item does not match the template's "
                        f"input count ({len(inputs)} != "
                        f"{len(self._inputs)})")
        for i, _frozen in self._static_inputs:
            self._check_spec(self._inputs[i], inputs[i])
        self._check_static(inputs)
        raws = []
        for slot, i in enumerate(self._binary_idx):
            tpl_inp, inp = self._inputs[i], inputs[i]
            self._check_spec(tpl_inp, inp)
            raw = inp._get_binary_data()
            if raw is None:
                raise_error(
                    f"infer_many item input {inp.name()!r} has no binary "
                    "data attached")
            raws.append(raw)
        return raws
