"""genai-perf-equivalent: LLM generation profiler over the sequence-stream protocol.

The reference repo carries the genai-perf tool only as a relocated-docs stub
(/root/reference/src/c++/perf_analyzer/genai-perf/README.md), so — like
``perf_analyzer.py`` — this is designed from the public CLI contract rather
than ported: profile a generation model at fixed concurrency and report the
LLM-serving metric set:

- **TTFT** (time to first token): prefill request → first token callback
- **ITL** (inter-token latency): gap between consecutive token callbacks
- **request latency**: prefill sent → last token received
- **output token throughput**: aggregate generated tokens/sec
- **request throughput**: completed generations/sec

Targets models speaking this framework's KV-cache decode contract
(``llama_decode``: TOKENS prompt window with ``sequence_start``, then one
fed-back token per step over a gRPC bidi stream — see
``examples/simple_grpc_decode_client.py``), which is the TPU-native analog
of the decoupled-LLM endpoints genai-perf drives.

Usage:
    python -m triton_client_tpu.genai_perf -m llama_decode -u localhost:8001 \
        --concurrency 4 --output-tokens 32 --num-requests 16 \
        --profile-export-file profile.json
"""

from __future__ import annotations

import argparse
import json
import queue
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


@dataclass
class _GenStats:
    """Per-request generation timings (all seconds)."""

    ttft: List[float] = field(default_factory=list)
    itl: List[float] = field(default_factory=list)
    #: per-request STEADY inter-token latency: (last - first token arrival)
    #: / (tokens - 1).  The server enqueues the decode chain with
    #: prefetched readbacks, so individual client-side gaps arrive in
    #: bursts (several frames land together behind a device drain) and the
    #: raw-gap p50 under-reads the true cadence; the window endpoints are
    #: burst-insensitive, making this the honest per-token rate.
    itl_steady: List[float] = field(default_factory=list)
    request_latency: List[float] = field(default_factory=list)
    tokens_out: int = 0
    requests: int = 0
    errors: int = 0
    first_error: Optional[str] = None

    def merge(self, other: "_GenStats") -> None:
        self.ttft.extend(other.ttft)
        self.itl.extend(other.itl)
        self.itl_steady.extend(other.itl_steady)
        self.request_latency.extend(other.request_latency)
        self.tokens_out += other.tokens_out
        self.requests += other.requests
        self.errors += other.errors
        if self.first_error is None:
            self.first_error = other.first_error


def _percentiles(values: List[float]) -> Dict[str, float]:
    if not values:
        return {}
    arr = np.asarray(values) * 1e3  # → ms
    return {
        "avg": float(arr.mean()),
        "min": float(arr.min()),
        "max": float(arr.max()),
        "p50": float(np.percentile(arr, 50)),
        "p90": float(np.percentile(arr, 90)),
        "p99": float(np.percentile(arr, 99)),
    }


def _prompt_window(prompt_len: int, rng: np.random.Generator) -> np.ndarray:
    # printable-byte tokens, right-aligned in the window like the
    # llama_preprocess tokenizer
    window = np.zeros(prompt_len, np.int32)
    n = max(1, prompt_len // 2)
    window[prompt_len - n:] = rng.integers(32, 127, n, dtype=np.int32)
    return window


def _resolve_decode_contract(client, model_name: str, model_version: str,
                             prompt_tokens: Optional[int] = None):
    md = client.get_model_metadata(model_name, model_version, as_json=True)
    cfg = client.get_model_config(model_name, model_version, as_json=True)
    if "config" in cfg:
        cfg = cfg["config"]
    inp = md["inputs"][0]
    token_output = None
    for o in md["outputs"]:
        if o["datatype"] == "INT32":
            token_output = o["name"]
            break
    if token_output is None:
        raise RuntimeError(
            f"model '{model_name}' has no INT32 output to feed back as the "
            "next token — not a decode-contract model")
    # Window size: explicit flag > advertised config parameter > fixed
    # metadata dims (dynamic -1 dims excluded).
    if prompt_tokens is None:
        advertised = (cfg.get("parameters") or {}).get("prompt_tokens", {})
        if advertised.get("string_value"):
            prompt_tokens = int(advertised["string_value"])
    if prompt_tokens is None:
        fixed = [int(s) for s in inp["shape"] if int(s) > 0]
        if not fixed:
            raise RuntimeError(
                f"model '{model_name}' has a fully dynamic prompt input and "
                "advertises no 'prompt_tokens' parameter — pass "
                "--prompt-tokens")
        prompt_tokens = int(np.prod(fixed))
    return inp["name"], inp["datatype"], prompt_tokens, token_output


def _worker(url, model_name, input_name, prompt_len, token_output,
            output_tokens, n_requests, worker_id, stats: _GenStats,
            barrier: threading.Barrier, stream_timeout: float) -> None:
    import triton_client_tpu.grpc as grpcclient

    rng = np.random.default_rng(worker_id)
    local = _GenStats()
    try:
        with grpcclient.InferenceServerClient(url) as client:
            results: "queue.Queue" = queue.Queue()
            client.start_stream(
                callback=lambda result, error: results.put((result, error)))
            barrier.wait(timeout=60)
            # wire fast path (reuse-infer-objects): ONE prompt input and
            # ONE feedback-token input per worker, re-stamped with
            # set_data_from_numpy each use — the per-step InferInput
            # construction was pure decode-loop overhead
            inp = grpcclient.InferInput(input_name, [prompt_len], "INT32")
            nxt = grpcclient.InferInput(input_name, [1], "INT32")
            for req in range(n_requests):
                seq_id = worker_id * 1_000_000 + req + 1
                window = _prompt_window(prompt_len, rng)
                inp.set_data_from_numpy(window)
                t_start = time.perf_counter()
                client.async_stream_infer(
                    model_name, [inp], sequence_id=seq_id,
                    sequence_start=True)
                t_prev = None
                ok = True
                for step in range(output_tokens):
                    res, err = results.get(timeout=stream_timeout)
                    t_now = time.perf_counter()
                    if err is not None:
                        local.errors += 1
                        if local.first_error is None:
                            local.first_error = str(err)
                        ok = False
                        break
                    if step == 0:
                        local.ttft.append(t_now - t_start)
                    else:
                        local.itl.append(t_now - t_prev)
                    t_prev = t_now
                    local.tokens_out += 1
                    tok = np.asarray(res.as_numpy(token_output)).astype(
                        np.int32).reshape(1)
                    nxt.set_data_from_numpy(tok)
                    client.async_stream_infer(
                        model_name, [nxt], sequence_id=seq_id,
                        sequence_end=(step == output_tokens - 1))
                if ok:
                    # the sequence_end step still returns one final token
                    res, err = results.get(timeout=stream_timeout)
                    t_now = time.perf_counter()
                    if err is None:
                        local.itl.append(t_now - t_prev)
                        local.tokens_out += 1
                        local.request_latency.append(t_now - t_start)
                        local.requests += 1
                        n_tok = output_tokens + 1
                        t_first = t_start + local.ttft[-1]
                        if n_tok > 1:
                            local.itl_steady.append(
                                (t_now - t_first) / (n_tok - 1))
                    else:
                        local.errors += 1
                        if local.first_error is None:
                            local.first_error = str(err)
            client.stop_stream()
    except Exception as e:  # noqa: BLE001 — worker reports, run continues
        local.errors += 1
        if local.first_error is None:
            local.first_error = str(e)
    with _MERGE_LOCK:
        stats.merge(local)


_MERGE_LOCK = threading.Lock()


def _generate_worker(http_url, model_name, prompt_text, output_tokens,
                     n_requests, worker_id, stats: _GenStats,
                     barrier: threading.Barrier,
                     stream_timeout: float) -> None:
    """SSE worker over POST /v2/models/{m}/generate_stream — the server runs
    the whole decode loop, so ITL is on-device step time, not a client
    round trip per token."""
    import json as _json
    import urllib.request

    local = _GenStats()
    try:
        barrier.wait(timeout=60)
    except threading.BrokenBarrierError:
        pass
    from ._telemetry import new_trace_context

    for req_i in range(n_requests):
        # per-request isolation: a transient failure counts one error and
        # the worker moves on to its remaining requests
        try:
            body = _json.dumps({
                "text_input": f"{prompt_text} [w{worker_id} r{req_i}]",
                "max_tokens": output_tokens,
            }).encode()
            # trace propagation, same as unary infer: the server records
            # the id/traceparent into the stream's trace record, so a
            # traced load run joins per-request client and server views
            headers = {"Content-Type": "application/json"}
            headers.update(new_trace_context())
            req = urllib.request.Request(
                f"http://{http_url}/v2/models/{model_name}/generate_stream",
                data=body, headers=headers)
            t_start = time.perf_counter()
            t_prev = None
            t_first = None
            n_frames = 0
            with urllib.request.urlopen(req, timeout=stream_timeout) as resp:
                for line in resp:
                    if not line.startswith(b"data: "):
                        continue
                    frame = _json.loads(line[len(b"data: "):])
                    t_now = time.perf_counter()
                    if "error" in frame:
                        raise RuntimeError(frame["error"])
                    if n_frames == 0:
                        local.ttft.append(t_now - t_start)
                        t_first = t_now
                    else:
                        local.itl.append(t_now - t_prev)
                    t_prev = t_now
                    n_frames += 1
                    local.tokens_out += 1
            local.request_latency.append(time.perf_counter() - t_start)
            local.requests += 1
            if n_frames > 1:
                local.itl_steady.append((t_prev - t_first) / (n_frames - 1))
        except Exception as e:  # noqa: BLE001 — worker reports, run continues
            local.errors += 1
            if local.first_error is None:
                local.first_error = str(e)
    with _MERGE_LOCK:
        stats.merge(local)


def profile_generate(http_url: str, model_name: str, concurrency: int = 1,
                     output_tokens: int = 16, num_requests: int = 8,
                     prompt_text: str = "In a hole in the ground",
                     stream_timeout: float = 600.0) -> dict:
    """Profile the generate_stream (SSE) endpoint; same metric set as
    ``profile``."""
    per_worker = max(1, num_requests // concurrency)
    stats = _GenStats()
    barrier = threading.Barrier(concurrency)
    threads = []
    t0 = time.perf_counter()
    for w in range(concurrency):
        t = threading.Thread(
            target=_generate_worker,
            args=(http_url, model_name, prompt_text, output_tokens,
                  per_worker, w + 1, stats, barrier, stream_timeout),
            daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    report = {
        "model": model_name,
        "endpoint": "generate_stream",
        "concurrency": concurrency,
        "output_tokens_per_request": output_tokens,
        "requests_completed": stats.requests,
        "errors": stats.errors,
        "wall_s": round(wall, 3),
        "time_to_first_token_ms": _percentiles(stats.ttft),
        "inter_token_latency_ms": _percentiles(stats.itl),
        # burst-corrected cadence (see _GenStats.itl_steady): prefetched
        # readbacks land in client-side bursts, so the raw-gap p50
        # under-reads — steady = per-request (last-first)/(n-1), which is
        # ~1/per-stream-tokens-per-sec by construction and self-consistent
        # with the throughput row
        "itl_steady_ms": _percentiles(stats.itl_steady),
        "request_latency_ms": _percentiles(stats.request_latency),
        "output_token_throughput_per_sec":
            round(stats.tokens_out / wall, 2) if wall > 0 else 0.0,
        "request_throughput_per_sec":
            round(stats.requests / wall, 2) if wall > 0 else 0.0,
    }
    if stats.first_error:
        report["first_error"] = stats.first_error
    return report


def profile(url: str, model_name: str, model_version: str = "",
            concurrency: int = 1, output_tokens: int = 16,
            num_requests: int = 8, stream_timeout: float = 600.0,
            prompt_tokens: Optional[int] = None) -> dict:
    """Run one profiling pass; returns the genai-perf-style metrics dict."""
    import triton_client_tpu.grpc as grpcclient

    with grpcclient.InferenceServerClient(url) as client:
        input_name, dtype, prompt_len, token_output = \
            _resolve_decode_contract(client, model_name, model_version,
                                     prompt_tokens)
        if dtype != "INT32":
            raise RuntimeError(
                f"decode contract requires an INT32 token input, got {dtype}")

    per_worker = max(1, num_requests // concurrency)
    stats = _GenStats()
    barrier = threading.Barrier(concurrency)
    threads = []
    t0 = time.perf_counter()
    for w in range(concurrency):
        t = threading.Thread(
            target=_worker,
            args=(url, model_name, input_name, prompt_len, token_output,
                  output_tokens, per_worker, w + 1, stats, barrier,
                  stream_timeout),
            daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    report = {
        "model": model_name,
        "concurrency": concurrency,
        "output_tokens_per_request": output_tokens + 1,
        "requests_completed": stats.requests,
        "errors": stats.errors,
        "wall_s": round(wall, 3),
        "time_to_first_token_ms": _percentiles(stats.ttft),
        "inter_token_latency_ms": _percentiles(stats.itl),
        # burst-corrected cadence — see profile_generate's field note
        "itl_steady_ms": _percentiles(stats.itl_steady),
        "request_latency_ms": _percentiles(stats.request_latency),
        "output_token_throughput_per_sec":
            round(stats.tokens_out / wall, 2) if wall > 0 else 0.0,
        "request_throughput_per_sec":
            round(stats.requests / wall, 2) if wall > 0 else 0.0,
    }
    if stats.first_error:
        report["first_error"] = stats.first_error
    return report


def _print_table(report: dict) -> None:
    print(f"\nModel: {report['model']}  concurrency={report['concurrency']}  "
          f"requests={report['requests_completed']}  "
          f"errors={report['errors']}")
    rows = [
        ("Time to first token (ms)", report["time_to_first_token_ms"]),
        ("Inter token latency (ms)", report["inter_token_latency_ms"]),
        ("ITL steady, de-burst (ms)", report.get("itl_steady_ms", {})),
        ("Request latency (ms)", report["request_latency_ms"]),
    ]
    hdr = f"{'Metric':<28}{'avg':>9}{'min':>9}{'max':>9}{'p50':>9}{'p90':>9}{'p99':>9}"
    print(hdr)
    print("-" * len(hdr))
    for name, p in rows:
        if not p:
            continue
        print(f"{name:<28}" + "".join(
            f"{p[k]:>9.2f}" for k in ("avg", "min", "max", "p50", "p90", "p99")))
    print(f"Output token throughput (per sec): "
          f"{report['output_token_throughput_per_sec']}")
    print(f"Request throughput (per sec): "
          f"{report['request_throughput_per_sec']}")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpu-genai-perf",
        description="LLM generation profiler (genai-perf CLI contract)")
    parser.add_argument("-m", "--model", required=True)
    parser.add_argument("-u", "--url", default="localhost:8001",
                        help="gRPC url for --endpoint stream; HTTP url for "
                        "--endpoint generate")
    parser.add_argument("--endpoint", choices=("stream", "generate"),
                        default="stream",
                        help="'stream': client closed loop over the gRPC "
                        "decode stream; 'generate': server-side loop via "
                        "POST .../generate_stream (SSE)")
    parser.add_argument("--model-version", default="")
    parser.add_argument("--concurrency", type=int, default=1)
    parser.add_argument("--output-tokens", type=int, default=16,
                        help="decode steps per request (one extra final "
                        "token arrives on sequence_end)")
    parser.add_argument("--num-requests", type=int, default=8,
                        help="total generations across all workers")
    parser.add_argument("--prompt-tokens", type=int, default=None,
                        help="prefill window size (default: the model's "
                        "advertised 'prompt_tokens' config parameter)")
    parser.add_argument("--stream-timeout", type=float, default=600.0)
    parser.add_argument("--profile-export-file", default=None,
                        help="write the full metrics dict as JSON")
    args = parser.parse_args(argv)

    try:
        if args.endpoint == "generate":
            report = profile_generate(
                args.url, args.model, concurrency=args.concurrency,
                output_tokens=args.output_tokens,
                num_requests=args.num_requests,
                stream_timeout=args.stream_timeout)
        else:
            report = profile(
                args.url, args.model, args.model_version,
                concurrency=args.concurrency,
                output_tokens=args.output_tokens,
                num_requests=args.num_requests,
                stream_timeout=args.stream_timeout,
                prompt_tokens=args.prompt_tokens)
    except Exception as e:  # noqa: BLE001 — CLI surface
        print(f"genai-perf failed: {e}", file=sys.stderr)
        return 1

    _print_table(report)
    if args.profile_export_file:
        with open(args.profile_export_file, "w") as f:
            json.dump(report, f, indent=2)
        print(f"exported: {args.profile_export_file}")
    if report["errors"] and not report["requests_completed"]:
        print(f"all requests failed: {report.get('first_error')}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
