"""Client-side cluster routing: one client surface over a server fleet.

The reproduction's clients each speak to one ``url``; the production
topology is N replicas behind every client.  This package is the layer in
between — pure client-side (no load balancer appliance, no service mesh):

* :class:`EndpointPool` — N endpoints with per-endpoint circuit breakers
  (consecutive-failure trip, half-open probe recovery) and pluggable
  balancing (:class:`RoundRobin`, :class:`LeastOutstanding`
  power-of-two-choices) plus mandatory sticky sequence routing
  (rendezvous-hashed ``sequence_id`` → endpoint, stable under membership
  change — stateful models break if a sequence migrates mid-stream).
* :class:`ClusterClient` (sync) / :class:`cluster.aio.ClusterClient`
  (asyncio) — the ``InferenceServerClient`` surface over http/grpc ×
  sync/aio, composing with :class:`~triton_client_tpu._resilience.RetryPolicy`
  so retries prefer a *different* replica, with active health probing,
  and with :class:`HedgePolicy` hedged requests (Dean & Barroso, "The
  Tail at Scale"): after the observed per-(model, endpoint) p95, issue a
  backup request to a second replica, first response wins.

Everything is observable from the client: ``nv_client_endpoint_requests_total``,
``nv_client_endpoint_state``, ``nv_client_hedges_total`` /
``nv_client_hedge_wins_total`` in the telemetry registry's Prometheus
rendering and JSON snapshot.
"""

from ._client import ClusterClient
from ._policy import (BalancingPolicy, HedgePolicy, LeastOutstanding,
                      RoundRobin, make_policy, rendezvous_rank)
from ._pool import CircuitBreaker, Endpoint, EndpointPool

__all__ = [
    "BalancingPolicy",
    "CircuitBreaker",
    "ClusterClient",
    "Endpoint",
    "EndpointPool",
    "HedgePolicy",
    "LeastOutstanding",
    "RoundRobin",
    "make_policy",
    "rendezvous_rank",
]
