"""Endpoint pool: health, circuit breaking, and routing state.

The transport-agnostic half of the cluster client.  An :class:`Endpoint`
carries one replica's routing state — in-flight count, per-model latency
histograms (they drive the hedge delay), and a :class:`CircuitBreaker`.
The :class:`EndpointPool` owns N of them plus the balancing policy and
implements ``pick()``: sticky sequence routing first (rendezvous hash —
mandatory for stateful models), then the policy over available endpoints,
honoring a per-request exclusion set so a retry prefers a replica other
than the one that just failed.

Breaker state machine (classic three-state):

    closed --[N consecutive failures]--> open
    open   --[reset_timeout_s elapsed]--> half_open (ONE trial admitted)
    half_open --[trial ok]--> closed     half_open --[trial fails]--> open

``would_allow()`` is the *non-mutating* candidate filter; ``try_admit()``
is the mutating gate called only on the endpoint actually chosen — the
split matters because admitting the half-open trial consumes a slot, and
listing candidates must never consume anything.

Every transition lands in the client telemetry registry
(``nv_client_endpoint_state``), so a fleet's health is scrapeable from the
client side without touching any server.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Union

from .._telemetry import LatencyHistogram, telemetry
from ._policy import make_policy, rendezvous_rank

__all__ = ["CircuitBreaker", "Endpoint", "EndpointPool"]


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probe recovery.

    ``record(ok)`` resolves each routed attempt (and each health probe).
    ``history`` keeps the transition chain (bounded) so tests can assert
    closed→open→half_open→closed literally.
    """

    def __init__(self, endpoint: str, failure_threshold: int = 3,
                 reset_timeout_s: float = 5.0) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.endpoint = endpoint
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._trial_in_flight = False
        self.history: List[str] = ["closed"]

    @property
    def state(self) -> str:
        return self._state

    def _transition(self, state: str) -> None:
        # lock held by caller
        if state == self._state:
            return
        self._state = state
        self.history.append(state)
        del self.history[:-64]  # bounded: a flapping endpoint must not leak
        telemetry().set_endpoint_state(self.endpoint, state)

    def would_allow(self, now: Optional[float] = None) -> bool:
        """Non-mutating: could a request be admitted right now?"""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                return now - self._opened_at >= self.reset_timeout_s
            return not self._trial_in_flight  # half_open

    def try_admit(self, now: Optional[float] = None) -> bool:
        """Mutating admission gate for the CHOSEN endpoint.  In the open
        state (cooldown elapsed) this performs the open→half_open
        transition and claims the single trial slot; a claimed slot is
        released by the next ``record()``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if now - self._opened_at < self.reset_timeout_s:
                    return False
                self._transition("half_open")
                self._trial_in_flight = True
                return True
            # half_open: one trial at a time — a thundering herd against a
            # barely-recovered replica would re-kill it
            if self._trial_in_flight:
                return False
            self._trial_in_flight = True
            return True

    def record(self, ok: bool, now: Optional[float] = None) -> None:
        """Resolve one attempt's outcome against the breaker."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._trial_in_flight = False
            if ok:
                self._consecutive_failures = 0
                if self._state != "open":
                    # an OPEN breaker closes only through the half-open
                    # trial: a success landing now was in flight before
                    # the trip (or rode the total-outage fallback), and
                    # one stale success must not flood traffic back onto
                    # a replica that just failed N times in a row
                    self._transition("closed")
                return
            self._consecutive_failures += 1
            if self._state == "half_open" \
                    or self._consecutive_failures >= self.failure_threshold:
                self._opened_at = now
                self._transition("open")


class Endpoint:
    """One replica's routing state (URL + breaker + load + latency)."""

    def __init__(self, url: str, failure_threshold: int = 3,
                 reset_timeout_s: float = 5.0) -> None:
        self.url = url
        self.breaker = CircuitBreaker(url, failure_threshold,
                                      reset_timeout_s)
        self._lock = threading.Lock()
        self._outstanding = 0
        # per-model client-observed latency — feeds the hedge delay
        # (hedge at this endpoint's observed p95 for the model)
        self._latency: Dict[str, LatencyHistogram] = {}
        telemetry().set_endpoint_state(url, "closed")

    @property
    def outstanding(self) -> int:
        return self._outstanding

    def acquire(self) -> None:
        with self._lock:
            self._outstanding += 1

    def release(self) -> None:
        with self._lock:
            self._outstanding -= 1

    def observe(self, model: str, latency_s: float) -> None:
        h = self._latency.get(model)
        if h is None:
            with self._lock:
                h = self._latency.setdefault(model, LatencyHistogram())
        h.observe(latency_s)

    def latency(self, model: str) -> Optional[LatencyHistogram]:
        return self._latency.get(model)

    def __repr__(self) -> str:  # diagnostics only
        return (f"Endpoint({self.url!r}, state={self.breaker.state}, "
                f"outstanding={self._outstanding})")


class EndpointPool:
    """N endpoints + a balancing policy + sticky sequence routing.

    ``probe_ok(url, ok)`` is how active health probing feeds back (the
    transport-owning client runs the probes; the pool is transport-free).
    A probe failure counts as a breaker failure, so a dead endpoint is
    evicted even when no user traffic is hitting it; a probe success on a
    recovering endpoint claims the half-open trial, so recovery does not
    require sacrificing a user request.
    """

    def __init__(
        self,
        urls: Union[str, Iterable[str]],
        policy: Union[str, object] = "least_outstanding",
        failure_threshold: int = 3,
        reset_timeout_s: float = 5.0,
    ) -> None:
        if isinstance(urls, str):
            urls = [u.strip() for u in urls.split(",") if u.strip()]
        urls = list(urls)
        if not urls:
            raise ValueError("EndpointPool needs at least one endpoint URL")
        if len(set(urls)) != len(urls):
            raise ValueError(f"duplicate endpoint URLs: {urls}")
        self.endpoints: List[Endpoint] = [
            Endpoint(u, failure_threshold, reset_timeout_s) for u in urls]
        self._by_url = {e.url: e for e in self.endpoints}
        self.policy = make_policy(policy)

    @property
    def urls(self) -> List[str]:
        return [e.url for e in self.endpoints]

    def endpoint(self, url: str) -> Endpoint:
        return self._by_url[url]

    def sticky_rank(self, sequence_id: int) -> List[str]:
        """The rendezvous-ranked endpoint order for one sequence (rank 0
        is the pin; later ranks are the deterministic failover order)."""
        return rendezvous_rank(sequence_id, self.urls)

    def _admit_from(self, candidates: Sequence[Endpoint]) -> \
            Optional[Endpoint]:
        """Choose with the policy, then claim admission on the choice;
        on a lost half-open race, retry among the remainder."""
        remaining = list(candidates)
        while remaining:
            chosen = (self.policy.choose(remaining) if len(remaining) > 1
                      else remaining[0])
            if chosen.breaker.try_admit():
                return chosen
            remaining.remove(chosen)
        return None

    def pick(self, sequence_id: int = 0,
             exclude: Sequence[str] = ()) -> Endpoint:
        """The endpoint for one attempt.

        Sticky first: a nonzero ``sequence_id`` routes by rendezvous rank
        (skipping evicted/excluded endpoints in rank order, so the pin
        only moves when the pinned replica itself is out).  Otherwise the
        balancing policy chooses among admittable endpoints.  Exclusion
        is best-effort: when it would empty the candidate set it is
        ignored (retrying the same replica beats failing outright), and a
        pool with every breaker open falls back to all endpoints — the
        retry path, not the router, is the last line of defense.
        """
        if sequence_id:
            ranked = self.sticky_rank(sequence_id)
            for pass_exclude in (exclude, ()):
                for url in ranked:
                    e = self._by_url[url]
                    if url in pass_exclude:
                        continue
                    if e.breaker.try_admit():
                        return e
                    if e.breaker.state == "half_open":
                        # the single trial slot is busy, but the replica is
                        # reachable enough to be on trial — a pinned
                        # sequence routes to it anyway rather than being
                        # remapped: the stickiness invariant ("a sequence
                        # moves only when ITS replica is out") outranks
                        # the trial-throttling heuristic for stateful
                        # traffic
                        return e
            return self._by_url[ranked[0]]
        for pass_exclude in (exclude, ()):
            candidates = [e for e in self.endpoints
                          if e.url not in pass_exclude
                          and e.breaker.would_allow()]
            chosen = self._admit_from(candidates)
            if chosen is not None:
                return chosen
        # total outage: route anyway and let the retry layer decide
        return (self.policy.choose(self.endpoints)
                if len(self.endpoints) > 1 else self.endpoints[0])

    def record(self, endpoint: Endpoint, ok: bool) -> None:
        """One routed attempt's outcome: breaker + per-endpoint counter."""
        endpoint.breaker.record(ok)
        telemetry().record_endpoint_request(endpoint.url, ok)

    def probe_ok(self, url: str, ok: bool) -> None:
        """Feed one active health-probe verdict back into the breaker.

        Probe *successes* only matter for recovery: on a CLOSED breaker
        they are dropped, because zeroing the consecutive-failure count
        every probe interval would keep a ready-but-failing replica (its
        health endpoint answers, its infers don't) closed forever at any
        failure rate below ~threshold/interval.  Probe failures always
        count — they are what evict a dead replica taking no traffic.
        """
        br = self._by_url[url].breaker
        if ok:
            if br.state == "closed":
                return
            if not br.try_admit():
                # open and still cooling down (or a trial is already in
                # flight): leave recovery to the state machine's clock
                return
        br.record(ok)

    def states(self) -> Dict[str, str]:
        return {e.url: e.breaker.state for e in self.endpoints}
