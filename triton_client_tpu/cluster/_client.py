"""Synchronous ``ClusterClient``: one client surface over N endpoints.

Wraps one per-endpoint ``InferenceServerClient`` (http or grpc) behind the
same method surface and adds the routing layer on top:

* every ``infer`` picks an endpoint through the :class:`EndpointPool`
  (balancing policy / sticky sequence routing / breaker eviction),
* the :class:`RetryPolicy` composes with routing: each failed attempt
  appends its endpoint to an exclusion set, so the retry lands on a
  *different* replica whenever one is available,
* **hedged requests**: after a per-(model, endpoint) delay derived from
  the observed latency quantiles (see :class:`HedgePolicy`), the request
  is issued to a second endpoint; first response wins, the loser is
  cancelled best-effort (a blocking transport call that already started
  runs to completion in its worker thread — its result is discarded, its
  outcome still feeds the breaker).  Gated on idempotency exactly like
  ``retry_infer``.
* active health probing (``health_interval_s``): a daemon thread polls
  every endpoint's readiness (the same ``/v2/health/ready`` / gRPC
  ``ServerReady`` gate the servers expose) and feeds verdicts into the
  breakers, so a dead replica is evicted — and a recovered one readmitted
  — without sacrificing user requests.

Health/metadata getters route to one available endpoint (retried across
endpoints under the client-level policy); control-plane calls
(``load_model``, shm registration, trace/log settings) **broadcast** to
every endpoint — a fleet where only one replica loaded the model is not a
fleet.  Streaming APIs are per-connection by nature and not exposed here.
"""

from __future__ import annotations

import contextvars
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import wait as _fut_wait
from functools import partial
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

from .._client import InferenceServerClientBase
from .._resilience import RetryPolicy, call_with_retry
from .._telemetry import telemetry
from ..utils import raise_error
from ._policy import HedgePolicy
from ._pool import Endpoint, EndpointPool

__all__ = ["ClusterClient"]

#: Read-only probe methods, retried across endpoints under the policy.
_HEALTH_METHODS = frozenset({
    "is_server_live", "is_server_ready", "is_model_ready",
})
#: Read-only metadata/statistics methods, routed to one endpoint.
_METADATA_METHODS = frozenset({
    "get_server_metadata", "get_model_metadata", "get_model_config",
    "get_model_repository_index", "get_inference_statistics",
    "get_trace_settings", "get_log_settings", "get_flight_recorder",
    "get_device_stats",
    "get_system_shared_memory_status", "get_cuda_shared_memory_status",
    "get_xla_shared_memory_status",
})
#: Control-plane methods applied to EVERY endpoint (first result returned).
_BROADCAST_METHODS = frozenset({
    "load_model", "unload_model",
    "update_trace_settings", "update_log_settings",
    "register_system_shared_memory", "unregister_system_shared_memory",
    "register_cuda_shared_memory", "unregister_cuda_shared_memory",
    "register_xla_shared_memory", "unregister_xla_shared_memory",
})
_STREAMING_METHODS = frozenset({
    "start_stream", "async_stream_infer", "stop_stream", "stream_infer",
})


def merge_cost_snapshots(snapshots: Iterable[dict]) -> dict:
    """Sum per-(model, tenant) cost snapshots from several replicas into
    one fleet view — counters (device_us/flops/tokens/kv_byte_seconds)
    add across processes.  Local to the client package on purpose: the
    clients must not import the server package (same shape as
    ``server/costs.py``'s merge; both sides are pinned by tests)."""
    merged: Dict[str, Dict[str, Dict[str, float]]] = {}
    enabled = False
    for snap in snapshots:
        if not isinstance(snap, dict):
            continue
        enabled = enabled or bool(snap.get("enabled"))
        models = snap.get("models")
        if not isinstance(models, dict):
            continue
        for model, tenants in models.items():
            if not isinstance(tenants, dict):
                continue
            dst_m = merged.setdefault(model, {})
            for tenant, cell in tenants.items():
                if not isinstance(cell, dict):
                    continue
                dst = dst_m.setdefault(tenant, {
                    "device_us": 0.0, "flops": 0.0, "tokens": 0,
                    "kv_byte_seconds": 0.0})
                for key in ("device_us", "flops", "kv_byte_seconds"):
                    try:
                        dst[key] = round(dst[key] + float(cell.get(key, 0.0)),
                                         6)
                    except (TypeError, ValueError):
                        pass
                try:
                    dst["tokens"] += int(cell.get("tokens", 0))
                except (TypeError, ValueError):
                    pass
    return {"enabled": enabled, "models": merged}


class ClusterClient(InferenceServerClientBase):
    """v2 client over a fleet of endpoints (sync; http or grpc).

    Parameters
    ----------
    urls:
        Endpoint list (``["h1:8000", "h2:8000"]``) or one comma-separated
        string.
    protocol:
        ``"http"`` or ``"grpc"`` — which per-endpoint client to build.
    policy:
        Balancing policy name (``round_robin`` / ``least_outstanding``)
        or a ``BalancingPolicy`` instance.  Nonzero ``sequence_id``
        requests bypass it: sticky rendezvous routing is mandatory for
        stateful models.
    retry_policy:
        Client-level :class:`RetryPolicy`; retries prefer a different
        endpoint than the failed attempt.
    hedge:
        A :class:`HedgePolicy` to enable hedged inference, or None.
    health_interval_s:
        Probe every endpoint's readiness at this cadence (None = passive
        health only, i.e. breakers fed by request outcomes).
    client_kwargs:
        Extra kwargs for each per-endpoint client constructor.
    client_factory:
        ``factory(url) -> client`` override (tests, custom transports).
    on_route:
        ``callback(endpoint_url, model_name, sequence_id)`` fired per
        routed inference attempt — routing introspection for tests and
        debugging.
    """

    def __init__(
        self,
        urls: Union[str, Iterable[str]],
        protocol: str = "http",
        policy: Union[str, object] = "least_outstanding",
        retry_policy: Optional[RetryPolicy] = None,
        hedge: Optional[HedgePolicy] = None,
        health_interval_s: Optional[float] = None,
        failure_threshold: int = 3,
        reset_timeout_s: float = 5.0,
        client_kwargs: Optional[Dict[str, Any]] = None,
        client_factory: Optional[Callable[[str], Any]] = None,
        hedge_workers: int = 32,
        on_route: Optional[Callable[[str, str, int], None]] = None,
    ):
        super().__init__()
        protocol = protocol.lower()
        if protocol not in ("http", "grpc"):
            raise_error(f"protocol must be 'http' or 'grpc', got {protocol}")
        self._protocol = protocol
        self._pool = EndpointPool(urls, policy=policy,
                                  failure_threshold=failure_threshold,
                                  reset_timeout_s=reset_timeout_s)
        self._retry_policy = retry_policy
        self._hedge = hedge
        self._hedge_workers = int(hedge_workers)
        self._on_route = on_route
        self._client_kwargs = dict(client_kwargs or {})
        self._client_factory = client_factory
        self._clients: Dict[str, Any] = {}
        self._probe_clients: Dict[str, Any] = {}
        self._clients_lock = threading.Lock()
        self._executor: Optional[ThreadPoolExecutor] = None
        self._probe_executor: Optional[ThreadPoolExecutor] = None
        self._closed = False
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        if health_interval_s is not None:
            self.start_probing(health_interval_s)

    # -- wiring ------------------------------------------------------------
    @property
    def pool(self) -> EndpointPool:
        return self._pool

    @property
    def urls(self) -> List[str]:
        return self._pool.urls

    def _make_client(self, url: str):
        if self._client_factory is not None:
            return self._client_factory(url)
        if self._protocol == "grpc":
            from .. import grpc as mod
        else:
            from .. import http as mod
        return mod.InferenceServerClient(url, **self._client_kwargs)

    def _client_for(self, ep: Endpoint):
        client = self._clients.get(ep.url)
        if client is None:
            with self._clients_lock:
                if self._closed:
                    # a call racing close() must not build a transport
                    # client into a dict nobody will ever close again
                    raise_error("client is closed")
                client = self._clients.get(ep.url)
                if client is None:
                    client = self._make_client(ep.url)
                    if self._plugin is not None:
                        client.register_plugin(self._plugin)
                    self._clients[ep.url] = client
        return client

    # -- plugin fan-out ----------------------------------------------------
    # a plugin registered on the cluster client (auth header injection is
    # the canonical case) must reach every wire request, and the requests
    # go out through the per-endpoint clients — so registration fans out
    # to existing clients and _client_for applies it to future ones
    def register_plugin(self, plugin) -> None:
        super().register_plugin(plugin)
        with self._clients_lock:
            clients = (list(self._clients.values())
                       + list(self._probe_clients.values()))
        for c in clients:
            c.register_plugin(plugin)

    def unregister_plugin(self) -> None:
        super().unregister_plugin()
        with self._clients_lock:
            clients = (list(self._clients.values())
                       + list(self._probe_clients.values()))
        for c in clients:
            if c.plugin() is not None:
                c.unregister_plugin()

    def close(self) -> None:
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=10)
            self._probe_thread = None
        # detach the executor handles UNDER the lock (they are lazily
        # created under it — an unlocked None store here races that
        # double-checked creation), but shut them down OUTSIDE it: their
        # in-flight tasks take this same lock via _client_for, so a
        # locked shutdown(wait=True) would deadlock against its own work
        with self._clients_lock:
            self._closed = True
            executor, self._executor = self._executor, None
            probe_executor, self._probe_executor = \
                self._probe_executor, None
        if executor is not None:
            executor.shutdown(wait=True)
        if probe_executor is not None:
            probe_executor.shutdown(wait=True)
        with self._clients_lock:
            clients = (list(self._clients.values())
                       + list(self._probe_clients.values()))
            self._clients = {}
            self._probe_clients = {}
        for c in clients:
            try:
                c.close()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- active health probing ---------------------------------------------
    def _probe_client_for(self, ep: Endpoint, timeout_s: float):
        """The client one health probe goes through.  gRPC takes a
        per-call timeout, so the regular client serves; the HTTP client's
        probe timeout is fixed at construction, so probes get a dedicated
        short-timeout client — a blackholed replica must cost one probe
        ``timeout_s``, not the regular client's 60 s transport default
        (which would stall the whole serial probe sweep)."""
        if self._protocol == "grpc" or self._client_factory is not None:
            return self._client_for(ep)
        client = self._probe_clients.get(ep.url)
        if client is None:
            with self._clients_lock:
                if self._closed:
                    raise_error("client is closed")
                client = self._probe_clients.get(ep.url)
                if client is None:
                    from .. import http as mod

                    kw = dict(self._client_kwargs)
                    kw["connection_timeout"] = timeout_s
                    kw["network_timeout"] = timeout_s
                    client = mod.InferenceServerClient(ep.url, **kw)
                    if self._plugin is not None:
                        client.register_plugin(self._plugin)
                    self._probe_clients[ep.url] = client
        return client

    def probe_all(self, timeout_s: float = 2.0) -> Dict[str, bool]:
        """One readiness sweep over every endpoint — probed concurrently,
        so a sweep costs ~one ``timeout_s`` no matter how many replicas
        are blackholed (serial probing would delay eviction/readmission
        linearly with dead-replica count).  Verdicts feed the breakers.
        Returns ``{url: ready}``."""
        verdicts: Dict[str, bool] = {}
        lock = threading.Lock()

        def probe_one(ep: Endpoint) -> None:
            try:
                client = self._probe_client_for(ep, timeout_s)
                if self._protocol == "grpc":
                    ok = bool(client.is_server_ready(
                        client_timeout=timeout_s))
                else:
                    ok = bool(client.is_server_ready())
            except Exception:
                ok = False
            with lock:
                verdicts[ep.url] = ok
            self._pool.probe_ok(ep.url, ok)

        endpoints = self._pool.endpoints
        if len(endpoints) == 1:
            probe_one(endpoints[0])
            return verdicts
        executor = self._probe_executor
        if executor is None:
            with self._clients_lock:
                if self._closed:
                    raise_error("client is closed")
                if self._probe_executor is None:
                    # persistent: a sweep every health_interval_s must
                    # not create and tear down N threads each time
                    self._probe_executor = ThreadPoolExecutor(
                        max_workers=len(endpoints),
                        thread_name_prefix="tc-tpu-probe")
                executor = self._probe_executor
        try:
            futures = [executor.submit(probe_one, ep)
                       for ep in endpoints]
        except RuntimeError:
            # close() shut the pool down between our executor read and
            # the submit — typed error, like the hedge path
            raise_error("client is closed")
        _fut_wait(futures, timeout=timeout_s + 5.0)
        return verdicts

    def start_probing(self, interval_s: float) -> None:
        if self._probe_thread is not None:
            return

        def _loop():
            while not self._probe_stop.wait(interval_s):
                try:
                    self.probe_all()
                except Exception:
                    pass  # a probe sweep must never kill the thread

        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=_loop, daemon=True, name="tc-tpu-cluster-probe")
        self._probe_thread.start()

    # -- routed single calls (health / metadata) ---------------------------
    def _routed(self, kind: str, name: str, *args, **kwargs):
        policy = self._retry_policy
        excluded: List[str] = []
        last: List[Optional[Endpoint]] = [None]

        def attempt(_remaining, _n):
            ep = self._pool.pick(exclude=excluded)
            last[0] = ep
            client = self._client_for(ep)
            ep.acquire()
            try:
                result = getattr(client, name)(*args, **kwargs)
            except Exception:
                self._pool.record(ep, ok=False)
                raise
            finally:
                ep.release()
            self._pool.record(ep, ok=True)
            return result

        if policy is None:
            return attempt(None, 1)

        def on_failure(_exc, _n):
            if last[0] is not None:
                excluded.append(last[0].url)

        return call_with_retry(
            policy, attempt, method=kind,
            retry_meta=("", self._protocol, kind, ""),
            on_failure=on_failure)

    def _broadcast(self, name: str, *args, **kwargs):
        """Apply a control-plane call to EVERY endpoint.  All endpoints
        are attempted; the first failure (if any) is re-raised after, so
        one dead replica doesn't leave the rest unconfigured silently."""
        first_result = _UNSET = object()
        first_error: Optional[BaseException] = None
        for ep in self._pool.endpoints:
            try:
                result = getattr(self._client_for(ep), name)(*args, **kwargs)
                if first_result is _UNSET:
                    first_result = result
            except Exception as e:  # noqa: BLE001 — collected, re-raised
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return None if first_result is _UNSET else first_result

    def get_costs(self, model_name=None, **kwargs) -> dict:
        """Fleet-wide per-tenant cost attribution: every endpoint's
        ``/v2/debug/costs`` ledger, summed per (model, tenant).  All
        endpoints are attempted; the first failure (if any) is re-raised
        after, like the control-plane broadcast — a silently missing
        replica would understate the fleet's spend."""
        snaps: List[dict] = []
        first_error: Optional[BaseException] = None
        for ep in self._pool.endpoints:
            try:
                snaps.append(self._client_for(ep).get_costs(
                    model_name=model_name, **kwargs))
            except Exception as e:  # noqa: BLE001 — collected, re-raised
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return merge_cost_snapshots(snaps)

    def __getattr__(self, name: str):
        # only reached when normal lookup fails; underscore lookups must
        # fail fast (copy/pickle/hasattr probing during __init__)
        if name.startswith("_"):
            raise AttributeError(name)
        if name in _HEALTH_METHODS:
            return partial(self._routed, "health", name)
        if name in _METADATA_METHODS:
            return partial(self._routed, "metadata", name)
        if name in _BROADCAST_METHODS:
            return partial(self._broadcast, name)
        if name in _STREAMING_METHODS:
            raise_error(
                f"{name} is per-connection and not supported on "
                "ClusterClient; open a stream on a single-endpoint client")
        raise AttributeError(
            f"{type(self).__name__!s} has no attribute {name!r}")

    # -- inference ---------------------------------------------------------
    def infer(
        self,
        model_name: str,
        inputs,
        model_version: str = "",
        outputs=None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout=None,
        headers=None,
        parameters=None,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
        hedge: Optional[bool] = None,
        tenant: Optional[str] = None,
        **kwargs,
    ):
        """Routed inference.  ``hedge`` overrides the idempotency gate per
        call (True asserts the model is safe to re-execute; False
        disables hedging for this request); ``priority``/``tenant`` are
        the QoS identity, carried in the per-attempt call dict so retries
        AND hedged backups re-stamp them; protocol-specific kwargs
        (``query_params``, ``client_timeout``, compression, ...) pass
        through to the per-endpoint client."""
        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        call = dict(
            inputs=inputs, model_version=model_version, outputs=outputs,
            request_id=request_id, sequence_id=sequence_id,
            sequence_start=sequence_start, sequence_end=sequence_end,
            priority=priority, timeout=timeout, headers=headers,
            parameters=parameters, tenant=tenant, **kwargs)
        hedging = self._hedge_armed(policy, hedge, sequence_id)
        excluded: List[str] = []
        last: List[Optional[Endpoint]] = [None]

        def attempt(remaining, _n):
            prev = last[0]
            ep = self._pool.pick(sequence_id=sequence_id, exclude=excluded)
            last[0] = ep
            if prev is not None and ep.url != prev.url:
                # a retry landing on a DIFFERENT replica is a journey
                # event — the cross-replica hop the trace join counts
                telemetry().record_journey_event(
                    "ENDPOINT_SWITCH", model_name, self._protocol,
                    endpoint=ep.url, request_id=request_id)
            if self._on_route is not None:
                self._on_route(ep.url, model_name, sequence_id)
            if hedging:
                return self._hedged_infer(
                    ep, remaining, excluded, model_name, request_id, call)
            return self._infer_on(ep, remaining, model_name, call)

        if policy is None and deadline_s is None:
            return attempt(None, 1)

        def on_failure(_exc, _n):
            if last[0] is not None:
                excluded.append(last[0].url)

        return call_with_retry(
            policy, attempt, method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, self._protocol, "infer", request_id),
            on_failure=on_failure, journey=True)

    def infer_many(
        self,
        model_name: str,
        requests,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
        **kwargs,
    ):
        """Routed batch submit (the wire fast path's ``infer_many``).  The
        WHOLE flight is routed to one endpoint — batch amortization needs
        one template and one connection, and split routing would reorder
        results.  A retry replays the whole flight on a different replica
        (gated on ``retry_infer`` like any inference retry — partial
        results from the failed attempt are discarded, so the model must
        tolerate re-execution).  Hedging does not apply; QoS/header kwargs
        pass through to the endpoint client."""
        items = list(requests)
        if not items:
            return []
        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        excluded: List[str] = []
        last: List[Optional[Endpoint]] = [None]

        call = dict(requests=items, **kwargs)

        def attempt(remaining, _n):
            ep = self._pool.pick(exclude=excluded)
            last[0] = ep
            if self._on_route is not None:
                self._on_route(ep.url, model_name, 0)
            return self._infer_on(ep, remaining, model_name, call,
                                  method="infer_many")

        if policy is None and deadline_s is None:
            return attempt(None, 1)

        def on_failure(_exc, _n):
            if last[0] is not None:
                excluded.append(last[0].url)

        return call_with_retry(
            policy, attempt, method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, self._protocol, "infer", ""),
            on_failure=on_failure)

    def _hedge_armed(self, policy: Optional[RetryPolicy],
                     hedge_override: Optional[bool],
                     sequence_id: int) -> bool:
        if self._hedge is None or len(self._pool.endpoints) < 2:
            return False
        if sequence_id:
            return False  # stateful: pinned to one replica by definition
        if hedge_override is not None:
            return hedge_override
        # the retry_infer opt-in is THE idempotency signal — hedging
        # re-executes exactly like a retry does
        return policy is not None and policy.retry_infer

    def _infer_on(self, ep: Endpoint, remaining_s: Optional[float],
                  model_name: str, call: Dict[str, Any],
                  method: str = "infer"):
        """One attempt on one endpoint: deadline propagation via the
        underlying client (single attempt — the cluster owns retries),
        outcome into the breaker + per-endpoint counters + latency.
        ``method`` selects the endpoint-client entry point (``infer`` /
        ``infer_many``) so batch flights share this bookkeeping."""
        client = self._client_for(ep)
        ep.acquire()
        t0 = time.perf_counter()
        try:
            result = getattr(client, method)(
                model_name, retry_policy=None, deadline_s=remaining_s,
                **call)
        except Exception:
            self._pool.record(ep, ok=False)
            raise
        finally:
            ep.release()
        ep.observe(model_name, time.perf_counter() - t0)
        self._pool.record(ep, ok=True)
        return result

    def _hedged_infer(self, primary: Endpoint,
                      remaining_s: Optional[float], excluded: List[str],
                      model_name: str, request_id: str,
                      call: Dict[str, Any]):
        """Dean-&-Barroso hedged attempt: primary now, backup to a
        different endpoint after the hedge delay, first response wins."""
        tel = telemetry()
        delay = self._hedge.delay_s(primary, model_name)
        if remaining_s is not None:
            # never spend more than half the budget waiting to hedge
            delay = min(delay, max(remaining_s * 0.5, 0.0))
        ex = self._hedge_executor()
        t0 = time.monotonic()
        t0_ns = time.monotonic_ns()
        f_primary = self._hedge_submit(ex, primary, remaining_s,
                                       model_name, call)
        done, _ = _fut_wait([f_primary], timeout=delay)
        if f_primary in done:
            return f_primary.result()  # fast path: no hedge needed
        backup_ep = self._pool.pick(
            exclude=list(excluded) + [primary.url])
        if backup_ep.url == primary.url:
            return f_primary.result()  # no distinct replica to hedge to
        tel.record_hedge(model_name, self._protocol)
        if self._on_route is not None:
            self._on_route(backup_ep.url, model_name, 0)
        rem2 = remaining_s
        if rem2 is not None:
            rem2 = max(rem2 - (time.monotonic() - t0), 1e-3)
        f_backup = self._hedge_submit(ex, backup_ep, rem2,
                                      model_name, call)
        pending = {f_primary, f_backup}
        primary_error: Optional[BaseException] = None
        while pending:
            done, pending = _fut_wait(pending, return_when=FIRST_COMPLETED)
            for f in done:
                err = f.exception()
                if err is None:
                    if f is f_backup:
                        tel.record_hedge(model_name, self._protocol,
                                         won=True)
                    for loser in pending:
                        # best-effort: unstarted work is cancelled; an
                        # in-flight transport call completes in its worker
                        # and is discarded (still feeds the breaker)
                        loser.cancel()
                    if tel.tracing_enabled:
                        tel.record_client_trace(
                            request_id, model_name, self._protocol,
                            "hedge",
                            spans=[("HEDGE", t0_ns, time.monotonic_ns())],
                            endpoint=backup_ep.url)
                    return f.result()
                if f is f_primary:
                    primary_error = err
                else:
                    # the backup's endpoint failed too: exclude it from
                    # the retry loop's next pick alongside the primary
                    excluded.append(backup_ep.url)
        raise primary_error if primary_error is not None \
            else f_backup.exception()

    def _hedge_submit(self, ex: ThreadPoolExecutor, *args):
        try:
            # copy_context: the hedged attempt runs on a pool thread, and
            # the journey contextvar must follow it — both hedge arms'
            # traceparents have to share the journey's trace id
            return ex.submit(contextvars.copy_context().run,
                             self._infer_on, *args)
        except RuntimeError:
            # close() shut the pool down between our executor read and
            # this submit — surface the typed closed error, not the raw
            # "cannot schedule new futures after shutdown"
            raise_error("client is closed")

    def _hedge_executor(self) -> ThreadPoolExecutor:
        executor = self._executor
        if executor is None:
            with self._clients_lock:
                # double-checked: two threads' first hedges must not
                # each build (and one leak) a 32-thread pool — and a
                # create racing close() must not leak a pool post-close
                if self._closed:
                    raise_error("client is closed")
                if self._executor is None:
                    self._executor = ThreadPoolExecutor(
                        max_workers=self._hedge_workers,
                        thread_name_prefix="tc-tpu-hedge")
                executor = self._executor
        return executor
