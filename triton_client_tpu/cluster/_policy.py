"""Pluggable balancing policies for the cluster client.

A policy answers one question — ``choose(endpoints)`` over the currently
*available* (probe-healthy, breaker-closed-or-trialing, not-excluded)
endpoints — and nothing else: health, exclusion, and sequence pinning are
the pool's job, so every policy stays a few lines and new ones are cheap.

Shipped policies:

* ``round_robin`` — strict rotation.  Predictable, ignores load; the
  baseline every balancing benchmark compares against.
* ``least_outstanding`` — power-of-two-choices (Mitzenmacher '01): sample
  two endpoints at random, take the one with fewer in-flight requests.
  Near-optimal load spread at O(1) cost, and — unlike a full argmin —
  avoids herd behavior when many clients share the same view of "least
  loaded".
* **Sticky sequence routing** is NOT a policy here: a ``sequence_id`` maps
  to an endpoint by rendezvous (highest-random-weight) hashing *before*
  the policy runs (see ``EndpointPool.pick``), because stateful sequences
  must land on one endpoint regardless of load.  Rendezvous hashing gives
  the invariant the failover test asserts: removing endpoint B never
  remaps a sequence pinned to endpoint A.
"""

from __future__ import annotations

import hashlib
import random
import threading
from typing import List, Optional, Sequence

__all__ = [
    "BalancingPolicy",
    "HedgePolicy",
    "LeastOutstanding",
    "RoundRobin",
    "make_policy",
    "rendezvous_rank",
]


class HedgePolicy:
    """When (and whether) to issue a backup request to a second endpoint.

    Dean & Barroso's hedged-request recipe ("The Tail at Scale", CACM
    2013): after a delay tied to the request's *expected* latency — here
    the chosen endpoint's observed per-model quantile from the client
    ``LatencyHistogram`` (default p95: hedge the slowest ~5%, bounding
    extra load at ~5%) — send the same request to a different replica and
    take whichever answers first.  Until ``min_samples`` observations
    exist for the (model, endpoint) the fixed ``default_delay_s`` is used.

    Hedging re-executes the request, so it is gated on idempotency
    exactly like ``retry_infer``: the cluster client hedges only when the
    retry policy opted inference into re-execution (or the caller forces
    ``hedge=True`` per call).  Sequence requests never hedge — a stateful
    sequence is pinned to one replica by definition.
    """

    def __init__(self, quantile: float = 0.95,
                 default_delay_s: float = 0.05,
                 min_samples: int = 16) -> None:
        if not 0.0 < quantile < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {quantile}")
        self.quantile = float(quantile)
        self.default_delay_s = float(default_delay_s)
        self.min_samples = int(min_samples)

    def delay_s(self, endpoint, model: str) -> float:
        """The hedge delay for one request to ``model`` on ``endpoint``."""
        h = endpoint.latency(model)
        if h is not None and h.count >= self.min_samples:
            return h.quantile(self.quantile)
        return self.default_delay_s


class BalancingPolicy:
    """Interface: pick one endpoint from a non-empty available set."""

    name = "abstract"

    def choose(self, endpoints: Sequence):
        raise NotImplementedError


class RoundRobin(BalancingPolicy):
    name = "round_robin"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._n = 0

    def choose(self, endpoints: Sequence):
        with self._lock:
            i = self._n
            self._n += 1
        return endpoints[i % len(endpoints)]


class LeastOutstanding(BalancingPolicy):
    """Power-of-two-choices over in-flight request counts."""

    name = "least_outstanding"

    def __init__(self, seed: Optional[int] = None) -> None:
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def choose(self, endpoints: Sequence):
        if len(endpoints) == 1:
            return endpoints[0]
        with self._lock:
            a, b = self._rng.sample(range(len(endpoints)), 2)
        ea, eb = endpoints[a], endpoints[b]
        return ea if ea.outstanding <= eb.outstanding else eb


def rendezvous_rank(sequence_id: int, urls: Sequence[str]) -> List[str]:
    """Endpoint URLs ranked by rendezvous (HRW) weight for one sequence.

    Deterministic across processes (md5, not ``hash()``, which is
    per-process salted) and stable under membership change: dropping any
    URL leaves the relative order of the others untouched, so a sequence
    pinned to its rank-0 endpoint only moves when *that* endpoint dies —
    and then deterministically to rank 1.
    """
    def weight(url: str) -> int:
        return int.from_bytes(
            hashlib.md5(f"{sequence_id}|{url}".encode()).digest()[:8],
            "big")

    return sorted(urls, key=weight, reverse=True)


_POLICIES = {
    "round_robin": RoundRobin,
    "least_outstanding": LeastOutstanding,
}


def make_policy(spec) -> BalancingPolicy:
    """A policy instance from a name or a ready-made instance."""
    if isinstance(spec, BalancingPolicy):
        return spec
    try:
        return _POLICIES[spec]()
    except KeyError:
        raise ValueError(
            f"unknown balancing policy {spec!r}; "
            f"expected one of {sorted(_POLICIES)} or a BalancingPolicy")
