"""asyncio ``ClusterClient``: the routing layer over the aio transports.

Same pool / policies / breaker / hedging semantics as the sync
:class:`triton_client_tpu.cluster.ClusterClient`, but over
``http.aio`` / ``grpc.aio`` clients inside one event loop: hedging uses
``asyncio.wait(FIRST_COMPLETED)`` and *really* cancels the loser (task
cancellation propagates into aiohttp/grpc.aio, aborting the wire call —
the sync client can only abandon a blocking call), and active probing is
an asyncio task (``start_probing``) instead of a thread.
"""

from __future__ import annotations

import asyncio
import time
from functools import partial
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

from .._client import InferenceServerClientBase
from .._resilience import RetryPolicy, call_with_retry_async
from .._telemetry import telemetry
from ..utils import raise_error
from ._client import (_BROADCAST_METHODS, _HEALTH_METHODS,
                      _METADATA_METHODS, _STREAMING_METHODS,
                      merge_cost_snapshots)
from ._policy import HedgePolicy
from ._pool import Endpoint, EndpointPool

__all__ = ["ClusterClient"]


class ClusterClient(InferenceServerClientBase):
    """v2 client over a fleet of endpoints (asyncio; http or grpc).

    Constructor parameters mirror the sync ``ClusterClient``; every
    public method is ``async``.
    """

    def __init__(
        self,
        urls: Union[str, Iterable[str]],
        protocol: str = "http",
        policy: Union[str, object] = "least_outstanding",
        retry_policy: Optional[RetryPolicy] = None,
        hedge: Optional[HedgePolicy] = None,
        health_interval_s: Optional[float] = None,
        failure_threshold: int = 3,
        reset_timeout_s: float = 5.0,
        client_kwargs: Optional[Dict[str, Any]] = None,
        client_factory: Optional[Callable[[str], Any]] = None,
        on_route: Optional[Callable[[str, str, int], None]] = None,
    ):
        super().__init__()
        protocol = protocol.lower()
        if protocol not in ("http", "grpc"):
            raise_error(f"protocol must be 'http' or 'grpc', got {protocol}")
        self._protocol_label = protocol + "_aio"
        self._protocol = protocol
        self._pool = EndpointPool(urls, policy=policy,
                                  failure_threshold=failure_threshold,
                                  reset_timeout_s=reset_timeout_s)
        self._retry_policy = retry_policy
        self._hedge = hedge
        self._on_route = on_route
        self._client_kwargs = dict(client_kwargs or {})
        self._client_factory = client_factory
        self._clients: Dict[str, Any] = {}
        self._closed = False
        self._probe_task: Optional[asyncio.Task] = None
        # deferred: the constructor may run outside any event loop, so the
        # probe task starts lazily on the first routed call instead
        self._health_interval_s = health_interval_s

    # -- wiring ------------------------------------------------------------
    @property
    def pool(self) -> EndpointPool:
        return self._pool

    @property
    def urls(self) -> List[str]:
        return self._pool.urls

    def _make_client(self, url: str):
        if self._client_factory is not None:
            return self._client_factory(url)
        if self._protocol == "grpc":
            from ..grpc import aio as mod
        else:
            from ..http import aio as mod
        return mod.InferenceServerClient(url, **self._client_kwargs)

    def _client_for(self, ep: Endpoint):
        client = self._clients.get(ep.url)
        if client is None:
            if self._closed:
                # a task resuming after close() must not rebuild a
                # session/channel into a dict nobody will ever close
                # (same contract as the sync client)
                raise_error("client is closed")
            client = self._make_client(ep.url)
            if self._plugin is not None:
                client.register_plugin(self._plugin)
            self._clients[ep.url] = client
        return client

    # plugin fan-out: same contract as the sync cluster client — a
    # registered plugin must reach every per-endpoint client's requests
    def register_plugin(self, plugin) -> None:
        super().register_plugin(plugin)
        for c in self._clients.values():
            c.register_plugin(plugin)

    def unregister_plugin(self) -> None:
        super().unregister_plugin()
        for c in self._clients.values():
            if c.plugin() is not None:
                c.unregister_plugin()

    async def close(self) -> None:
        self._closed = True
        if self._probe_task is not None:
            self._probe_task.cancel()
            try:
                await self._probe_task
            except (asyncio.CancelledError, Exception):
                pass
            self._probe_task = None
        clients, self._clients = dict(self._clients), {}
        for c in clients.values():
            try:
                await c.close()
            except Exception:
                pass

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    # -- active health probing ---------------------------------------------
    async def probe_all(self, timeout_s: float = 2.0) -> Dict[str, bool]:
        """One readiness sweep, all endpoints probed concurrently (a
        sweep costs ~one ``timeout_s`` regardless of how many replicas
        are dead); verdicts feed the breakers."""
        async def probe_one(ep: Endpoint) -> bool:
            try:
                client = self._client_for(ep)
                if self._protocol == "grpc":
                    return bool(await client.is_server_ready(
                        client_timeout=timeout_s))
                return bool(await asyncio.wait_for(
                    client.is_server_ready(), timeout=timeout_s))
            except Exception:
                return False

        results = await asyncio.gather(
            *(probe_one(ep) for ep in self._pool.endpoints))
        verdicts = {}
        for ep, ok in zip(self._pool.endpoints, results):
            verdicts[ep.url] = ok
            self._pool.probe_ok(ep.url, ok)
        return verdicts

    def _maybe_start_probing(self) -> None:
        if self._health_interval_s is not None and self._probe_task is None:
            self.start_probing(self._health_interval_s)

    def start_probing(self, interval_s: float) -> None:
        if self._probe_task is not None:
            return

        async def _loop():
            while True:
                await asyncio.sleep(interval_s)
                try:
                    await self.probe_all()
                except Exception:
                    pass

        self._probe_task = asyncio.ensure_future(_loop())

    # -- routed single calls -----------------------------------------------
    async def _routed(self, kind: str, name: str, *args, **kwargs):
        self._maybe_start_probing()
        policy = self._retry_policy
        excluded: List[str] = []
        last: List[Optional[Endpoint]] = [None]

        async def attempt(_remaining, _n):
            ep = self._pool.pick(exclude=excluded)
            last[0] = ep
            client = self._client_for(ep)
            ep.acquire()
            try:
                result = await getattr(client, name)(*args, **kwargs)
            except Exception:
                self._pool.record(ep, ok=False)
                raise
            finally:
                ep.release()
            self._pool.record(ep, ok=True)
            return result

        if policy is None:
            return await attempt(None, 1)

        def on_failure(_exc, _n):
            if last[0] is not None:
                excluded.append(last[0].url)

        return await call_with_retry_async(
            policy, attempt, method=kind,
            retry_meta=("", self._protocol_label, kind, ""),
            on_failure=on_failure)

    async def _broadcast(self, name: str, *args, **kwargs):
        """Control-plane call applied to every endpoint (see the sync
        client); first failure re-raised after all were attempted."""
        first_result = _UNSET = object()
        first_error: Optional[BaseException] = None
        for ep in self._pool.endpoints:
            try:
                result = await getattr(
                    self._client_for(ep), name)(*args, **kwargs)
                if first_result is _UNSET:
                    first_result = result
            except Exception as e:  # noqa: BLE001 — collected, re-raised
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return None if first_result is _UNSET else first_result

    async def get_costs(self, model_name=None, **kwargs) -> dict:
        """Fleet-wide per-tenant cost attribution: every endpoint's
        ``/v2/debug/costs`` ledger, summed per (model, tenant) — the
        async mirror of the sync cluster client's fan-out."""
        snaps: List[dict] = []
        first_error: Optional[BaseException] = None
        for ep in self._pool.endpoints:
            try:
                snaps.append(await self._client_for(ep).get_costs(
                    model_name=model_name, **kwargs))
            except Exception as e:  # noqa: BLE001 — collected, re-raised
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return merge_cost_snapshots(snaps)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in _HEALTH_METHODS:
            return partial(self._routed, "health", name)
        if name in _METADATA_METHODS:
            return partial(self._routed, "metadata", name)
        if name in _BROADCAST_METHODS:
            return partial(self._broadcast, name)
        if name in _STREAMING_METHODS:
            raise_error(
                f"{name} is per-connection and not supported on "
                "ClusterClient; open a stream on a single-endpoint client")
        raise AttributeError(
            f"{type(self).__name__!s} has no attribute {name!r}")

    # -- inference ---------------------------------------------------------
    async def infer(
        self,
        model_name: str,
        inputs,
        model_version: str = "",
        outputs=None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout=None,
        headers=None,
        parameters=None,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
        hedge: Optional[bool] = None,
        tenant: Optional[str] = None,
        **kwargs,
    ):
        """Routed inference — same contract as the sync cluster client
        (``priority``/``tenant`` ride the per-attempt call dict, so
        retries and hedged backups re-stamp the QoS identity)."""
        self._maybe_start_probing()
        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        call = dict(
            inputs=inputs, model_version=model_version, outputs=outputs,
            request_id=request_id, sequence_id=sequence_id,
            sequence_start=sequence_start, sequence_end=sequence_end,
            priority=priority, timeout=timeout, headers=headers,
            parameters=parameters, tenant=tenant, **kwargs)
        hedging = self._hedge_armed(policy, hedge, sequence_id)
        excluded: List[str] = []
        last: List[Optional[Endpoint]] = [None]

        async def attempt(remaining, _n):
            prev = last[0]
            ep = self._pool.pick(sequence_id=sequence_id, exclude=excluded)
            last[0] = ep
            if prev is not None and ep.url != prev.url:
                # cross-replica hop: journey event, as in the sync client
                telemetry().record_journey_event(
                    "ENDPOINT_SWITCH", model_name, self._protocol_label,
                    endpoint=ep.url, request_id=request_id)
            if self._on_route is not None:
                self._on_route(ep.url, model_name, sequence_id)
            if hedging:
                return await self._hedged_infer(
                    ep, remaining, excluded, model_name, request_id, call)
            return await self._infer_on(ep, remaining, model_name, call)

        if policy is None and deadline_s is None:
            return await attempt(None, 1)

        def on_failure(_exc, _n):
            if last[0] is not None:
                excluded.append(last[0].url)

        return await call_with_retry_async(
            policy, attempt, method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, self._protocol_label, "infer",
                        request_id),
            on_failure=on_failure, journey=True)

    async def infer_many(
        self,
        model_name: str,
        requests,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
        **kwargs,
    ):
        """Routed batch submit — the sync cluster client's contract over
        the aio endpoint clients (whole flight to one endpoint; a retry
        replays the flight on another replica, gated on ``retry_infer``;
        no hedging)."""
        items = list(requests)
        if not items:
            return []
        self._maybe_start_probing()
        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        excluded: List[str] = []
        last: List[Optional[Endpoint]] = [None]

        call = dict(requests=items, **kwargs)

        async def attempt(remaining, _n):
            ep = self._pool.pick(exclude=excluded)
            last[0] = ep
            if self._on_route is not None:
                self._on_route(ep.url, model_name, 0)
            return await self._infer_on(ep, remaining, model_name, call,
                                        method="infer_many")

        if policy is None and deadline_s is None:
            return await attempt(None, 1)

        def on_failure(_exc, _n):
            if last[0] is not None:
                excluded.append(last[0].url)

        return await call_with_retry_async(
            policy, attempt, method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, self._protocol_label, "infer", ""),
            on_failure=on_failure)

    def _hedge_armed(self, policy: Optional[RetryPolicy],
                     hedge_override: Optional[bool],
                     sequence_id: int) -> bool:
        if self._hedge is None or len(self._pool.endpoints) < 2:
            return False
        if sequence_id:
            return False
        if hedge_override is not None:
            return hedge_override
        return policy is not None and policy.retry_infer

    async def _infer_on(self, ep: Endpoint, remaining_s: Optional[float],
                        model_name: str, call: Dict[str, Any],
                        method: str = "infer"):
        """``method`` selects the endpoint-client entry point (``infer`` /
        ``infer_many``) so batch flights share this bookkeeping (see the
        sync client)."""
        client = self._client_for(ep)
        ep.acquire()
        t0 = time.perf_counter()
        try:
            result = await getattr(client, method)(
                model_name, retry_policy=None, deadline_s=remaining_s,
                **call)
        except Exception:
            self._pool.record(ep, ok=False)
            raise
        finally:
            ep.release()
        ep.observe(model_name, time.perf_counter() - t0)
        self._pool.record(ep, ok=True)
        return result

    async def _hedged_infer(self, primary: Endpoint,
                            remaining_s: Optional[float],
                            excluded: List[str], model_name: str,
                            request_id: str, call: Dict[str, Any]):
        """Hedged attempt over asyncio tasks: the loser is genuinely
        cancelled (cancellation aborts the in-flight wire call)."""
        tel = telemetry()
        delay = self._hedge.delay_s(primary, model_name)
        if remaining_s is not None:
            delay = min(delay, max(remaining_s * 0.5, 0.0))
        t0 = time.monotonic()
        t0_ns = time.monotonic_ns()
        t_primary = asyncio.ensure_future(
            self._infer_on(primary, remaining_s, model_name, call))
        done, _ = await asyncio.wait({t_primary}, timeout=delay)
        if t_primary in done:
            return t_primary.result()
        backup_ep = self._pool.pick(exclude=list(excluded) + [primary.url])
        if backup_ep.url == primary.url:
            return await t_primary
        tel.record_hedge(model_name, self._protocol_label)
        if self._on_route is not None:
            self._on_route(backup_ep.url, model_name, 0)
        rem2 = remaining_s
        if rem2 is not None:
            rem2 = max(rem2 - (time.monotonic() - t0), 1e-3)
        t_backup = asyncio.ensure_future(
            self._infer_on(backup_ep, rem2, model_name, call))
        pending = {t_primary, t_backup}
        primary_error: Optional[BaseException] = None
        try:
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                for t in done:
                    if not t.cancelled() and t.exception() is None:
                        if t is t_backup:
                            tel.record_hedge(model_name,
                                             self._protocol_label, won=True)
                        if tel.tracing_enabled:
                            tel.record_client_trace(
                                request_id, model_name,
                                self._protocol_label, "hedge",
                                spans=[("HEDGE", t0_ns,
                                        time.monotonic_ns())],
                                endpoint=backup_ep.url)
                        return t.result()
                    if t is t_primary:
                        primary_error = t.exception()
                    else:
                        excluded.append(backup_ep.url)
            raise primary_error if primary_error is not None \
                else t_backup.exception()
        finally:
            for t in (t_primary, t_backup):
                if not t.done():
                    t.cancel()
                    try:
                        await t
                    except (asyncio.CancelledError, Exception):
                        pass
