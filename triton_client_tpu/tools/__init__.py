"""Operator-facing CLI tools riding the library (no server required).

``trace_summary`` is the canonical consumer of the server's trace files
(the reference repo's ``src/python/examples/trace_summary.py`` analog):
per-model/per-stage latency breakdowns, client/server trace joins, and
Chrome trace-event export for Perfetto.
"""
