"""Operator-facing CLI tools riding the library (stdlib-only by contract:
every module here must import — and its ``--help`` must exit 0 — with none
of the optional client deps installed; ``tests/test_tools_import.py``
enforces it for each registered console script).

``trace_summary`` is the canonical consumer of the server's trace files
(the reference repo's ``src/python/examples/trace_summary.py`` analog):
per-model/per-stage latency breakdowns, client/server trace joins, and
Chrome trace-event export for Perfetto.

``top`` (``triton-top``) is the live console: it polls a running server's
``/metrics`` + ``/v2/debug/flight_recorder`` and renders a refreshing
per-model table (QPS, p50/p99, queue share, batch occupancy, error rate,
most recent tail-latency outlier), with ``--once --json`` for scripting.
"""
