"""``triton-top``: a top(1)-style live console for a running server.

Polls two HTTP surfaces — ``GET /metrics`` (the Triton-convention
``nv_inference_*`` counters) and ``GET /v2/debug/flight_recorder`` (the
always-on flight recorder's live per-model quantiles + pinned outliers) —
and renders one refreshing per-model table: QPS, p50/p99, queue share,
realized batch, in-flight requests, error rate, watchdog counters, device
duty cycle, the memory-governor columns (MEM% = the model's share of the
live byte budget from ``nv_mem_inflight_bytes`` / ``nv_mem_budget_bytes``,
SHED/s = its memory-shed rate from ``nv_mem_shed_total``), the fleet
columns (INST = live batcher instance parallelism,
VER = the version unversioned traffic routes to), the SLO burn rate
(with a ``!`` breach marker when both the 5m and 1h windows burn over
the fast-burn threshold, and an autoscale-actuation marker beside it:
``^`` scaled out / ``v`` scaled in since the previous poll), the
supervisor's worker-restart count in the header, and the most recent
pinned outlier — plus a **buckets** view (one line per model/bucket with
tick rate, realized occupancy, pad-waste %, assembly cost, and queue
depth) whenever the server exports ``nv_tpu_tick_*`` series.  "What is
the server doing right now" becomes one command::

    triton-top --url localhost:8000            # live, refresh every 2s
    triton-top --url localhost:8000 --once --json   # one snapshot, JSON

stdlib-only on purpose (same contract as ``trace_summary``): the console
must run — and ``--help`` must exit 0 — on a box with none of the optional
client deps installed.

Rates (QPS, error %, queue share, batch) are deltas between consecutive
polls; ``--once`` takes a single sample, so rate columns fall back to the
cumulative counters (and QPS is null in ``--json``).

``--url`` is repeatable: with a fleet, every server is polled each cycle
and the table shows one aggregated row per model (QPS/pending/shed summed
across replicas, latency tails as the WORST replica — the fleet's honest
tail) with a per-server breakdown row under it; an unreachable replica is
shown as down instead of killing the console.  ``--once --json`` carries
the per-endpoint samples next to the aggregate.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

#: nv_* families the table consumes (summed across versions per model).
_METRICS = (
    "nv_inference_request_success",
    "nv_inference_request_failure",
    "nv_inference_request_duration_us",
    "nv_inference_queue_duration_us",
    "nv_inference_batch_size_total",
    "nv_inference_batch_execution_count",
    "nv_inference_pending_request_count",
    "nv_inference_rejected_total",
    "nv_inference_deadline_exceeded_total",
)

# greedy label block up to the LAST `}` before the value: a label value
# may contain a literal `}` (tenant ids are client-supplied octets); the
# block is optional — unlabeled gauges (nv_slo_burn_threshold) match with
# a None label group
_SERIES_RE = re.compile(r'^(\w+)(?:\{(.*)\})?\s+([0-9.eE+-]+)\s*$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _fetch(url: str, timeout: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def parse_metrics(text: str) -> Dict[str, Dict[str, float]]:
    """Prometheus exposition -> ``{metric: {model: value}}`` for the
    families the table uses, versions summed per model."""
    out: Dict[str, Dict[str, float]] = {m: {} for m in _METRICS}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if not m:
            continue
        name, labels_raw, value = m.groups()
        if name not in out:
            continue
        labels = dict(_LABEL_RE.findall(labels_raw or ""))
        model = labels.get("model", "")
        if not model:
            continue
        out[name][model] = out[name].get(model, 0.0) + float(value)
    return out


#: nv_tpu_tick_* families folded into the buckets view, keyed by the
#: short field name the rows use.
_BUCKET_METRICS = {
    "nv_tpu_tick_total": "ticks",
    "nv_tpu_tick_batch_total": "batch",
    "nv_tpu_tick_padded_total": "padded",
    "nv_tpu_tick_assembly_duration_us": "assembly_us",
    "nv_tpu_tick_queue_depth_total": "queue_depth",
    "nv_tpu_tick_sync_total": "syncs",
    "nv_tpu_tick_step_total": "steps",
    "nv_tpu_tick_upload_total": "uploads",
}


def parse_device(text: str) -> Dict[str, Any]:
    """Device/SLO/fleet series -> ``{"duty": {model: v}, "mfu": {model:
    v}, "burn": {(model, window): v}, "burn_threshold": v, "buckets":
    {(model, bucket): {field: v}}, "inst": {model: v}, "ver": {model:
    v}, "scale": {(model, direction): v}, "restarts": {worker: v}}``.
    Servers predating the device-stats or fleet layers simply produce
    empty maps (and the default threshold)."""
    out: Dict[str, Any] = {"duty": {}, "mfu": {}, "burn": {}, "buckets": {},
                           "burn_threshold": 14.4,
                           "inst": {}, "ver": {}, "scale": {},
                           "restarts": {},
                           "mem_inflight": {}, "mem_budget": None,
                           "mem_shed": {},
                           "host_lag_us": None, "host_gc_us": None,
                           "fault": {}, "quar": {},
                           "cache_hit": {}, "cache_miss": {},
                           "cache_pinned": {}}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if not m:
            continue
        name, labels_raw, value = m.groups()
        if name == "nv_slo_burn_threshold":
            # the server's configured page condition — the "!" breach
            # marker must agree with a non-default --slo-burn-threshold
            out["burn_threshold"] = float(value)
            continue
        if name == "nv_mem_budget_bytes":
            # unlabeled live-budget gauge (shrinks under mem_pressure
            # chaos) — the MEM% column's denominator
            out["mem_budget"] = float(value)
            continue
        if name == "nv_host_loop_lag_us":
            # per-loop gauges fold to the WORST loop — the stall an
            # operator chases is on whichever frontend loop has it
            v = float(value)
            if out["host_lag_us"] is None or v > out["host_lag_us"]:
                out["host_lag_us"] = v
            continue
        if name == "nv_host_gc_pause_us_total":
            # summed over generations: the GC column answers "how much
            # wall time does GC steal", not which generation stole it
            out["host_gc_us"] = (out["host_gc_us"] or 0.0) + float(value)
            continue
        if name == "nv_fleet_worker_restart_total":
            # kept per worker: every worker of one supervised fleet
            # exports the SAME fleet-global counters (shared state
            # file), so the fleet view must dedup per worker across
            # polled endpoints, not sum endpoints
            labels = dict(_LABEL_RE.findall(labels_raw or ""))
            worker = labels.get("worker", "")
            out["restarts"][worker] = (out["restarts"].get(worker, 0.0)
                                       + float(value))
            continue
        if name not in ("nv_tpu_duty_cycle", "nv_tpu_live_mfu",
                        "nv_slo_burn_rate", "nv_fleet_instances",
                        "nv_fleet_serving_version", "nv_fleet_scale_total",
                        "nv_mem_inflight_bytes", "nv_mem_shed_total",
                        "nv_tpu_roofline_arithmetic_intensity",
                        "nv_tpu_roofline_pct_of_peak",
                        "nv_device_fault_total", "nv_device_quarantine",
                        "nv_cache_hit_total", "nv_cache_miss_total",
                        "nv_cache_pinned_bytes"
                        ) and name not in _BUCKET_METRICS:
            continue
        labels = dict(_LABEL_RE.findall(labels_raw or ""))
        model = labels.get("model", "")
        if not model:
            continue
        if name == "nv_tpu_duty_cycle":
            out["duty"][model] = float(value)
        elif name == "nv_tpu_live_mfu":
            out["mfu"][model] = float(value)
        elif name == "nv_slo_burn_rate":
            out["burn"][(model, labels.get("window", ""))] = float(value)
        elif name == "nv_fleet_instances":
            out["inst"][model] = float(value)
        elif name == "nv_fleet_serving_version":
            out["ver"][model] = float(value)
        elif name == "nv_fleet_scale_total":
            key = (model, labels.get("direction", ""))
            out["scale"][key] = out["scale"].get(key, 0.0) + float(value)
        elif name == "nv_mem_inflight_bytes":
            out["mem_inflight"][model] = float(value)
        elif name == "nv_mem_shed_total":
            # summed over (tenant, tier, reason): the SHED/s column is
            # per model; the reason split stays on the metrics surface
            out["mem_shed"][model] = (out["mem_shed"].get(model, 0.0)
                                      + float(value))
        elif name == "nv_device_fault_total":
            # summed over fault kinds: the FAULT column answers "is this
            # model's device faulting"; the kind split stays on /metrics
            out["fault"][model] = (out["fault"].get(model, 0.0)
                                   + float(value))
        elif name == "nv_device_quarantine":
            out["quar"][model] = float(value)
        elif name == "nv_cache_hit_total":
            # prefix/KV block cache (server/kvcache.py) — NOT the
            # response cache's nv_cache_num_*_per_model families
            out["cache_hit"][model] = float(value)
        elif name == "nv_cache_miss_total":
            out["cache_miss"][model] = float(value)
        elif name == "nv_cache_pinned_bytes":
            out["cache_pinned"][model] = float(value)
        elif name == "nv_tpu_roofline_arithmetic_intensity":
            # gauges, not counters: the buckets view shows the current
            # value, never a delta
            entry = out["buckets"].setdefault(
                (model, labels.get("bucket", "")), {})
            entry["roofline_ai"] = float(value)
        elif name == "nv_tpu_roofline_pct_of_peak":
            entry = out["buckets"].setdefault(
                (model, labels.get("bucket", "")), {})
            entry["roofline_pct"] = float(value)
            entry["roofline_verdict"] = labels.get("verdict", "")
        else:
            bucket = labels.get("bucket", "")
            entry = out["buckets"].setdefault((model, bucket), {})
            entry[_BUCKET_METRICS[name]] = entry.get(
                _BUCKET_METRICS[name], 0.0) + float(value)
    return out


def parse_qos(text: str) -> Dict[str, Dict[tuple, float]]:
    """Tenant/tier-labeled QoS series -> ``{"requests": {(tenant, tier):
    v}, "shed": {(tenant, tier): v}}`` (shed summed over models).  Servers
    predating the QoS layer simply produce empty maps."""
    out: Dict[str, Dict[tuple, float]] = {"requests": {}, "shed": {}}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if not m:
            continue
        name, labels_raw, value = m.groups()
        if name == "nv_qos_tenant_requests_total":
            bucket = out["requests"]
        elif name == "nv_inference_rejected_total":
            bucket = out["shed"]
        else:
            continue
        labels = dict(_LABEL_RE.findall(labels_raw or ""))
        tenant = labels.get("tenant")
        if tenant is None:
            continue  # pre-QoS model-only series
        key = (tenant, labels.get("tier", "0"))
        bucket[key] = bucket.get(key, 0.0) + float(value)
    return out


#: nv_cost_* families folded into the COST view, keyed by the short
#: field name the rows use.
_COST_METRICS = {
    "nv_cost_device_us_total": "device_us",
    "nv_cost_flops_total": "flops",
    "nv_cost_tokens_total": "tokens",
    "nv_cost_kv_byte_seconds_total": "kv_byte_seconds",
}


def parse_costs(text: str) -> Dict[tuple, Dict[str, float]]:
    """Per-tenant cost-attribution series -> ``{(model, tenant):
    {field: v}}``.  Servers predating the cost ledger simply produce an
    empty map."""
    out: Dict[tuple, Dict[str, float]] = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if not m:
            continue
        name, labels_raw, value = m.groups()
        field = _COST_METRICS.get(name)
        if field is None:
            continue
        labels = dict(_LABEL_RE.findall(labels_raw or ""))
        key = (labels.get("model", ""), labels.get("tenant", ""))
        entry = out.setdefault(key, {})
        entry[field] = entry.get(field, 0.0) + float(value)
    return out


def sample(base_url: str, timeout: float, limit: int = 0) -> Dict[str, Any]:
    """One poll of both surfaces, monotonic-stamped for rate deltas."""
    recorder_url = f"{base_url}/v2/debug/flight_recorder"
    if limit:
        recorder_url += f"?limit={int(limit)}"
    metrics_text = _fetch(f"{base_url}/metrics", timeout)
    return {
        "t": time.monotonic(),
        "metrics": parse_metrics(metrics_text),
        "qos": parse_qos(metrics_text),
        "device": parse_device(metrics_text),
        "costs": parse_costs(metrics_text),
        "recorder": json.loads(_fetch(recorder_url, timeout)),
    }


def _delta(cur: Dict[str, Dict[str, float]],
           prev: Optional[Dict[str, Dict[str, float]]],
           metric: str, model: str) -> float:
    now = cur.get(metric, {}).get(model, 0.0)
    if prev is None:
        return now  # cumulative fallback for the first/only sample
    d = now - prev.get(metric, {}).get(model, 0.0)
    # a negative delta means the server restarted between polls (its
    # cumulative counters reset): the post-restart cumulative value is
    # the honest frame, not a negative QPS
    return now if d < 0 else d


def model_rows(cur: Dict[str, Any], prev: Optional[Dict[str, Any]],
               include_idle: bool = False) -> Dict[str, Dict[str, Any]]:
    """Fold one (or two, for rates) samples into per-model table rows.
    Models that have never served a request are dropped unless
    ``include_idle`` — a zoo registers dozens of models and the operator
    is looking at the ones taking traffic."""
    metrics = cur["metrics"]
    pmetrics = prev["metrics"] if prev else None
    recorder = cur["recorder"]
    dt = (cur["t"] - prev["t"]) if prev else None
    names = set(recorder.get("models", {}))
    for per_model in metrics.values():
        names.update(m for m, v in per_model.items()
                     if include_idle or v > 0)
    last_outlier: Dict[str, dict] = {}
    for o in recorder.get("outliers", []):
        seen = last_outlier.get(o["model"])
        if seen is None or o["seq"] > seen["seq"]:
            last_outlier[o["model"]] = o
    rows: Dict[str, Dict[str, Any]] = {}
    for model in sorted(names):
        succ = _delta(metrics, pmetrics, "nv_inference_request_success", model)
        fail = _delta(metrics, pmetrics, "nv_inference_request_failure", model)
        req_us = _delta(metrics, pmetrics,
                        "nv_inference_request_duration_us", model)
        queue_us = _delta(metrics, pmetrics,
                          "nv_inference_queue_duration_us", model)
        batch_total = _delta(metrics, pmetrics,
                             "nv_inference_batch_size_total", model)
        batch_exec = _delta(metrics, pmetrics,
                            "nv_inference_batch_execution_count", model)
        rejected = _delta(metrics, pmetrics,
                          "nv_inference_rejected_total", model)
        deadline_x = _delta(metrics, pmetrics,
                            "nv_inference_deadline_exceeded_total", model)
        total = succ + fail
        rec = recorder.get("models", {}).get(model, {})
        device = cur.get("device") or {}
        pdevice = (prev.get("device") or {}) if prev else None
        duty = device.get("duty", {}).get(model)
        mfu = device.get("mfu", {}).get(model)
        burn5 = device.get("burn", {}).get((model, "5m"))
        burn1h = device.get("burn", {}).get((model, "1h"))
        inst = device.get("inst", {}).get(model)
        ver = device.get("ver", {}).get(model)
        # autoscale-actuation marker: did nv_fleet_scale_total move for
        # this model between polls?  (Needs a delta base — the first/only
        # sample shows no marker rather than re-flagging history.)
        scaled = ""
        if pdevice is not None:
            for direction, mark in (("out", "^"), ("in", "v")):
                d = (device.get("scale", {}).get((model, direction), 0.0)
                     - pdevice.get("scale", {}).get((model, direction), 0.0))
                if d > 0:
                    scaled += mark
        rows[model] = {
            "qps": round(total / dt, 1) if dt else None,
            "p50_ms": rec.get("p50_ms"),
            "p99_ms": rec.get("p99_ms"),
            "queue_share_pct": (round(100.0 * queue_us / req_us, 1)
                                if req_us > 0 else None),
            "batch_avg": (round(batch_total / batch_exec, 1)
                          if batch_exec > 0 else None),
            "pending": int(metrics.get(
                "nv_inference_pending_request_count", {}).get(model, 0)),
            "error_pct": round(100.0 * fail / total, 2) if total > 0 else None,
            # resilience layer: shed + deadline-dropped rates (cumulative
            # counters on the first/only sample, like qps)
            "rejected_per_s": round(rejected / dt, 1) if dt else None,
            "deadline_exceeded_per_s": (round(deadline_x / dt, 1)
                                        if dt else None),
            "slow_total": rec.get("slow_total", 0),
            "captured_total": rec.get("captured_total", 0),
            "threshold_ms": rec.get("threshold_ms"),
            # device/SLO layer (absent on servers predating it)
            "duty_pct": (round(100.0 * duty, 1)
                         if duty is not None else None),
            "mfu_pct": round(100.0 * mfu, 1) if mfu is not None else None,
            # fleet layer: live instance parallelism, serving version,
            # and whether the autoscaler actuated since the last poll
            "instances": int(inst) if inst is not None else None,
            "version": int(ver) if ver is not None else None,
            "scaled": scaled or None,
            # memory governor (server/memory.py): this model's share of
            # the live byte budget, and its memory-shed rate (cumulative
            # on the first/only sample, like the other counters)
            "mem_pct": (round(100.0 * device.get(
                "mem_inflight", {}).get(model, 0.0)
                / device["mem_budget"], 1)
                if device.get("mem_budget") else None),
            "mem_shed_per_s": (round(_mem_shed_delta(
                device, pdevice, model) / dt, 1) if dt
                else device.get("mem_shed", {}).get(model)),
            # host self-observation (server/profiler.py): process-wide
            # values repeated per row — in the fleet view the worst
            # replica's lag and the summed GC rate survive aggregation
            "host_lag_ms": (round(device["host_lag_us"] / 1e3, 2)
                            if device.get("host_lag_us") is not None
                            else None),
            "gc_ms_per_s": _gc_rate(device, pdevice, dt),
            "burn_5m": round(burn5, 1) if burn5 is not None else None,
            "burn_1h": round(burn1h, 1) if burn1h is not None else None,
            # multi-window breach at the server's exported threshold
            # (nv_slo_burn_threshold): both windows burning — the page
            # condition, matching what the server itself pins on
            "slo_breach": (burn5 is not None and burn1h is not None
                           and burn5 >= device.get("burn_threshold", 14.4)
                           and burn1h >= device.get("burn_threshold", 14.4)),
            # device-fault containment: fault rate between polls
            # (cumulative on the first/only sample) and the quarantine
            # flag — QUAR shows the model is refusing with typed 503s
            "fault_per_s": (round(_fault_delta(device, pdevice, model)
                                  / dt, 1) if dt
                            else device.get("fault", {}).get(model)),
            "quarantined": bool(device.get("quar", {}).get(model, 0.0)),
            # prefix/KV block cache (server/kvcache.py): hit ratio over
            # the poll window (cumulative on the first/only sample) and
            # the MB currently pinned by resident blocks.  Raw deltas
            # ride along unrendered so the fleet fold can recompute the
            # ratio from summed counts instead of averaging percentages.
            "cache_hits_d": _cache_delta(device, pdevice, model,
                                         "cache_hit"),
            "cache_lookups_d": (_cache_delta(device, pdevice, model,
                                             "cache_hit")
                                + _cache_delta(device, pdevice, model,
                                               "cache_miss")),
            "hit_pct": _hit_pct(device, pdevice, model),
            "cache_mb": (round(device["cache_pinned"][model] / 1e6, 1)
                         if model in device.get("cache_pinned", {})
                         else None),
            "last_outlier": _outlier_brief(last_outlier.get(model)),
        }
    return rows


def _cache_delta(device: Dict[str, Any], pdevice: Optional[Dict[str, Any]],
                 model: str, key: str) -> float:
    """Prefix-cache counter movement between polls (cumulative fallback
    on the first sample; counter resets clamp at the new value, same
    contract as ``_delta``)."""
    now = (device.get(key) or {}).get(model, 0.0)
    if pdevice is None:
        return now
    d = now - (pdevice.get(key) or {}).get(model, 0.0)
    return now if d < 0 else d


def _hit_pct(device: Dict[str, Any], pdevice: Optional[Dict[str, Any]],
             model: str) -> Optional[float]:
    """HIT% over the poll window: hits / (hits + misses) * 100, None
    when the model took no cache lookups (or predates the cache) — a
    dash is honest where 0.0 would read as "all misses"."""
    hits = _cache_delta(device, pdevice, model, "cache_hit")
    lookups = hits + _cache_delta(device, pdevice, model, "cache_miss")
    if lookups <= 0:
        return None
    return round(100.0 * hits / lookups, 1)


def _fault_delta(device: Dict[str, Any], pdevice: Optional[Dict[str, Any]],
                 model: str) -> float:
    """nv_device_fault_total movement between polls (summed over fault
    kinds; counter-reset clamps at the new value, like ``_delta``)."""
    now = device.get("fault", {}).get(model, 0.0)
    if pdevice is None:
        return now
    d = now - pdevice.get("fault", {}).get(model, 0.0)
    return now if d < 0 else d


def _gc_rate(device: Dict[str, Any], pdevice: Optional[Dict[str, Any]],
             dt: Optional[float]) -> Optional[float]:
    """GC pause milliseconds per second of wall clock between polls
    (cumulative total in ms on the first/only sample; a counter reset
    clamps at the new value, same contract as ``_delta``)."""
    now = device.get("host_gc_us")
    if now is None:
        return None
    if not dt or pdevice is None:
        return round(now / 1e3, 1)
    d = now - (pdevice.get("host_gc_us") or 0.0)
    if d < 0:
        d = now
    return round(d / 1e3 / dt, 2)


def _mem_shed_delta(device: Dict[str, Any],
                    pdevice: Optional[Dict[str, Any]],
                    model: str) -> float:
    """Memory-shed counter movement between polls (cumulative fallback
    on the first sample; post-restart resets clamp at the new value,
    same contract as ``_delta``)."""
    now = (device.get("mem_shed") or {}).get(model, 0.0)
    if pdevice is None:
        return now
    d = now - (pdevice.get("mem_shed") or {}).get(model, 0.0)
    return now if d < 0 else d


def bucket_rows(cur: Dict[str, Any],
                prev: Optional[Dict[str, Any]]) -> Dict[tuple, Dict[str, Any]]:
    """Per-(model, bucket) tick rows — the buckets view (ROADMAP item 2's
    bucket-geometry tuning surface).  Rates are deltas between polls;
    occupancy/pad-waste/assembly columns are averaged over the delta
    window (cumulative on the first/only sample)."""
    device = cur.get("device") or {}
    pdevice = (prev.get("device") or {}) if prev else {}
    dt = (cur["t"] - prev["t"]) if prev else None
    rows: Dict[tuple, Dict[str, Any]] = {}
    for key, cum in sorted(device.get("buckets", {}).items()):
        pcum = pdevice.get("buckets", {}).get(key)

        def delta(field: str) -> float:
            now = cum.get(field, 0.0)
            if pcum is None:
                return now
            d = now - pcum.get(field, 0.0)
            return now if d < 0 else d  # counter reset = server restart

        ticks = delta("ticks")
        batch, padded = delta("batch"), delta("padded")
        rows[key] = {
            "ticks_per_s": round(ticks / dt, 1) if dt else None,
            "ticks": cum.get("ticks", 0.0),
            "avg_batch": round(batch / ticks, 1) if ticks else None,
            "pad_pct": (round(100.0 * (1.0 - batch / padded), 1)
                        if padded else None),
            "avg_assembly_us": (round(delta("assembly_us") / ticks, 1)
                                if ticks else None),
            "avg_queue_depth": (round(delta("queue_depth") / ticks, 1)
                                if ticks else None),
            "syncs_per_tick": (round(delta("syncs") / ticks, 2)
                               if ticks else None),
            # decode fast-path columns: steps fused per dispatch (the T
            # amortization) and host->device control uploads per tick
            # (~0 in steady-state generation)
            "steps_per_tick": (round(delta("steps") / ticks, 2)
                               if ticks else None),
            "uploads_per_tick": (round(delta("uploads") / ticks, 2)
                                 if ticks else None),
            # roofline gauges (XLA cost analysis): current value, not a
            # delta — absent when the server has no analysis for this
            # bucket (never fabricated)
            "roofline_ai": cum.get("roofline_ai"),
            "roofline_pct": cum.get("roofline_pct"),
            "roofline_verdict": cum.get("roofline_verdict"),
        }
    return rows


def aggregate_buckets(per_url: Dict[str, Dict[tuple, Dict[str, Any]]]
                      ) -> Dict[tuple, Dict[str, Any]]:
    """Fleet buckets view: tick rates sum; occupancy/pad/assembly columns
    take the worst replica (the straggler bucket is the tuning target)."""
    agg: Dict[tuple, Dict[str, Any]] = {}
    keys: set = set()
    for rows in per_url.values():
        keys.update(rows)
    for key in sorted(keys):
        rows = [r[key] for r in per_url.values() if key in r]

        def _sum(field, nd=1):
            vals = [r[field] for r in rows if r.get(field) is not None]
            return round(sum(vals), nd) if vals else None

        def _worst(field):
            vals = [r[field] for r in rows if r.get(field) is not None]
            return max(vals) if vals else None

        def _least(field):
            vals = [r[field] for r in rows if r.get(field) is not None]
            return min(vals) if vals else None

        agg[key] = {
            "ticks_per_s": _sum("ticks_per_s"),
            "ticks": sum(r.get("ticks", 0.0) for r in rows),
            "avg_batch": _worst("avg_batch"),
            "pad_pct": _worst("pad_pct"),
            "avg_assembly_us": _worst("avg_assembly_us"),
            "avg_queue_depth": _worst("avg_queue_depth"),
            "syncs_per_tick": _worst("syncs_per_tick"),
            # steps-per-dispatch: the LEAST-amortized replica is the
            # straggler; uploads take the highest replica — any nonzero
            # steady-state value is the regression smell
            "steps_per_tick": _least("steps_per_tick"),
            "uploads_per_tick": _worst("uploads_per_tick"),
            # roofline: AI is a compile-time property (identical across
            # replicas) — any value serves; the achieved %-of-peak takes
            # the worst (hottest) replica, and its verdict rides along
            "roofline_ai": _worst("roofline_ai"),
            "roofline_pct": _worst("roofline_pct"),
            "roofline_verdict": next(
                (r["roofline_verdict"] for r in rows
                 if r.get("roofline_verdict")), None),
        }
    return agg


def tenant_rows(cur: Dict[str, Any],
                prev: Optional[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Per-tenant QoS rows: request rate plus SHED/s broken down by tier
    (cumulative counters on the first/only sample, like the model rows).
    Empty when the server exposes no tenant-labeled series."""
    qos = cur.get("qos") or {}
    pqos = (prev.get("qos") or {}) if prev else None
    dt = (cur["t"] - prev["t"]) if prev else None

    def delta(kind: str, key: tuple) -> float:
        now = qos.get(kind, {}).get(key, 0.0)
        if pqos is None:
            return now
        d = now - pqos.get(kind, {}).get(key, 0.0)
        return now if d < 0 else d  # counter reset = server restart

    rows: Dict[str, Dict[str, Any]] = {}
    keys = set(qos.get("requests", {})) | set(qos.get("shed", {}))
    for tenant, tier in sorted(keys):
        row = rows.setdefault(tenant, {"req": 0.0, "shed_by_tier": {}})
        row["req"] += delta("requests", (tenant, tier))
        shed = delta("shed", (tenant, tier))
        if shed or (tenant, tier) in qos.get("shed", {}):
            row["shed_by_tier"][tier] = \
                row["shed_by_tier"].get(tier, 0.0) + shed
    for row in rows.values():
        row["req_per_s"] = round(row["req"] / dt, 1) if dt else None
        row["shed_per_s_by_tier"] = {
            t: (round(v / dt, 1) if dt else None)
            for t, v in sorted(row["shed_by_tier"].items())}
    return rows


def aggregate_tenants(per_url: Dict[str, Dict[str, Dict[str, Any]]]
                      ) -> Dict[str, Dict[str, Any]]:
    """Sum per-server tenant rows into fleet rows (all columns additive;
    rate columns sum over the replicas that have a delta base and stay
    None until at least one does — same partial-sum convention as the
    per-model fleet rows)."""
    agg: Dict[str, Dict[str, Any]] = {}
    for rows in per_url.values():
        for tenant, r in rows.items():
            a = agg.setdefault(tenant, {
                "req": 0.0, "shed_by_tier": {},
                "req_per_s": None, "shed_per_s_by_tier": {}})
            a["req"] += r["req"]
            for t, v in r["shed_by_tier"].items():
                a["shed_by_tier"][t] = a["shed_by_tier"].get(t, 0.0) + v
            if r.get("req_per_s") is not None:
                a["req_per_s"] = round(
                    (a["req_per_s"] or 0.0) + r["req_per_s"], 1)
            for t, v in (r.get("shed_per_s_by_tier") or {}).items():
                if v is not None:
                    cur = a["shed_per_s_by_tier"].get(t)
                    a["shed_per_s_by_tier"][t] = round(
                        (cur or 0.0) + v, 1)
    return agg


def _tenant_lines(rows: Dict[str, Dict[str, Any]]) -> List[str]:
    if not rows:
        return []
    rated = any(r.get("req_per_s") is not None for r in rows.values())
    unit = "/s" if rated else " total"
    lines = ["", f"  {'TENANT':<24}{'REQ' + unit:>12}  SHED{unit} by tier"]
    for tenant in sorted(rows):
        r = rows[tenant]
        req = r["req_per_s"] if rated else r["req"]
        shed = (r.get("shed_per_s_by_tier") if rated
                else r["shed_by_tier"]) or {}
        shed_s = "  ".join(
            f"t{t}={_fmt(v)}" for t, v in sorted(shed.items())) or "-"
        lines.append(f"  {tenant:<24}{_fmt(req):>12}  {shed_s}")
    return lines


def cost_rows(cur: Dict[str, Any],
              prev: Optional[Dict[str, Any]]) -> Dict[tuple, Dict[str, Any]]:
    """Per-(model, tenant) cost-attribution rows — the COST view.  Rate
    columns are deltas between polls (cumulative counters on the
    first/only sample); device-time and unit-cost columns derive from
    the same window so they always agree with each other."""
    costs = cur.get("costs") or {}
    pcosts = (prev.get("costs") or {}) if prev else None
    dt = (cur["t"] - prev["t"]) if prev else None
    rows: Dict[tuple, Dict[str, Any]] = {}
    for key, cum in sorted(costs.items()):
        pcum = pcosts.get(key) if pcosts is not None else None

        def delta(field: str) -> float:
            now = cum.get(field, 0.0)
            if pcum is None:
                return now
            d = now - pcum.get(field, 0.0)
            return now if d < 0 else d  # counter reset = server restart

        dev_us, tokens = delta("device_us"), delta("tokens")
        rows[key] = {
            "device_us": round(cum.get("device_us", 0.0), 1),
            "tokens": int(cum.get("tokens", 0.0)),
            "flops": cum.get("flops", 0.0),
            "kv_byte_seconds": round(cum.get("kv_byte_seconds", 0.0), 3),
            # DEVms/s: attributed device-milliseconds per wall second —
            # a tenant's share of the accelerator, directly comparable
            # across tenants and against the duty-cycle column
            "device_ms_per_s": (round(dev_us / dt / 1e3, 2)
                                if dt else None),
            "tokens_per_s": round(tokens / dt, 1) if dt else None,
            "gflops_per_s": (round(delta("flops") / dt / 1e9, 1)
                             if dt else None),
            # unit cost: device-microseconds per generated token over
            # the delta window (the billing-grade efficiency number)
            "us_per_token": (round(dev_us / tokens, 1)
                             if tokens else None),
        }
    return rows


def aggregate_costs(per_url: Dict[str, Dict[tuple, Dict[str, Any]]]
                    ) -> Dict[tuple, Dict[str, Any]]:
    """Sum per-server cost rows into fleet rows (everything here is
    additive work done; rate columns sum over replicas with a delta
    base; unit cost re-derives from the summed window)."""
    agg: Dict[tuple, Dict[str, Any]] = {}
    keys: set = set()
    for rows in per_url.values():
        keys.update(rows)
    for key in sorted(keys):
        rows = [r[key] for r in per_url.values() if key in r]

        def _sum(field, nd=1):
            vals = [r[field] for r in rows if r.get(field) is not None]
            return round(sum(vals), nd) if vals else None

        dev_us = sum(r.get("device_us", 0.0) for r in rows)
        tokens = sum(r.get("tokens", 0) for r in rows)
        agg[key] = {
            "device_us": round(dev_us, 1),
            "tokens": int(tokens),
            "flops": sum(r.get("flops", 0.0) for r in rows),
            "kv_byte_seconds": round(
                sum(r.get("kv_byte_seconds", 0.0) for r in rows), 3),
            "device_ms_per_s": _sum("device_ms_per_s", nd=2),
            "tokens_per_s": _sum("tokens_per_s"),
            "gflops_per_s": _sum("gflops_per_s"),
            "us_per_token": (round(dev_us / tokens, 1)
                             if tokens else None),
        }
    return agg


def _cost_lines(rows: Dict[tuple, Dict[str, Any]]) -> List[str]:
    """The COST view: one line per (model, tenant) with attributed
    device-time, token throughput, FLOP rate, and unit cost — the
    who-is-spending-the-accelerator surface."""
    if not rows:
        return []
    rated = any(r.get("device_ms_per_s") is not None for r in rows.values())
    lines = ["", f"  {'MODEL/TENANT':<24}"
                 + (f"{'DEVms/s':>9}" if rated else f"{'DEVms':>9}")
                 + (f"{'TOK/s':>8}" if rated else f"{'TOKENS':>8}")
                 + (f"{'GFLOP/s':>9}" if rated else "")
                 + f"{'us/TOK':>10}{'KV GB*s':>9}"]
    for (model, tenant), r in sorted(rows.items()):
        label = f"{model}/{tenant or '-'}"
        dev = (r["device_ms_per_s"] if rated
               else round(r["device_us"] / 1e3, 1))
        tok = r["tokens_per_s"] if rated else r["tokens"]
        line = f"  {label:<24}{_fmt(dev, 2):>9}{_fmt(tok):>8}"
        if rated:
            line += f"{_fmt(r['gflops_per_s']):>9}"
        line += (f"{_fmt(r['us_per_token']):>10}"
                 f"{_fmt(r['kv_byte_seconds'] / 1e9, 3):>9}")
        lines.append(line)
    return lines


def aggregate_restarts(per_url: Dict[str, Dict[str, float]]) -> int:
    """Fleet worker-restart total across polled endpoints.  Every
    worker of one supervised fleet reports the SAME fleet-global
    counters (they all read the supervisor's shared state file), so a
    per-endpoint SUM would multiply the truth by the number of polled
    workers — dedup by taking the max per worker label across
    endpoints, then sum workers."""
    per_worker: Dict[str, float] = {}
    for counts in per_url.values():
        for worker, v in (counts or {}).items():
            per_worker[worker] = max(per_worker.get(worker, 0.0), v)
    return int(sum(per_worker.values()))


def _outlier_brief(o: Optional[dict]) -> Optional[Dict[str, Any]]:
    if o is None:
        return None
    # age_s is computed by the SERVER at snapshot time (its clock) —
    # differencing o["ts"] against this host's clock would be wrong under
    # skew; fall back to it only for pre-age_s servers
    age = o.get("age_s")
    if age is None:
        age = round(max(0.0, time.time() - o["ts"]), 1)
    return {
        "seq": o["seq"],
        "age_s": age,
        "total_ms": round(o["total_us"] / 1e3, 2),
        "reason": o.get("capture_reason"),
        "outcome": o.get("outcome"),
        "chaos": o.get("chaos"),
        "request_id": o.get("request_id", ""),
    }


def aggregate_rows(per_url_rows: Dict[str, Dict[str, Dict[str, Any]]]
                   ) -> Dict[str, Dict[str, Any]]:
    """Fold per-server model rows into one fleet row per model.

    Additive columns (QPS, pending, shed/deadline rates, watchdog counts)
    sum; latency/queue/batch/error columns take the WORST replica — an
    operator triaging a fleet needs the tail that users actually see, and
    averaging replicas hides exactly the straggler they're looking for.
    The newest outlier across replicas (smallest server-computed age)
    represents the fleet.
    """
    models: set = set()
    for rows in per_url_rows.values():
        models.update(rows)
    agg: Dict[str, Dict[str, Any]] = {}
    for model in sorted(models):
        rows = [r[model] for r in per_url_rows.values() if model in r]

        def _sum(key, nd=1):
            vals = [r[key] for r in rows if r.get(key) is not None]
            return round(sum(vals), nd) if vals else None

        def _worst(key):
            vals = [r[key] for r in rows if r.get(key) is not None]
            return max(vals) if vals else None

        outliers = [r["last_outlier"] for r in rows
                    if r.get("last_outlier") is not None]
        agg[model] = {
            "qps": _sum("qps"),
            "p50_ms": _worst("p50_ms"),
            "p99_ms": _worst("p99_ms"),
            "queue_share_pct": _worst("queue_share_pct"),
            "batch_avg": _worst("batch_avg"),
            "pending": sum(r["pending"] for r in rows),
            "error_pct": _worst("error_pct"),
            "rejected_per_s": _sum("rejected_per_s"),
            "deadline_exceeded_per_s": _sum("deadline_exceeded_per_s"),
            "slow_total": sum(r["slow_total"] for r in rows),
            "captured_total": sum(r["captured_total"] for r in rows),
            "threshold_ms": _worst("threshold_ms"),
            # device/SLO columns: worst replica (the fleet pages on its
            # hottest/most-burning member, not the average)
            "duty_pct": _worst("duty_pct"),
            "mfu_pct": _worst("mfu_pct"),
            # memory governor: MEM% = worst replica (the one nearest its
            # budget pages first), shed rate sums like the other sheds
            "mem_pct": _worst("mem_pct"),
            "mem_shed_per_s": _sum("mem_shed_per_s"),
            # host columns: LAG takes the worst replica (the stall users
            # on that replica actually feel); the GC rate sums like the
            # other per-process rates
            "host_lag_ms": _worst("host_lag_ms"),
            "gc_ms_per_s": _sum("gc_ms_per_s", nd=2),
            "burn_5m": _worst("burn_5m"),
            "burn_1h": _worst("burn_1h"),
            "slo_breach": any(r.get("slo_breach") for r in rows),
            # fleet columns: instances sum (total executing capacity),
            # version = the newest any replica serves (a mid-rollout
            # fleet shows the front of the wave; the per-server rows
            # underneath show who lags), marker if ANY replica actuated
            "instances": _sum("instances", nd=0),
            "version": _worst("version"),
            "scaled": "".join(sorted({c for r in rows
                                      for c in (r.get("scaled") or "")}),
                              ) or None,
            # device faults sum across replicas; QUAR flags when ANY
            # replica is refusing traffic (the one the client routes
            # around — exactly what the operator should see)
            "fault_per_s": _sum("fault_per_s"),
            "quarantined": any(r.get("quarantined") for r in rows),
            # prefix-cache columns: HIT% recomputed from the SUMMED raw
            # hit/lookup deltas (averaging per-replica percentages would
            # let an idle replica's dash/100% skew the fleet ratio);
            # CACHE-MB sums — each replica pins its own device bytes
            "cache_hits_d": _sum("cache_hits_d"),
            "cache_lookups_d": _sum("cache_lookups_d"),
            "hit_pct": _fleet_hit_pct(rows),
            "cache_mb": _sum("cache_mb"),
            "last_outlier": (min(outliers, key=lambda o: o["age_s"])
                            if outliers else None),
        }
    return agg


def _fleet_hit_pct(rows) -> Optional[float]:
    hits = sum(r.get("cache_hits_d") or 0.0 for r in rows)
    lookups = sum(r.get("cache_lookups_d") or 0.0 for r in rows)
    if lookups <= 0:
        return None
    return round(100.0 * hits / lookups, 1)


# -- rendering ---------------------------------------------------------------

def _fmt(v, nd: int = 1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


_COLUMNS = (f"  {'MODEL':<24}{'QPS':>8}{'P50ms':>9}{'P99ms':>9}{'QUEUE%':>8}"
            f"{'BATCH':>7}{'PEND':>6}{'ERR%':>7}{'REJ/s':>7}{'DLX/s':>7}"
            f"{'SLOW':>6}{'CAPT':>6}{'DUTY%':>7}{'MEM%':>7}{'SHED/s':>8}"
            f"{'INST':>6}{'VER':>5}"
            f"{'LAGms':>8}{'GCms/s':>8}"
            f"{'FAULT':>7}{'QUAR':>6}"
            f"{'HIT%':>7}{'CACHE-MB':>10}"
            f"{'BURN':>9}"
            f"  LAST OUTLIER")


def _row_line(label: str, r: Dict[str, Any]) -> str:
    o = r["last_outlier"]
    brief = ""
    if o is not None:
        brief = (f"{o['age_s']:g}s ago {o['total_ms']:g}ms "
                 f"{o['reason'] or ''}")
        if o.get("chaos"):
            # injected weather, labeled so an operator staring at a
            # spike can tell the chaos harness from the real world
            brief += f" [chaos:{o['chaos']}]"
        if o["outcome"] != "ok":
            brief += f" ({o['outcome'][:40]})"
    # the breach marker rides the burn column: "23.1!" = both windows
    # over the fast-burn threshold (the page condition); the autoscale
    # marker rides next to it — "^" = scaled out since the last poll,
    # "v" = scaled in (the alarm and its actuator, side by side)
    burn = _fmt(r.get("burn_5m"))
    if r.get("slo_breach"):
        burn += "!"
    if r.get("scaled"):
        burn += r["scaled"]
    return (
        f"  {label:<24}{_fmt(r['qps']):>8}{_fmt(r['p50_ms']):>9}"
        f"{_fmt(r['p99_ms']):>9}{_fmt(r['queue_share_pct']):>8}"
        f"{_fmt(r['batch_avg']):>7}{r['pending']:>6}"
        f"{_fmt(r['error_pct'], 2):>7}{_fmt(r['rejected_per_s']):>7}"
        f"{_fmt(r['deadline_exceeded_per_s']):>7}{r['slow_total']:>6}"
        f"{r['captured_total']:>6}{_fmt(r.get('duty_pct')):>7}"
        f"{_fmt(r.get('mem_pct')):>7}{_fmt(r.get('mem_shed_per_s')):>8}"
        f"{_fmt(r.get('instances')):>6}{_fmt(r.get('version')):>5}"
        f"{_fmt(r.get('host_lag_ms'), 2):>8}"
        f"{_fmt(r.get('gc_ms_per_s'), 2):>8}"
        f"{_fmt(r.get('fault_per_s')):>7}"
        f"{('QUAR' if r.get('quarantined') else '-'):>6}"
        f"{_fmt(r.get('hit_pct')):>7}{_fmt(r.get('cache_mb')):>10}"
        f"{burn:>9}  {brief}")


def _bucket_rank(bucket: Any) -> tuple:
    """Numeric-first sort key for bucket labels (Prometheus hands them
    back as strings: "8" must come before "16", not after "128")."""
    try:
        return (0, int(bucket))
    except (TypeError, ValueError):
        return (1, str(bucket))


def _bucket_lines(rows: Dict[tuple, Dict[str, Any]]) -> List[str]:
    """The buckets view: one line per (model, bucket) with tick rate,
    realized occupancy, pad waste, assembly cost, and queue depth — the
    read-the-dashboard surface for bucket-geometry tuning."""
    if not rows:
        return []
    rated = any(r.get("ticks_per_s") is not None for r in rows.values())
    tick_hdr = "TICK/s" if rated else "TICKS"
    lines = ["", f"  {'MODEL/BUCKET':<24}{tick_hdr:>8}{'AVGBATCH':>10}"
                 f"{'PAD%':>7}{'ASM us':>9}{'QDEPTH':>8}{'SYNC/T':>8}"
                 f"{'STEP/T':>8}{'UPL/T':>8}{'AI':>8}  ROOFLINE"]
    for (model, bucket), r in sorted(
            rows.items(), key=lambda kv: (kv[0][0], _bucket_rank(kv[0][1]))):
        ticks = r["ticks_per_s"] if rated else r.get("ticks")
        # roofline verdict + achieved %-of-peak, e.g. "mem 38%": which
        # wall this bucket leans on and how hard it pushes it — "-" when
        # XLA cost analysis is unavailable, never a fabricated value
        verdict = r.get("roofline_verdict")
        if verdict:
            roof = "comp" if verdict == "compute_bound" else "mem"
            if r.get("roofline_pct") is not None:
                roof += f" {r['roofline_pct']:.0f}%"
        else:
            roof = "-"
        lines.append(
            f"  {model + '@' + str(bucket):<24}{_fmt(ticks):>8}"
            f"{_fmt(r['avg_batch']):>10}{_fmt(r['pad_pct']):>7}"
            f"{_fmt(r['avg_assembly_us']):>9}{_fmt(r['avg_queue_depth']):>8}"
            f"{_fmt(r['syncs_per_tick'], 2):>8}"
            f"{_fmt(r.get('steps_per_tick'), 2):>8}"
            f"{_fmt(r.get('uploads_per_tick'), 2):>8}"
            f"{_fmt(r.get('roofline_ai')):>8}  {roof}")
    return lines


def render(url: str, cur: Dict[str, Any],
           rows: Dict[str, Dict[str, Any]], interval: float,
           tenants: Optional[Dict[str, Dict[str, Any]]] = None,
           buckets: Optional[Dict[tuple, Dict[str, Any]]] = None,
           costs: Optional[Dict[tuple, Dict[str, Any]]] = None) -> str:
    recorder = cur["recorder"]
    restarts = int(sum(
        ((cur.get("device") or {}).get("restarts") or {}).values()))
    lines = [
        f"triton-top — {url} — {time.strftime('%H:%M:%S')}  "
        f"refresh={interval:g}s  recorder="
        f"{'on' if recorder.get('enabled') else 'OFF'} "
        f"({recorder.get('capture_slower_than')}, "
        f"{recorder.get('recorded_total', 0)} recorded, "
        f"{len(recorder.get('outliers', []))} outlier(s) pinned)"
        # the self-healing supervisor's scoreboard: nonzero means a
        # frontend worker crashed and was restarted behind this port
        + (f"  worker-restarts={restarts}" if restarts else ""),
        "",
        _COLUMNS,
    ]
    for model, r in rows.items():
        lines.append(_row_line(model, r))
    if not rows:
        lines.append("  (no recorded requests yet)")
    lines.extend(_bucket_lines(buckets or {}))
    lines.extend(_cost_lines(costs or {}))
    lines.extend(_tenant_lines(tenants or {}))
    return "\n".join(lines) + "\n"


def render_fleet(urls: List[str],
                 per_url_rows: Dict[str, Dict[str, Dict[str, Any]]],
                 agg: Dict[str, Dict[str, Any]], interval: float,
                 tenants: Optional[Dict[str, Dict[str, Any]]] = None,
                 buckets: Optional[Dict[tuple, Dict[str, Any]]] = None,
                 costs: Optional[Dict[tuple, Dict[str, Any]]] = None,
                 restarts: int = 0) -> str:
    """Fleet view: one aggregated row per model (sums + worst-replica
    tails) with a per-server breakdown row for every polled endpoint."""
    down = [u for u in urls if u not in per_url_rows]
    header = (f"triton-top — fleet of {len(urls)} "
              f"({len(urls) - len(down)} up) — {time.strftime('%H:%M:%S')}  "
              f"refresh={interval:g}s")
    if restarts:
        header += f"  worker-restarts={restarts}"
    if down:
        header += "  DOWN: " + ", ".join(down)
    lines = [header, "", _COLUMNS]
    for model, row in agg.items():
        lines.append(_row_line(model, row))
        for u in urls:
            rows = per_url_rows.get(u)
            if rows is not None and model in rows:
                lines.append(_row_line(f" └ {u}", rows[model]))
    if not agg:
        lines.append("  (no recorded requests yet)")
    lines.extend(_bucket_lines(buckets or {}))
    lines.extend(_cost_lines(costs or {}))
    lines.extend(_tenant_lines(tenants or {}))
    return "\n".join(lines) + "\n"


def _buckets_json(rows: Dict[tuple, Dict[str, Any]]) -> Dict[str, Any]:
    """Tuple-keyed bucket rows -> ``{model: {bucket: row}}`` for JSON."""
    out: Dict[str, Any] = {}
    for (model, bucket), r in sorted(
            rows.items(), key=lambda kv: (kv[0][0], _bucket_rank(kv[0][1]))):
        out.setdefault(model, {})[str(bucket)] = r
    return out


def _costs_json(rows: Dict[tuple, Dict[str, Any]]) -> Dict[str, Any]:
    """Tuple-keyed cost rows -> ``{model: {tenant: row}}`` for JSON."""
    out: Dict[str, Any] = {}
    for (model, tenant), r in sorted(rows.items()):
        out.setdefault(model, {})[tenant] = r
    return out


# -- CLI --------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="triton-top",
        description="Live per-model console for a running server: polls "
                    "/metrics and /v2/debug/flight_recorder, renders QPS, "
                    "p50/p99, queue share, batch occupancy, error rate, "
                    "and the most recent tail-latency outlier.")
    parser.add_argument("--url", action="append", default=None,
                        help="server host:port (default localhost:8000); "
                             "repeat for a fleet — every server is polled "
                             "and the table aggregates per model with a "
                             "per-server breakdown row")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh interval in seconds (default 2.0)")
    parser.add_argument("--once", action="store_true",
                        help="take one snapshot and exit (rate columns "
                             "fall back to cumulative counters)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit machine-readable JSON instead of the "
                             "table (for scripting; pairs with --once)")
    parser.add_argument("--all", action="store_true", dest="include_idle",
                        help="show every registered model, including ones "
                             "that have never served a request")
    parser.add_argument("--limit", type=int, default=None,
                        help="recent-ring entries fetched per poll "
                             "(default: 0 = whole ring with --once, 1 in "
                             "live mode — the table reads only the "
                             "per-model stats and outliers, so pulling a "
                             "large ring every refresh would be waste)")
    parser.add_argument("--timeout", type=float, default=5.0,
                        help="per-poll HTTP timeout in seconds")
    args = parser.parse_args(argv)

    bases = []
    for u in (args.url or ["localhost:8000"]):
        base = u if "://" in u else f"http://{u}"
        bases.append(base.rstrip("/"))
    fleet = len(bases) > 1
    limit = args.limit if args.limit is not None else (0 if args.once else 1)

    def sample_all(quiet=False):
        """One poll of every server, in parallel — a blackholed replica
        must cost the fleet one --timeout, not one per dead replica per
        refresh.  An unreachable server maps to None — the fleet view
        must survive (and show) a dead replica."""
        # pre-filled: a poll thread that outlives its join timeout must
        # leave its server marked down, not missing from the dict
        out = {base: None for base in bases}
        lock = threading.Lock()

        def poll_one(base):
            try:
                s = sample(base, args.timeout, limit=limit)
            except (urllib.error.URLError, OSError, ValueError) as e:
                s = None
                if not quiet:
                    print(f"error: cannot poll {base}: {e}",
                          file=sys.stderr)
            with lock:
                out[base] = s

        if len(bases) == 1:
            poll_one(bases[0])
            return out
        threads = [threading.Thread(target=poll_one, args=(b,),
                                    daemon=True) for b in bases]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=args.timeout + 5.0)
        return out

    def fold(cur, prev):
        """Per-server rows + the fleet aggregates from one (or two)
        polls; also returns the per-tenant QoS aggregate, the
        (model, bucket) tick aggregate, and the summed supervisor
        worker-restart count."""
        per_url = {}
        per_url_tenants = {}
        per_url_buckets = {}
        per_url_costs = {}
        per_url_restarts = {}
        for base, s in cur.items():
            if s is None:
                continue
            p = prev.get(base) if prev else None
            per_url[base] = model_rows(s, p,
                                       include_idle=args.include_idle)
            per_url_tenants[base] = tenant_rows(s, p)
            per_url_buckets[base] = bucket_rows(s, p)
            per_url_costs[base] = cost_rows(s, p)
            per_url_restarts[base] = (s.get("device") or {}).get(
                "restarts") or {}
        return (per_url, aggregate_rows(per_url),
                aggregate_tenants(per_url_tenants),
                aggregate_buckets(per_url_buckets),
                aggregate_costs(per_url_costs),
                aggregate_restarts(per_url_restarts))

    cur = sample_all()
    if all(s is None for s in cur.values()):
        return 1
    if args.once:
        per_url, agg, tenants, buckets, costs, restarts = fold(cur, None)
        if args.as_json:
            if fleet:
                out = {
                    "urls": bases,
                    "ts": time.time(),
                    "models": agg,
                    "tenants": tenants,
                    "buckets": _buckets_json(buckets),
                    "costs": _costs_json(costs),
                    "worker_restarts": restarts,
                    # per-endpoint samples: each server's rows + recorder
                    "endpoints": {
                        base: (None if cur[base] is None else {
                            "models": per_url.get(base, {}),
                            "recorder": cur[base]["recorder"],
                        }) for base in bases
                    },
                }
            else:
                # single-url shape unchanged (scripting compat); buckets
                # and worker_restarts are additive — new keys, never a
                # reshaped one
                out = {
                    "url": bases[0],
                    "ts": time.time(),
                    "models": per_url.get(bases[0], {}),
                    "tenants": tenants,
                    "buckets": _buckets_json(buckets),
                    "costs": _costs_json(costs),
                    "worker_restarts": restarts,
                    "recorder": cur[bases[0]]["recorder"],
                }
            print(json.dumps(out, indent=2))
        elif fleet:
            sys.stdout.write(render_fleet(bases, per_url, agg,
                                          args.interval, tenants=tenants,
                                          buckets=buckets, costs=costs,
                                          restarts=restarts))
        else:
            sys.stdout.write(render(bases[0], cur[bases[0]],
                                    per_url.get(bases[0], {}),
                                    args.interval, tenants=tenants,
                                    buckets=buckets, costs=costs))
        return 0

    prev = cur
    try:
        while True:
            time.sleep(max(0.05, args.interval))
            cur = sample_all(quiet=True)
            if all(s is None for s in cur.values()):
                # transient blip (deploy, overloaded scrape): keep the
                # console alive and retry — monitoring must not die at
                # exactly the moment the server gets interesting
                continue
            per_url, agg, tenants, buckets, costs, restarts = fold(cur, prev)
            if args.as_json:
                print(json.dumps({
                    "ts": time.time(),
                    "models": agg if fleet else
                              next(iter(per_url.values()), {}),
                    "tenants": tenants,
                    "buckets": _buckets_json(buckets),
                    "costs": _costs_json(costs),
                    "worker_restarts": restarts,
                    **({"endpoints": {b: per_url.get(b)
                                      for b in bases}} if fleet else {}),
                }))
            else:
                # clear screen + home, top(1)-style
                sys.stdout.write("\x1b[H\x1b[2J")
                if fleet:
                    sys.stdout.write(render_fleet(bases, per_url, agg,
                                                  args.interval,
                                                  tenants=tenants,
                                                  buckets=buckets,
                                                  costs=costs,
                                                  restarts=restarts))
                else:
                    sys.stdout.write(render(bases[0], cur[bases[0]],
                                            per_url.get(bases[0], {}),
                                            args.interval,
                                            tenants=tenants,
                                            buckets=buckets,
                                            costs=costs))
                sys.stdout.flush()
            # a server that missed THIS poll keeps its previous sample as
            # the delta base, so its next successful poll shows a sane rate
            prev = {b: (cur[b] if cur[b] is not None else prev.get(b))
                    for b in bases}
    except KeyboardInterrupt:
        return 0
    except BrokenPipeError:
        # downstream consumer closed (e.g. `triton-top --json | head`)
        return 0


if __name__ == "__main__":
    sys.exit(main())
