"""``triton-top``: a top(1)-style live console for a running server.

Polls two HTTP surfaces — ``GET /metrics`` (the Triton-convention
``nv_inference_*`` counters) and ``GET /v2/debug/flight_recorder`` (the
always-on flight recorder's live per-model quantiles + pinned outliers) —
and renders one refreshing per-model table: QPS, p50/p99, queue share,
realized batch, in-flight requests, error rate, watchdog counters, and the
most recent pinned outlier.  "What is the server doing right now" becomes
one command::

    triton-top --url localhost:8000            # live, refresh every 2s
    triton-top --url localhost:8000 --once --json   # one snapshot, JSON

stdlib-only on purpose (same contract as ``trace_summary``): the console
must run — and ``--help`` must exit 0 — on a box with none of the optional
client deps installed.

Rates (QPS, error %, queue share, batch) are deltas between consecutive
polls; ``--once`` takes a single sample, so rate columns fall back to the
cumulative counters (and QPS is null in ``--json``).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

#: nv_* families the table consumes (summed across versions per model).
_METRICS = (
    "nv_inference_request_success",
    "nv_inference_request_failure",
    "nv_inference_request_duration_us",
    "nv_inference_queue_duration_us",
    "nv_inference_batch_size_total",
    "nv_inference_batch_execution_count",
    "nv_inference_pending_request_count",
    "nv_inference_rejected_total",
    "nv_inference_deadline_exceeded_total",
)

_SERIES_RE = re.compile(r'^(\w+)\{([^}]*)\}\s+([0-9.eE+-]+)\s*$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _fetch(url: str, timeout: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def parse_metrics(text: str) -> Dict[str, Dict[str, float]]:
    """Prometheus exposition -> ``{metric: {model: value}}`` for the
    families the table uses, versions summed per model."""
    out: Dict[str, Dict[str, float]] = {m: {} for m in _METRICS}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if not m:
            continue
        name, labels_raw, value = m.groups()
        if name not in out:
            continue
        labels = dict(_LABEL_RE.findall(labels_raw))
        model = labels.get("model", "")
        if not model:
            continue
        out[name][model] = out[name].get(model, 0.0) + float(value)
    return out


def sample(base_url: str, timeout: float, limit: int = 0) -> Dict[str, Any]:
    """One poll of both surfaces, monotonic-stamped for rate deltas."""
    recorder_url = f"{base_url}/v2/debug/flight_recorder"
    if limit:
        recorder_url += f"?limit={int(limit)}"
    return {
        "t": time.monotonic(),
        "metrics": parse_metrics(_fetch(f"{base_url}/metrics", timeout)),
        "recorder": json.loads(_fetch(recorder_url, timeout)),
    }


def _delta(cur: Dict[str, Dict[str, float]],
           prev: Optional[Dict[str, Dict[str, float]]],
           metric: str, model: str) -> float:
    now = cur.get(metric, {}).get(model, 0.0)
    if prev is None:
        return now  # cumulative fallback for the first/only sample
    d = now - prev.get(metric, {}).get(model, 0.0)
    # a negative delta means the server restarted between polls (its
    # cumulative counters reset): the post-restart cumulative value is
    # the honest frame, not a negative QPS
    return now if d < 0 else d


def model_rows(cur: Dict[str, Any], prev: Optional[Dict[str, Any]],
               include_idle: bool = False) -> Dict[str, Dict[str, Any]]:
    """Fold one (or two, for rates) samples into per-model table rows.
    Models that have never served a request are dropped unless
    ``include_idle`` — a zoo registers dozens of models and the operator
    is looking at the ones taking traffic."""
    metrics = cur["metrics"]
    pmetrics = prev["metrics"] if prev else None
    recorder = cur["recorder"]
    dt = (cur["t"] - prev["t"]) if prev else None
    names = set(recorder.get("models", {}))
    for per_model in metrics.values():
        names.update(m for m, v in per_model.items()
                     if include_idle or v > 0)
    last_outlier: Dict[str, dict] = {}
    for o in recorder.get("outliers", []):
        seen = last_outlier.get(o["model"])
        if seen is None or o["seq"] > seen["seq"]:
            last_outlier[o["model"]] = o
    rows: Dict[str, Dict[str, Any]] = {}
    for model in sorted(names):
        succ = _delta(metrics, pmetrics, "nv_inference_request_success", model)
        fail = _delta(metrics, pmetrics, "nv_inference_request_failure", model)
        req_us = _delta(metrics, pmetrics,
                        "nv_inference_request_duration_us", model)
        queue_us = _delta(metrics, pmetrics,
                          "nv_inference_queue_duration_us", model)
        batch_total = _delta(metrics, pmetrics,
                             "nv_inference_batch_size_total", model)
        batch_exec = _delta(metrics, pmetrics,
                            "nv_inference_batch_execution_count", model)
        rejected = _delta(metrics, pmetrics,
                          "nv_inference_rejected_total", model)
        deadline_x = _delta(metrics, pmetrics,
                            "nv_inference_deadline_exceeded_total", model)
        total = succ + fail
        rec = recorder.get("models", {}).get(model, {})
        rows[model] = {
            "qps": round(total / dt, 1) if dt else None,
            "p50_ms": rec.get("p50_ms"),
            "p99_ms": rec.get("p99_ms"),
            "queue_share_pct": (round(100.0 * queue_us / req_us, 1)
                                if req_us > 0 else None),
            "batch_avg": (round(batch_total / batch_exec, 1)
                          if batch_exec > 0 else None),
            "pending": int(metrics.get(
                "nv_inference_pending_request_count", {}).get(model, 0)),
            "error_pct": round(100.0 * fail / total, 2) if total > 0 else None,
            # resilience layer: shed + deadline-dropped rates (cumulative
            # counters on the first/only sample, like qps)
            "rejected_per_s": round(rejected / dt, 1) if dt else None,
            "deadline_exceeded_per_s": (round(deadline_x / dt, 1)
                                        if dt else None),
            "slow_total": rec.get("slow_total", 0),
            "captured_total": rec.get("captured_total", 0),
            "threshold_ms": rec.get("threshold_ms"),
            "last_outlier": _outlier_brief(last_outlier.get(model)),
        }
    return rows


def _outlier_brief(o: Optional[dict]) -> Optional[Dict[str, Any]]:
    if o is None:
        return None
    # age_s is computed by the SERVER at snapshot time (its clock) —
    # differencing o["ts"] against this host's clock would be wrong under
    # skew; fall back to it only for pre-age_s servers
    age = o.get("age_s")
    if age is None:
        age = round(max(0.0, time.time() - o["ts"]), 1)
    return {
        "seq": o["seq"],
        "age_s": age,
        "total_ms": round(o["total_us"] / 1e3, 2),
        "reason": o.get("capture_reason"),
        "outcome": o.get("outcome"),
        "chaos": o.get("chaos"),
        "request_id": o.get("request_id", ""),
    }


# -- rendering ---------------------------------------------------------------

def _fmt(v, nd: int = 1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render(url: str, cur: Dict[str, Any],
           rows: Dict[str, Dict[str, Any]], interval: float) -> str:
    recorder = cur["recorder"]
    lines = [
        f"triton-top — {url} — {time.strftime('%H:%M:%S')}  "
        f"refresh={interval:g}s  recorder="
        f"{'on' if recorder.get('enabled') else 'OFF'} "
        f"({recorder.get('capture_slower_than')}, "
        f"{recorder.get('recorded_total', 0)} recorded, "
        f"{len(recorder.get('outliers', []))} outlier(s) pinned)",
        "",
        f"  {'MODEL':<24}{'QPS':>8}{'P50ms':>9}{'P99ms':>9}{'QUEUE%':>8}"
        f"{'BATCH':>7}{'PEND':>6}{'ERR%':>7}{'REJ/s':>7}{'DLX/s':>7}"
        f"{'SLOW':>6}{'CAPT':>6}"
        f"  LAST OUTLIER",
    ]
    for model, r in rows.items():
        o = r["last_outlier"]
        brief = ""
        if o is not None:
            brief = (f"{o['age_s']:g}s ago {o['total_ms']:g}ms "
                     f"{o['reason'] or ''}")
            if o.get("chaos"):
                # injected weather, labeled so an operator staring at a
                # spike can tell the chaos harness from the real world
                brief += f" [chaos:{o['chaos']}]"
            if o["outcome"] != "ok":
                brief += f" ({o['outcome'][:40]})"
        lines.append(
            f"  {model:<24}{_fmt(r['qps']):>8}{_fmt(r['p50_ms']):>9}"
            f"{_fmt(r['p99_ms']):>9}{_fmt(r['queue_share_pct']):>8}"
            f"{_fmt(r['batch_avg']):>7}{r['pending']:>6}"
            f"{_fmt(r['error_pct'], 2):>7}{_fmt(r['rejected_per_s']):>7}"
            f"{_fmt(r['deadline_exceeded_per_s']):>7}{r['slow_total']:>6}"
            f"{r['captured_total']:>6}  {brief}")
    if not rows:
        lines.append("  (no recorded requests yet)")
    return "\n".join(lines) + "\n"


# -- CLI --------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="triton-top",
        description="Live per-model console for a running server: polls "
                    "/metrics and /v2/debug/flight_recorder, renders QPS, "
                    "p50/p99, queue share, batch occupancy, error rate, "
                    "and the most recent tail-latency outlier.")
    parser.add_argument("--url", default="localhost:8000",
                        help="server host:port (default localhost:8000)")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh interval in seconds (default 2.0)")
    parser.add_argument("--once", action="store_true",
                        help="take one snapshot and exit (rate columns "
                             "fall back to cumulative counters)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit machine-readable JSON instead of the "
                             "table (for scripting; pairs with --once)")
    parser.add_argument("--all", action="store_true", dest="include_idle",
                        help="show every registered model, including ones "
                             "that have never served a request")
    parser.add_argument("--limit", type=int, default=None,
                        help="recent-ring entries fetched per poll "
                             "(default: 0 = whole ring with --once, 1 in "
                             "live mode — the table reads only the "
                             "per-model stats and outliers, so pulling a "
                             "large ring every refresh would be waste)")
    parser.add_argument("--timeout", type=float, default=5.0,
                        help="per-poll HTTP timeout in seconds")
    args = parser.parse_args(argv)

    base = args.url if "://" in args.url else f"http://{args.url}"
    base = base.rstrip("/")
    limit = args.limit if args.limit is not None else (0 if args.once else 1)

    def one_sample():
        try:
            return sample(base, args.timeout, limit=limit)
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"error: cannot poll {base}: {e}", file=sys.stderr)
            return None

    cur = one_sample()
    if cur is None:
        return 1
    if args.once:
        rows = model_rows(cur, None, include_idle=args.include_idle)
        if args.as_json:
            out = {
                "url": base,
                "ts": time.time(),
                "models": rows,
                "recorder": cur["recorder"],
            }
            print(json.dumps(out, indent=2))
        else:
            sys.stdout.write(render(base, cur, rows, args.interval))
        return 0

    prev = cur
    try:
        while True:
            time.sleep(max(0.05, args.interval))
            cur = one_sample()
            if cur is None:
                # transient blip (deploy, overloaded scrape): keep the
                # console alive and retry — monitoring must not die at
                # exactly the moment the server gets interesting
                continue
            rows = model_rows(cur, prev, include_idle=args.include_idle)
            if args.as_json:
                print(json.dumps({"ts": time.time(), "models": rows}))
            else:
                # clear screen + home, top(1)-style
                sys.stdout.write("\x1b[H\x1b[2J")
                sys.stdout.write(render(base, cur, rows, args.interval))
                sys.stdout.flush()
            prev = cur
    except KeyboardInterrupt:
        return 0
    except BrokenPipeError:
        # downstream consumer closed (e.g. `triton-top --json | head`)
        return 0


if __name__ == "__main__":
    sys.exit(main())
