"""Trace-file analyzer (``triton-trace-summary``).

The reference client repo ships ``src/python/examples/trace_summary.py`` as
the canonical consumer of Triton's trace files; this is its analog for the
TPU harness, upgraded for the span-structured records ``RequestTracer``
emits (and still able to digest the legacy flat-timestamp shape).

    python -m triton_client_tpu.tools.trace_summary server.json
    python -m triton_client_tpu.tools.trace_summary server.json \
        --client client.json            # join on triton-request-id
    python -m triton_client_tpu.tools.trace_summary server.json \
        --format chrome -o trace.chrome.json   # load in Perfetto / chrome://tracing

Inputs are JSON Lines:

* **server file** — one object per traced request, written by the server's
  ``RequestTracer`` (``trace_level=TIMESTAMPS`` via the trace-settings API).
  Span-structured records carry ``"spans": [{"name", "start_ns", "end_ns",
  "parent"}, ...]`` with a ``REQUEST`` root; legacy records carry only
  ``"timestamps"`` and get REQUEST/QUEUE/COMPUTE derived from the pairs.
* **client file** — one object per inference, written by
  ``telemetry().enable_tracing(path)`` in any of the four Python clients:
  ``{"request_id", "model", "protocol", "spans": [SERIALIZE, NETWORK,
  DESERIALIZE, ...]}``.

The two files join on the propagated ``triton-request-id`` (the server
record's ``triton_request_id`` key).  The clocks are different processes'
monotonic clocks, so the join compares **durations** only: network overhead
= client REQUEST duration − server REQUEST duration (wire + client stack
time that never shows up server-side).

stdlib-only on purpose: the tool must run (and ``--help`` must exit 0) in an
environment with none of the optional client deps installed.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import math
import os
import re
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: Server-side stages in reporting order (the span taxonomy emitted by the
#: instrumentation points; see docs/ARCHITECTURE.md "Tracing").
SERVER_STAGES = (
    "DECODE",
    "QUEUE",
    "SLOT_WAIT",
    "PREFILL",
    "BATCH_ASSEMBLY",
    "H2D_TRANSFER",
    "COMPUTE",
    "D2H_TRANSFER",
    "SERIALIZE",
    "NETWORK_WRITE",
)
#: Client-side stages recorded by the instrumented clients.
CLIENT_STAGES = ("SERIALIZE", "NETWORK", "DESERIALIZE")


def expand_inputs(paths: Sequence[str]) -> List[str]:
    """Expand a mix of literal paths, globs, and directories into a
    deduplicated file list.  A directory contributes every regular file in
    it (one rotated trace set per directory is the common layout); a glob
    contributes its matches.  Dedup is by ``realpath`` so overlapping
    specs — ``trace.json trace.json*``, or a directory plus a glob into
    it — never double-count a rotated file's records.  A literal path
    with no glob match is kept as-is so ``open()`` fails loudly."""
    out: List[str] = []
    seen = set()
    for p in paths:
        if os.path.isdir(p):
            matches = sorted(
                m for m in _glob.glob(os.path.join(p, "*"))
                if os.path.isfile(m))
        else:
            matches = sorted(m for m in _glob.glob(p) if os.path.isfile(m))
            if not matches and not _glob.has_magic(p):
                matches = [p]
        for m in matches:
            rp = os.path.realpath(m)
            if rp in seen:
                continue
            seen.add(rp)
            out.append(m)
    return out


def load_trace_files(paths: Sequence[str]) -> List[dict]:
    """Load and concatenate every file ``expand_inputs`` resolves from
    ``paths`` (records keep file order; files are visited in the expanded
    order, so a rotated set ``trace.json.0 .1 ...`` reads chronologically)."""
    records: List[dict] = []
    for path in expand_inputs(paths):
        records.extend(load_trace_file(path))
    return records


def load_trace_file(path: str) -> List[dict]:
    """Parse a JSON-Lines trace file; blank lines are skipped, a malformed
    line fails loudly with its line number (a silently-dropped record would
    skew every percentile below)."""
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: not valid JSON: {e}")
            if not isinstance(rec, dict):
                raise ValueError(f"{path}:{lineno}: trace record must be an "
                                 "object")
            records.append(rec)
    return records


def record_spans(rec: dict) -> List[Tuple[str, int, int]]:
    """(name, start_ns, end_ns) intervals of one record.  Span-structured
    records are used as-is; legacy records derive REQUEST and COMPUTE from
    their ``*_START``/``*_END`` timestamp pairs and QUEUE from
    QUEUE_START→COMPUTE_START (the legacy shape never wrote a QUEUE_END)."""
    spans = rec.get("spans")
    if spans:
        return [(s["name"], int(s["start_ns"]), int(s["end_ns"]))
                for s in spans]
    ts: Dict[str, int] = {}
    for t in rec.get("timestamps", []):
        ts.setdefault(str(t["name"]), int(t["ns"]))
    out: List[Tuple[str, int, int]] = []
    for name in {n[: -len("_START")] for n in ts if n.endswith("_START")}:
        start = ts.get(name + "_START")
        end = ts.get(name + "_END")
        if end is None and name == "QUEUE":
            end = ts.get("COMPUTE_START")
        if start is not None and end is not None:
            out.append((name, start, end))
    out.sort(key=lambda s: (s[1], s[0]))
    return out


def token_events(rec: dict) -> List[Tuple[int, int]]:
    """(token index, ns) pairs of a stream record's strided token
    timeline: ``FIRST_TOKEN`` is index 0, ``TOKEN[n]`` is index n.  Sorted
    by index; empty for unary records."""
    out: List[Tuple[int, int]] = []
    for t in rec.get("timestamps", []):
        name = str(t.get("name", ""))
        if name == "FIRST_TOKEN":
            out.append((0, int(t["ns"])))
        elif name.startswith("TOKEN[") and name.endswith("]"):
            try:
                out.append((int(name[len("TOKEN["):-1]), int(t["ns"])))
            except ValueError:
                continue
    out.sort()
    return out


def percentile(sorted_vals: Sequence[float], p: float) -> float:
    """Nearest-rank percentile over an already-sorted sequence."""
    if not sorted_vals:
        return float("nan")
    rank = max(1, math.ceil(p / 100.0 * len(sorted_vals)))
    return sorted_vals[min(rank, len(sorted_vals)) - 1]


def _stage_stats(durations_ns: List[int]) -> Dict[str, Any]:
    vals = sorted(durations_ns)
    n = len(vals)
    if not n:
        # None, not NaN: summaries embed into strict-JSON exports
        # (perf_analyzer --export-metrics, bench.py)
        return {"count": 0, "mean_us": None, "p50_us": None,
                "p90_us": None, "p99_us": None}
    return {
        "count": n,
        "mean_us": (sum(vals) / n) / 1e3,
        "p50_us": percentile(vals, 50) / 1e3,
        "p90_us": percentile(vals, 90) / 1e3,
        "p99_us": percentile(vals, 99) / 1e3,
    }


def summarize(server_records: List[dict],
              client_records: Optional[List[dict]] = None) -> Dict[str, Any]:
    """Aggregate trace records into the summary structure the text renderer
    (and ``--format json``) prints: per-model stage stats, queue share, and
    — when a client file is joined — network-overhead stats."""
    models: Dict[str, Dict[str, Any]] = {}
    per_model_stage: Dict[str, Dict[str, List[int]]] = {}
    per_model_request: Dict[str, List[int]] = {}
    # per-model generation timeline stats (stream records: "tokens" +
    # FIRST_TOKEN / strided TOKEN[n] events) — TTFT is first token vs the
    # REQUEST root, ITL is recovered from the strided gaps as
    # (t[n+k]-t[n])/k so any stride yields per-token estimates
    per_model_gen: Dict[str, Dict[str, Any]] = {}
    # (model, bucket) -> accumulated tick fields (records that rode the
    # dynamic batcher carry a "tick" object: bucket chosen, occupancy,
    # pad waste, queue depth, assembly cost)
    per_bucket: Dict[Tuple[str, int], Dict[str, Any]] = {}
    # model -> tenant -> accumulated cost stamps (records attributed by
    # the cost ledger carry a "cost" object: tenant, device_us, tokens)
    per_model_cost: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for rec in server_records:
        model = str(rec.get("model_name", "?"))
        stages = per_model_stage.setdefault(model, {})
        root_start = None
        for name, start, end in record_spans(rec):
            dur = max(0, end - start)
            if name == "REQUEST":
                per_model_request.setdefault(model, []).append(dur)
                root_start = start
            else:
                stages.setdefault(name, []).append(dur)
        if "tokens" in rec:
            g = per_model_gen.setdefault(model, {
                "streams": 0, "tokens": 0, "failed": 0, "cancelled": 0,
                "ttft": [], "itl": []})
            g["streams"] += 1
            g["tokens"] += int(rec.get("tokens") or 0)
            outcome = str(rec.get("outcome", "ok"))
            if outcome == "cancelled":
                # consumer walked away mid-stream — served, not failed
                g["cancelled"] += 1
            elif outcome != "ok":
                g["failed"] += 1
            evs = token_events(rec)
            if evs and root_start is not None:
                g["ttft"].append(max(0, evs[0][1] - root_start))
            for (n0, t0), (n1, t1) in zip(evs, evs[1:]):
                if n1 > n0:
                    g["itl"].append(max(0, (t1 - t0) // (n1 - n0)))
        cost = rec.get("cost")
        if isinstance(cost, dict):
            c = per_model_cost.setdefault(model, {}).setdefault(
                str(cost.get("tenant", "")),
                {"records": 0, "device_us": 0.0, "tokens": 0})
            c["records"] += 1
            c["device_us"] += float(cost.get("device_us") or 0.0)
            c["tokens"] += int(cost.get("tokens") or 0)
        tick = rec.get("tick")
        if isinstance(tick, dict) and "bucket" in tick:
            agg = per_bucket.setdefault((model, int(tick["bucket"])), {
                "records": 0, "batch": [], "pad": [], "depth": [],
                "assembly_us": []})
            agg["records"] += 1
            for field, key in (("batch", "batch"), ("pad", "pad_fraction"),
                               ("depth", "queue_depth"),
                               ("assembly_us", "assembly_us")):
                if key in tick:
                    agg[field].append(float(tick[key]))
    for model, stages in per_model_stage.items():
        requests = per_model_request.get(model, [])
        total_request_ns = sum(requests)
        stage_out: Dict[str, Any] = {}
        order = [s for s in SERVER_STAGES if s in stages] + sorted(
            s for s in stages if s not in SERVER_STAGES)
        for name in order:
            st = _stage_stats(stages[name])
            st["share_pct"] = (100.0 * sum(stages[name]) / total_request_ns
                               if total_request_ns else None)
            stage_out[name] = st
        entry: Dict[str, Any] = {
            "count": len(requests) or max(
                (len(v) for v in stages.values()), default=0),
            "request": _stage_stats(requests),
            "stages": stage_out,
        }
        if "QUEUE" in stage_out:
            entry["queue_share_pct"] = stage_out["QUEUE"]["share_pct"]
        models[model] = entry
    for model, g in per_model_gen.items():
        entry = models.setdefault(model, {"count": 0, "request":
                                          _stage_stats([]), "stages": {}})
        entry["generation"] = {
            "streams": g["streams"],
            "tokens": g["tokens"],
            "failed": g["failed"],
            "cancelled": g["cancelled"],
            "ttft_us": _stage_stats(g["ttft"]),
            "itl_us": _stage_stats(g["itl"]),
        }
    for (model, bucket), agg in sorted(per_bucket.items()):
        entry = models.setdefault(model, {"count": 0, "request":
                                          _stage_stats([]), "stages": {}})
        n = agg["records"]

        def _avg(vals):
            return round(sum(vals) / len(vals), 2) if vals else None

        entry.setdefault("buckets", {})[str(bucket)] = {
            "records": n,
            "avg_batch": _avg(agg["batch"]),
            "pad_waste_pct": (round(100.0 * sum(agg["pad"]) / len(agg["pad"]),
                                    1) if agg["pad"] else None),
            "avg_queue_depth": _avg(agg["depth"]),
            "avg_assembly_us": _avg(agg["assembly_us"]),
        }
    for model, tenants in sorted(per_model_cost.items()):
        entry = models.setdefault(model, {"count": 0, "request":
                                          _stage_stats([]), "stages": {}})
        # per-tenant attributed device-time over the SAMPLED records only
        # (the cost ledger's /v2/debug/costs is the complete total; this
        # table shows what the traced subset spent)
        entry["costs"] = {
            t: {"records": c["records"],
                "device_us": round(c["device_us"], 1),
                "tokens": c["tokens"],
                "us_per_token": (round(c["device_us"] / c["tokens"], 1)
                                 if c["tokens"] else None)}
            for t, c in sorted(tenants.items())}
    summary: Dict[str, Any] = {
        "requests": len(server_records),
        "models": {m: models[m] for m in sorted(models)},
    }
    if client_records is not None:
        summary["join"] = _join(server_records, client_records)
        journeys = _journeys(server_records, client_records)
        if journeys is not None:
            summary["journeys"] = journeys
    return summary


_TRACEPARENT_RE = re.compile(
    r"\A[0-9a-f]{2}-([0-9a-f]{32})-[0-9a-f]{16}-[0-9a-f]{2}\Z")


def trace_id_of(rec: dict) -> str:
    """The 32-hex trace id of a record's ``traceparent``, or "".  The
    JOURNEY join key: client attempt records mint a fresh span id per
    attempt but share one trace id, so joining on the full traceparent
    would split one journey into its attempts."""
    m = _TRACEPARENT_RE.match(str(rec.get("traceparent", "")))
    return m.group(1) if m else ""


def _journeys(server_records: List[dict],
              client_records: List[dict]) -> Optional[Dict[str, Any]]:
    """Reconstruct request journeys: every client record (attempts, RETRY
    backoffs, HEDGE wins, BREAKER_OPEN/ENDPOINT_SWITCH events) and every
    server record (successes and refusals) carrying the same trace id is
    one caller-visible request's story.  Returns None when no client
    record carries a traceparent (pre-journey trace files)."""
    jmap: Dict[str, Dict[str, Any]] = {}
    for rec in client_records:
        tid = trace_id_of(rec)
        if not tid:
            continue
        j = jmap.setdefault(tid, {"attempts": [], "events": {}, "hedge_wins": 0})
        names = [str(s.get("name", "")) for s in rec.get("spans", [])]
        if "REQUEST" in names:
            j["attempts"].append(rec)
        elif "HEDGE" in names:
            # only hedge WINS are recorded (the backup answered first);
            # fired-but-lost hedges show up as overlapping attempts below
            j["hedge_wins"] += 1
        else:
            for name in names:
                j["events"][name] = j["events"].get(name, 0) + 1
    if not jmap:
        return None
    smap: Dict[str, List[dict]] = {}
    for rec in server_records:
        tid = trace_id_of(rec)
        if tid:
            smap.setdefault(tid, []).append(rec)

    def _request_span(rec: dict) -> Optional[Tuple[int, int]]:
        for name, start, end in record_spans(rec):
            if name == "REQUEST":
                return start, end
        return None

    complete = 0
    attempts_per_success: List[int] = []
    replica_counts: List[int] = []
    cross_replica = 0
    retry_added_ns: List[int] = []
    hedges_fired = 0
    hedge_wins = 0
    shed_journeys = 0
    shed_converted = 0
    event_totals: Dict[str, int] = {}
    for tid, j in jmap.items():
        spans = [(_request_span(a), bool(a.get("ok", True)))
                 for a in j["attempts"]]
        spans = [(iv, ok) for iv, ok in spans if iv is not None]
        success = any(ok for _, ok in spans)
        if success:
            complete += 1
            attempts_per_success.append(len(j["attempts"]))
            if len(spans) > 1:
                # wall-clock the retries added on the CLIENT clock: the
                # whole journey envelope (first attempt start -> last
                # attempt end, backoff sleeps included) minus the winning
                # attempt's own duration
                lo = min(s for (s, _), _ in spans)
                hi = max(e for (_, e), _ in spans)
                win = max(e - s for (s, e), ok in spans if ok)
                retry_added_ns.append(max(0, (hi - lo) - win))
        ordered = sorted(iv for iv, _ in spans)
        overlapped = any(b_start < a_end for (_, a_end), (b_start, _)
                        in zip(ordered, ordered[1:]))
        if overlapped or j["hedge_wins"]:
            hedges_fired += 1
        if j["hedge_wins"]:
            hedge_wins += 1
        sjoin = smap.get(tid, [])
        replicas = {str(r.get("replica", "")) for r in sjoin
                    if r.get("replica")}
        if replicas:
            replica_counts.append(len(replicas))
            if len(replicas) > 1:
                cross_replica += 1
        if any(r.get("refused") for r in sjoin):
            shed_journeys += 1
            if success:
                shed_converted += 1
        for name, count in j["events"].items():
            event_totals[name] = event_totals.get(name, 0) + count
    n = len(jmap)
    counts = sorted(attempts_per_success)
    return {
        "count": n,
        "complete": complete,
        "attempts_per_success": {
            "mean": (round(sum(counts) / len(counts), 2) if counts
                     else None),
            "p50": percentile(counts, 50) if counts else None,
            "p99": percentile(counts, 99) if counts else None,
            "max": counts[-1] if counts else None,
        },
        "replicas_per_journey": {
            "mean": (round(sum(replica_counts) / len(replica_counts), 2)
                     if replica_counts else None),
            "max": max(replica_counts) if replica_counts else None,
            "cross_replica_journeys": cross_replica,
        },
        "retry_added_us": _stage_stats(retry_added_ns),
        "hedge": {
            "fired": hedges_fired,
            "wins": hedge_wins,
            "win_rate_pct": (round(100.0 * hedge_wins / hedges_fired, 1)
                             if hedges_fired else None),
        },
        "sheds": {
            "journeys_shed": shed_journeys,
            "converted": shed_converted,
            "conversion_pct": (round(100.0 * shed_converted / shed_journeys,
                                     1) if shed_journeys else None),
        },
        "events": dict(sorted(event_totals.items())),
        # server trace ids with no client-side journey: traffic from
        # un-instrumented callers (or a client file that wasn't collected)
        "orphan_server_traces": sum(1 for t in smap if t not in jmap),
    }


def _join(server_records: List[dict],
          client_records: List[dict]) -> Dict[str, Any]:
    def request_dur(spans):
        for name, start, end in spans:
            if name == "REQUEST":
                return max(0, end - start)
        return None

    client_by_id: Dict[str, dict] = {}
    for rec in client_records:
        rid = str(rec.get("request_id", ""))
        if rid:
            client_by_id.setdefault(rid, rec)
    overhead_ns: List[int] = []
    joined = 0
    for rec in server_records:
        rid = str(rec.get("triton_request_id", ""))
        crec = client_by_id.get(rid)
        if crec is None:
            continue
        joined += 1
        sdur = request_dur(record_spans(rec))
        cdur = request_dur(
            [(s["name"], int(s["start_ns"]), int(s["end_ns"]))
             for s in crec.get("spans", [])])
        if sdur is not None and cdur is not None:
            overhead_ns.append(cdur - sdur)
    client_stages: Dict[str, List[int]] = {}
    for rec in client_records:
        for s in rec.get("spans", []):
            name = str(s["name"])
            if name == "REQUEST":
                continue
            client_stages.setdefault(name, []).append(
                max(0, int(s["end_ns"]) - int(s["start_ns"])))
    order = [s for s in CLIENT_STAGES if s in client_stages] + sorted(
        s for s in client_stages if s not in CLIENT_STAGES)
    return {
        "client_requests": len(client_records),
        "joined": joined,
        # wire + client-stack time invisible to the server: the honest
        # "how much latency is NOT the server" number
        "network_overhead_us": _stage_stats(overhead_ns),
        "client_stages": {name: _stage_stats(client_stages[name])
                          for name in order},
    }


# -- text rendering ---------------------------------------------------------

def _fmt_val(v) -> str:
    return "-" if v is None or v != v else f"{v:.1f}"  # None/NaN-safe


def _stage_table(rows: List[Tuple[str, Dict[str, float]]],
                 share: bool) -> List[str]:
    head = (f"  {'stage':<16}{'count':>7}{'mean_us':>12}{'p50_us':>12}"
            f"{'p90_us':>12}{'p99_us':>12}")
    if share:
        head += f"{'share%':>9}"
    lines = [head]
    for name, st in rows:
        line = (f"  {name:<16}{st['count']:>7}{_fmt_val(st['mean_us']):>12}"
                f"{_fmt_val(st['p50_us']):>12}{_fmt_val(st['p90_us']):>12}"
                f"{_fmt_val(st['p99_us']):>12}")
        if share:
            line += f"{_fmt_val(st.get('share_pct', float('nan'))):>9}"
        lines.append(line)
    return lines


def format_text(summary: Dict[str, Any]) -> str:
    lines: List[str] = []
    n_models = len(summary["models"])
    lines.append(f"== server trace: {summary['requests']} request(s), "
                 f"{n_models} model(s) ==")
    for model, entry in summary["models"].items():
        lines.append("")
        lines.append(f"model={model}  requests={entry['count']}")
        req = entry["request"]
        lines.append(
            f"  {'REQUEST':<16}{req['count']:>7}"
            f"{_fmt_val(req['mean_us']):>12}{_fmt_val(req['p50_us']):>12}"
            f"{_fmt_val(req['p90_us']):>12}{_fmt_val(req['p99_us']):>12}")
        lines.extend(_stage_table(list(entry["stages"].items()), share=True))
        if "queue_share_pct" in entry:
            lines.append(
                f"  queue share: "
                f"{_fmt_val(entry['queue_share_pct'])}% of request time")
        gen = entry.get("generation")
        if gen:
            ttft, itl = gen["ttft_us"], gen["itl_us"]
            lines.append(
                f"  generation: streams={gen['streams']} "
                f"tokens={gen['tokens']} failed={gen['failed']} "
                f"cancelled={gen['cancelled']}")
            lines.append(
                f"    TTFT us: p50 {_fmt_val(ttft['p50_us'])}  "
                f"p99 {_fmt_val(ttft['p99_us'])}   "
                f"ITL us: p50 {_fmt_val(itl['p50_us'])}  "
                f"p99 {_fmt_val(itl['p99_us'])}")
        buckets = entry.get("buckets")
        if buckets:
            # the buckets view: which tick shapes the sampled requests
            # rode, at what occupancy/pad waste — bucket-geometry tuning
            # reads straight off this table
            lines.append(f"  {'bucket':<10}{'records':>9}{'avg_batch':>11}"
                         f"{'pad%':>7}{'qdepth':>8}{'asm_us':>9}")
            for bucket, b in sorted(buckets.items(), key=lambda kv:
                                    int(kv[0])):
                lines.append(
                    f"  {bucket:<10}{b['records']:>9}"
                    f"{_fmt_val(b['avg_batch']):>11}"
                    f"{_fmt_val(b['pad_waste_pct']):>7}"
                    f"{_fmt_val(b['avg_queue_depth']):>8}"
                    f"{_fmt_val(b['avg_assembly_us']):>9}")
        costs = entry.get("costs")
        if costs:
            # who spent the device time among the traced requests — the
            # sampled-view companion to /v2/debug/costs
            lines.append(f"  {'tenant':<16}{'records':>9}{'device_us':>12}"
                         f"{'tokens':>8}{'us/tok':>8}")
            for tenant, c in costs.items():
                lines.append(
                    f"  {tenant or '-':<16}{c['records']:>9}"
                    f"{_fmt_val(c['device_us']):>12}{c['tokens']:>8}"
                    f"{_fmt_val(c['us_per_token']):>8}")
    join = summary.get("join")
    if join is not None:
        lines.append("")
        lines.append(
            f"== client join: {join['joined']}/{summary['requests']} server "
            f"trace(s) joined on request id ==")
        ov = join["network_overhead_us"]
        lines.append(
            "  network overhead (client REQUEST - server REQUEST): "
            f"count {ov['count']}  mean_us {_fmt_val(ov['mean_us'])}  "
            f"p50_us {_fmt_val(ov['p50_us'])}  "
            f"p99_us {_fmt_val(ov['p99_us'])}")
        lines.extend(
            _stage_table(list(join["client_stages"].items()), share=False))
    jo = summary.get("journeys")
    if jo is not None:
        lines.append("")
        lines.append(f"== journeys: {jo['count']} trace id(s), "
                     f"{jo['complete']} complete ==")
        a = jo["attempts_per_success"]
        lines.append(
            f"  attempts/success: mean {_fmt_val(a['mean'])}  "
            f"p50 {_fmt_val(a['p50'])}  p99 {_fmt_val(a['p99'])}  "
            f"max {a['max'] if a['max'] is not None else '-'}")
        r = jo["replicas_per_journey"]
        lines.append(
            f"  replicas/journey: mean {_fmt_val(r['mean'])}  "
            f"max {r['max'] if r['max'] is not None else '-'}  "
            f"cross-replica journeys {r['cross_replica_journeys']}")
        ra = jo["retry_added_us"]
        lines.append(
            f"  retry-added latency us ({ra['count']} multi-attempt "
            f"journey(s)): p50 {_fmt_val(ra['p50_us'])}  "
            f"p99 {_fmt_val(ra['p99_us'])}")
        h = jo["hedge"]
        lines.append(
            f"  hedges: fired {h['fired']}  wins {h['wins']}  "
            f"win rate {_fmt_val(h['win_rate_pct'])}%")
        s = jo["sheds"]
        lines.append(
            f"  sheds: {s['journeys_shed']} journey(s) shed, "
            f"{s['converted']} converted to success "
            f"({_fmt_val(s['conversion_pct'])}%)")
        if jo["events"]:
            lines.append("  events: " + "  ".join(
                f"{k}={v}" for k, v in jo["events"].items()))
        if jo["orphan_server_traces"]:
            lines.append(f"  orphan server traces (no client journey): "
                         f"{jo['orphan_server_traces']}")
    return "\n".join(lines) + "\n"


# -- Chrome trace-event export ----------------------------------------------

def chrome_trace(server_records: List[dict],
                 client_records: Optional[List[dict]] = None) -> Dict[str, Any]:
    """Chrome trace-event JSON (the object form: ``{"traceEvents": [...]}``)
    loadable in Perfetto / chrome://tracing.  Server and client records get
    separate pids (their monotonic clocks do not align); timestamps are
    rebased per source so the view starts at t=0.

    Stream records additionally render:

    * **token instants** (``FIRST_TOKEN`` / strided ``TOKEN[n]``) on the
      sequence's own lane, and
    * a **decode-worker pid** with one lane per (model, bucket) holding a
      span per fused dispatch (deduped on ``tick_seq`` across the traced
      sequences that rode it), occupancy in ``args``.

    Sequence lanes and tick lanes join on ``tick_seq`` — each sequence
    span carries its ``tick_seqs`` list, each tick span its ``tick_seq``
    — so pad-waste and prefill/decode interleaving read visually."""
    events: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "server"}},
    ]

    # one shared base for EVERY server-side lane (request spans, token
    # instants, decode ticks live on the same monotonic clock — rebasing
    # them separately would break the visual tick<->sequence alignment
    # this view exists for)
    ticks: Dict[Tuple[str, int], dict] = {}
    starts = []
    for rec in server_records:
        starts.extend(s for _, s, _ in record_spans(rec))
        starts.extend(ns for _, ns in token_events(rec))
        model = str(rec.get("model_name", ""))
        for t in rec.get("ticks", []):
            if "tick_seq" in t:
                ticks.setdefault((model, int(t["tick_seq"])), t)
    starts.extend(int(t.get("start_ns", 0)) for t in ticks.values())
    base = min(starts) if starts else 0

    for rec in server_records:
        tid = int(rec.get("id", 0))
        args: Dict[str, Any] = {
            "model": rec.get("model_name", ""),
            "request_id": rec.get("triton_request_id", "")}
        seqs = sorted({int(t["tick_seq"]) for t in rec.get("ticks", [])
                       if "tick_seq" in t})
        if seqs:
            args["tick_seqs"] = seqs
        if "outcome" in rec:
            args["outcome"] = rec["outcome"]
        cost = rec.get("cost")
        for name, start, end in record_spans(rec):
            span_args = args
            if isinstance(cost, dict) and name in ("COMPUTE", "DECODE"):
                # cost stamps ride the device-time spans: click a
                # COMPUTE/DECODE slice in Perfetto and read who paid
                # for it and at what unit cost
                span_args = dict(args)
                for k in ("tenant", "device_us", "tokens"):
                    if k in cost:
                        span_args[f"cost_{k}"] = cost[k]
            events.append({
                "name": name,
                "ph": "X",
                "ts": (start - base) / 1e3,       # microseconds
                "dur": max(0, end - start) / 1e3,
                "pid": 1,
                "tid": tid,
                "cat": "server",
                "args": span_args,
            })
        for n, ns in token_events(rec):
            events.append({
                "name": "FIRST_TOKEN" if n == 0 else f"TOKEN[{n}]",
                "ph": "i",
                "s": "t",                         # thread-scoped instant
                "ts": (ns - base) / 1e3,
                "pid": 1,
                "tid": tid,
                "cat": "server",
                "args": {"token": n},
            })

    if ticks:
        events.append({"ph": "M", "name": "process_name", "pid": 3,
                       "args": {"name": "decode worker"}})
        lanes: Dict[Tuple[str, int], int] = {}
        for (model, seq), t in sorted(ticks.items()):
            lane = lanes.setdefault((model, int(t.get("bucket", 0))),
                                    len(lanes) + 1)
            events.append({
                "name": f"tick {seq}",
                "ph": "X",
                "ts": (int(t.get("start_ns", 0)) - base) / 1e3,
                "dur": max(0, int(t.get("end_ns", 0))
                           - int(t.get("start_ns", 0))) / 1e3,
                "pid": 3,
                "tid": lane,
                "cat": "tick",
                "args": {"model": model,
                         **{k: t[k] for k in ("tick_seq", "bucket", "batch",
                                              "padded", "steps", "requests")
                            if k in t}},
            })

    if client_records is not None:
        events.insert(1, {"ph": "M", "name": "process_name", "pid": 2,
                          "args": {"name": "client"}})
        tids: Dict[str, int] = {}
        cstarts = [s for rec in client_records
                   for _, s, _ in record_spans(rec)]
        cbase = min(cstarts) if cstarts else 0
        for rec in client_records:
            rid = str(rec.get("request_id", ""))
            ctid = tids.setdefault(rid, len(tids) + 1)
            for name, start, end in record_spans(rec):
                events.append({
                    "name": name,
                    "ph": "X",
                    "ts": (start - cbase) / 1e3,
                    "dur": max(0, end - start) / 1e3,
                    "pid": 2,
                    "tid": ctid,
                    "cat": "client",
                    "args": {"model": rec.get("model", ""),
                             "request_id": rid},
                })
        # journey lanes: one pid per trace id, the client's attempts on
        # lane 0 and one lane per replica the journey touched, all on ONE
        # rebased clock — each joined server record is shifted onto the
        # client clock by aligning its REQUEST start with the wire time of
        # the attempt that reached it (exact traceparent match)
        events.extend(_journey_lanes(server_records, client_records))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


#: first journey pid in the chrome export (server=1, client=2, decode
#: worker=3 — journeys start far above so added fixed pids never collide)
JOURNEY_PID_BASE = 100


def _journey_lanes(server_records: List[dict],
                   client_records: List[dict]) -> List[dict]:
    jmap: Dict[str, List[dict]] = {}
    for rec in client_records:
        tid = trace_id_of(rec)
        if tid:
            jmap.setdefault(tid, []).append(rec)
    if not jmap:
        return []
    smap: Dict[str, List[dict]] = {}
    for rec in server_records:
        tid = trace_id_of(rec)
        if tid:
            smap.setdefault(tid, []).append(rec)
    events: List[dict] = []
    pid = JOURNEY_PID_BASE
    for tid in sorted(jmap):
        crecs = jmap[tid]
        pid += 1
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "args": {"name": f"journey {tid[:8]}"}})
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": 0, "args": {"name": "client"}})
        cstarts = [s for rec in crecs for _, s, _ in record_spans(rec)]
        base = min(cstarts) if cstarts else 0
        # the wire anchor of each attempt: its NETWORK span start (fall
        # back to REQUEST start), keyed by the attempt's full traceparent
        anchors: Dict[str, int] = {}
        for rec in crecs:
            tp = str(rec.get("traceparent", ""))
            spans = {name: start for name, start, _ in record_spans(rec)}
            if tp and ("NETWORK" in spans or "REQUEST" in spans):
                anchors.setdefault(
                    tp, spans.get("NETWORK", spans.get("REQUEST", 0)))
        for rec in crecs:
            attempt = rec.get("attempt")
            for name, start, end in record_spans(rec):
                ev = {
                    "name": name,
                    "ts": (start - base) / 1e3,
                    "pid": pid,
                    "tid": 0,
                    "cat": "journey",
                    "args": {"model": rec.get("model", ""),
                             "request_id": rec.get("request_id", "")},
                }
                if attempt is not None:
                    ev["args"]["attempt"] = attempt
                if end > start:
                    ev.update(ph="X", dur=(end - start) / 1e3)
                else:
                    # zero-duration journey event (BREAKER_OPEN, ...)
                    ev.update(ph="i", s="t")
                events.append(ev)
        lanes: Dict[str, int] = {}
        for rec in smap.get(tid, []):
            spans = record_spans(rec)
            root = next((s for s in spans if s[0] == "REQUEST"), None)
            if root is None:
                continue
            anchor = anchors.get(str(rec.get("traceparent", "")))
            # server clock -> client clock: the attempt hit the wire at
            # `anchor`, the server opened its root at root start.  With no
            # exact attempt match the record sits at the journey origin.
            offset = (anchor - root[1]) if anchor is not None else (base - root[1])
            replica = str(rec.get("replica", "")) or "server"
            lane = lanes.get(replica)
            if lane is None:
                lane = lanes[replica] = len(lanes) + 1
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": lane,
                               "args": {"name": replica}})
            args: Dict[str, Any] = {"model": rec.get("model_name", "")}
            for key in ("outcome", "shed_reason"):
                if key in rec:
                    args[key] = rec[key]
            for name, start, end in spans:
                ev = {
                    "name": ("REFUSED" if name == "REQUEST"
                             and rec.get("refused") else name),
                    "ts": (start + offset - base) / 1e3,
                    "pid": pid,
                    "tid": lane,
                    "cat": "journey",
                    "args": args,
                }
                if end > start:
                    ev.update(ph="X", dur=(end - start) / 1e3)
                else:
                    ev.update(ph="i", s="t")
                events.append(ev)
    return events


# -- CLI --------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="trace_summary",
        description="Summarize server trace files (per-model/per-stage "
                    "latency breakdown), join client trace files on "
                    "triton-request-id, export Chrome trace-event JSON.")
    parser.add_argument("server", nargs="+",
                        help="server trace file(s): literal paths, globs "
                        "('trace.json*' collects a rotated set), or "
                        "directories (every file inside); overlapping "
                        "specs are deduplicated by realpath")
    parser.add_argument("--client", action="append", default=None,
                        metavar="PATH",
                        help="client trace file(s) "
                        "(telemetry().enable_tracing); repeatable, each "
                        "a path/glob/directory — joined on "
                        "triton-request-id, and on the traceparent trace "
                        "id for the journeys report")
    parser.add_argument("--format", default="text",
                        choices=["text", "json", "chrome"],
                        help="text table (default), summary JSON, or Chrome "
                             "trace-event JSON for Perfetto")
    parser.add_argument("-o", "--output", default=None, metavar="PATH",
                        help="write to a file instead of stdout")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="no output, exit status only (scripted use; "
                             "-o files are still written)")
    args = parser.parse_args(argv)

    def fail(msg: str) -> int:
        # one line on stderr, nonzero exit — never an unhandled traceback
        # (a missing/empty trace file is an operator mistake, not a crash)
        if not args.quiet:
            print(f"error: {msg}", file=sys.stderr)
        return 1

    try:
        server_records = load_trace_files(args.server)
        client_records = (load_trace_files(args.client)
                          if args.client else None)
    except (OSError, ValueError) as e:
        return fail(str(e))
    if not server_records:
        return fail(f"{' '.join(args.server)}: empty trace file(s) — no "
                    "trace records (was trace_level=TIMESTAMPS set while "
                    "traffic ran?)")

    if args.format == "chrome":
        out = json.dumps(chrome_trace(server_records, client_records),
                         indent=2)
    elif args.format == "json":
        out = json.dumps(summarize(server_records, client_records), indent=2)
    else:
        out = format_text(summarize(server_records, client_records))
    if args.output:
        with open(args.output, "w") as f:
            f.write(out if out.endswith("\n") else out + "\n")
    elif not args.quiet:
        sys.stdout.write(out if out.endswith("\n") else out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
