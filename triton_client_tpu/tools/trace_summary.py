"""Trace-file analyzer (``triton-trace-summary``).

The reference client repo ships ``src/python/examples/trace_summary.py`` as
the canonical consumer of Triton's trace files; this is its analog for the
TPU harness, upgraded for the span-structured records ``RequestTracer``
emits (and still able to digest the legacy flat-timestamp shape).

    python -m triton_client_tpu.tools.trace_summary server.json
    python -m triton_client_tpu.tools.trace_summary server.json \
        --client client.json            # join on triton-request-id
    python -m triton_client_tpu.tools.trace_summary server.json \
        --format chrome -o trace.chrome.json   # load in Perfetto / chrome://tracing

Inputs are JSON Lines:

* **server file** — one object per traced request, written by the server's
  ``RequestTracer`` (``trace_level=TIMESTAMPS`` via the trace-settings API).
  Span-structured records carry ``"spans": [{"name", "start_ns", "end_ns",
  "parent"}, ...]`` with a ``REQUEST`` root; legacy records carry only
  ``"timestamps"`` and get REQUEST/QUEUE/COMPUTE derived from the pairs.
* **client file** — one object per inference, written by
  ``telemetry().enable_tracing(path)`` in any of the four Python clients:
  ``{"request_id", "model", "protocol", "spans": [SERIALIZE, NETWORK,
  DESERIALIZE, ...]}``.

The two files join on the propagated ``triton-request-id`` (the server
record's ``triton_request_id`` key).  The clocks are different processes'
monotonic clocks, so the join compares **durations** only: network overhead
= client REQUEST duration − server REQUEST duration (wire + client stack
time that never shows up server-side).

stdlib-only on purpose: the tool must run (and ``--help`` must exit 0) in an
environment with none of the optional client deps installed.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: Server-side stages in reporting order (the span taxonomy emitted by the
#: instrumentation points; see docs/ARCHITECTURE.md "Tracing").
SERVER_STAGES = (
    "DECODE",
    "QUEUE",
    "SLOT_WAIT",
    "PREFILL",
    "BATCH_ASSEMBLY",
    "H2D_TRANSFER",
    "COMPUTE",
    "D2H_TRANSFER",
    "SERIALIZE",
    "NETWORK_WRITE",
)
#: Client-side stages recorded by the instrumented clients.
CLIENT_STAGES = ("SERIALIZE", "NETWORK", "DESERIALIZE")


def load_trace_file(path: str) -> List[dict]:
    """Parse a JSON-Lines trace file; blank lines are skipped, a malformed
    line fails loudly with its line number (a silently-dropped record would
    skew every percentile below)."""
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: not valid JSON: {e}")
            if not isinstance(rec, dict):
                raise ValueError(f"{path}:{lineno}: trace record must be an "
                                 "object")
            records.append(rec)
    return records


def record_spans(rec: dict) -> List[Tuple[str, int, int]]:
    """(name, start_ns, end_ns) intervals of one record.  Span-structured
    records are used as-is; legacy records derive REQUEST and COMPUTE from
    their ``*_START``/``*_END`` timestamp pairs and QUEUE from
    QUEUE_START→COMPUTE_START (the legacy shape never wrote a QUEUE_END)."""
    spans = rec.get("spans")
    if spans:
        return [(s["name"], int(s["start_ns"]), int(s["end_ns"]))
                for s in spans]
    ts: Dict[str, int] = {}
    for t in rec.get("timestamps", []):
        ts.setdefault(str(t["name"]), int(t["ns"]))
    out: List[Tuple[str, int, int]] = []
    for name in {n[: -len("_START")] for n in ts if n.endswith("_START")}:
        start = ts.get(name + "_START")
        end = ts.get(name + "_END")
        if end is None and name == "QUEUE":
            end = ts.get("COMPUTE_START")
        if start is not None and end is not None:
            out.append((name, start, end))
    out.sort(key=lambda s: (s[1], s[0]))
    return out


def token_events(rec: dict) -> List[Tuple[int, int]]:
    """(token index, ns) pairs of a stream record's strided token
    timeline: ``FIRST_TOKEN`` is index 0, ``TOKEN[n]`` is index n.  Sorted
    by index; empty for unary records."""
    out: List[Tuple[int, int]] = []
    for t in rec.get("timestamps", []):
        name = str(t.get("name", ""))
        if name == "FIRST_TOKEN":
            out.append((0, int(t["ns"])))
        elif name.startswith("TOKEN[") and name.endswith("]"):
            try:
                out.append((int(name[len("TOKEN["):-1]), int(t["ns"])))
            except ValueError:
                continue
    out.sort()
    return out


def percentile(sorted_vals: Sequence[float], p: float) -> float:
    """Nearest-rank percentile over an already-sorted sequence."""
    if not sorted_vals:
        return float("nan")
    rank = max(1, math.ceil(p / 100.0 * len(sorted_vals)))
    return sorted_vals[min(rank, len(sorted_vals)) - 1]


def _stage_stats(durations_ns: List[int]) -> Dict[str, Any]:
    vals = sorted(durations_ns)
    n = len(vals)
    if not n:
        # None, not NaN: summaries embed into strict-JSON exports
        # (perf_analyzer --export-metrics, bench.py)
        return {"count": 0, "mean_us": None, "p50_us": None,
                "p90_us": None, "p99_us": None}
    return {
        "count": n,
        "mean_us": (sum(vals) / n) / 1e3,
        "p50_us": percentile(vals, 50) / 1e3,
        "p90_us": percentile(vals, 90) / 1e3,
        "p99_us": percentile(vals, 99) / 1e3,
    }


def summarize(server_records: List[dict],
              client_records: Optional[List[dict]] = None) -> Dict[str, Any]:
    """Aggregate trace records into the summary structure the text renderer
    (and ``--format json``) prints: per-model stage stats, queue share, and
    — when a client file is joined — network-overhead stats."""
    models: Dict[str, Dict[str, Any]] = {}
    per_model_stage: Dict[str, Dict[str, List[int]]] = {}
    per_model_request: Dict[str, List[int]] = {}
    # per-model generation timeline stats (stream records: "tokens" +
    # FIRST_TOKEN / strided TOKEN[n] events) — TTFT is first token vs the
    # REQUEST root, ITL is recovered from the strided gaps as
    # (t[n+k]-t[n])/k so any stride yields per-token estimates
    per_model_gen: Dict[str, Dict[str, Any]] = {}
    # (model, bucket) -> accumulated tick fields (records that rode the
    # dynamic batcher carry a "tick" object: bucket chosen, occupancy,
    # pad waste, queue depth, assembly cost)
    per_bucket: Dict[Tuple[str, int], Dict[str, Any]] = {}
    # model -> tenant -> accumulated cost stamps (records attributed by
    # the cost ledger carry a "cost" object: tenant, device_us, tokens)
    per_model_cost: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for rec in server_records:
        model = str(rec.get("model_name", "?"))
        stages = per_model_stage.setdefault(model, {})
        root_start = None
        for name, start, end in record_spans(rec):
            dur = max(0, end - start)
            if name == "REQUEST":
                per_model_request.setdefault(model, []).append(dur)
                root_start = start
            else:
                stages.setdefault(name, []).append(dur)
        if "tokens" in rec:
            g = per_model_gen.setdefault(model, {
                "streams": 0, "tokens": 0, "failed": 0, "cancelled": 0,
                "ttft": [], "itl": []})
            g["streams"] += 1
            g["tokens"] += int(rec.get("tokens") or 0)
            outcome = str(rec.get("outcome", "ok"))
            if outcome == "cancelled":
                # consumer walked away mid-stream — served, not failed
                g["cancelled"] += 1
            elif outcome != "ok":
                g["failed"] += 1
            evs = token_events(rec)
            if evs and root_start is not None:
                g["ttft"].append(max(0, evs[0][1] - root_start))
            for (n0, t0), (n1, t1) in zip(evs, evs[1:]):
                if n1 > n0:
                    g["itl"].append(max(0, (t1 - t0) // (n1 - n0)))
        cost = rec.get("cost")
        if isinstance(cost, dict):
            c = per_model_cost.setdefault(model, {}).setdefault(
                str(cost.get("tenant", "")),
                {"records": 0, "device_us": 0.0, "tokens": 0})
            c["records"] += 1
            c["device_us"] += float(cost.get("device_us") or 0.0)
            c["tokens"] += int(cost.get("tokens") or 0)
        tick = rec.get("tick")
        if isinstance(tick, dict) and "bucket" in tick:
            agg = per_bucket.setdefault((model, int(tick["bucket"])), {
                "records": 0, "batch": [], "pad": [], "depth": [],
                "assembly_us": []})
            agg["records"] += 1
            for field, key in (("batch", "batch"), ("pad", "pad_fraction"),
                               ("depth", "queue_depth"),
                               ("assembly_us", "assembly_us")):
                if key in tick:
                    agg[field].append(float(tick[key]))
    for model, stages in per_model_stage.items():
        requests = per_model_request.get(model, [])
        total_request_ns = sum(requests)
        stage_out: Dict[str, Any] = {}
        order = [s for s in SERVER_STAGES if s in stages] + sorted(
            s for s in stages if s not in SERVER_STAGES)
        for name in order:
            st = _stage_stats(stages[name])
            st["share_pct"] = (100.0 * sum(stages[name]) / total_request_ns
                               if total_request_ns else None)
            stage_out[name] = st
        entry: Dict[str, Any] = {
            "count": len(requests) or max(
                (len(v) for v in stages.values()), default=0),
            "request": _stage_stats(requests),
            "stages": stage_out,
        }
        if "QUEUE" in stage_out:
            entry["queue_share_pct"] = stage_out["QUEUE"]["share_pct"]
        models[model] = entry
    for model, g in per_model_gen.items():
        entry = models.setdefault(model, {"count": 0, "request":
                                          _stage_stats([]), "stages": {}})
        entry["generation"] = {
            "streams": g["streams"],
            "tokens": g["tokens"],
            "failed": g["failed"],
            "cancelled": g["cancelled"],
            "ttft_us": _stage_stats(g["ttft"]),
            "itl_us": _stage_stats(g["itl"]),
        }
    for (model, bucket), agg in sorted(per_bucket.items()):
        entry = models.setdefault(model, {"count": 0, "request":
                                          _stage_stats([]), "stages": {}})
        n = agg["records"]

        def _avg(vals):
            return round(sum(vals) / len(vals), 2) if vals else None

        entry.setdefault("buckets", {})[str(bucket)] = {
            "records": n,
            "avg_batch": _avg(agg["batch"]),
            "pad_waste_pct": (round(100.0 * sum(agg["pad"]) / len(agg["pad"]),
                                    1) if agg["pad"] else None),
            "avg_queue_depth": _avg(agg["depth"]),
            "avg_assembly_us": _avg(agg["assembly_us"]),
        }
    for model, tenants in sorted(per_model_cost.items()):
        entry = models.setdefault(model, {"count": 0, "request":
                                          _stage_stats([]), "stages": {}})
        # per-tenant attributed device-time over the SAMPLED records only
        # (the cost ledger's /v2/debug/costs is the complete total; this
        # table shows what the traced subset spent)
        entry["costs"] = {
            t: {"records": c["records"],
                "device_us": round(c["device_us"], 1),
                "tokens": c["tokens"],
                "us_per_token": (round(c["device_us"] / c["tokens"], 1)
                                 if c["tokens"] else None)}
            for t, c in sorted(tenants.items())}
    summary: Dict[str, Any] = {
        "requests": len(server_records),
        "models": {m: models[m] for m in sorted(models)},
    }
    if client_records is not None:
        summary["join"] = _join(server_records, client_records)
    return summary


def _join(server_records: List[dict],
          client_records: List[dict]) -> Dict[str, Any]:
    def request_dur(spans):
        for name, start, end in spans:
            if name == "REQUEST":
                return max(0, end - start)
        return None

    client_by_id: Dict[str, dict] = {}
    for rec in client_records:
        rid = str(rec.get("request_id", ""))
        if rid:
            client_by_id.setdefault(rid, rec)
    overhead_ns: List[int] = []
    joined = 0
    for rec in server_records:
        rid = str(rec.get("triton_request_id", ""))
        crec = client_by_id.get(rid)
        if crec is None:
            continue
        joined += 1
        sdur = request_dur(record_spans(rec))
        cdur = request_dur(
            [(s["name"], int(s["start_ns"]), int(s["end_ns"]))
             for s in crec.get("spans", [])])
        if sdur is not None and cdur is not None:
            overhead_ns.append(cdur - sdur)
    client_stages: Dict[str, List[int]] = {}
    for rec in client_records:
        for s in rec.get("spans", []):
            name = str(s["name"])
            if name == "REQUEST":
                continue
            client_stages.setdefault(name, []).append(
                max(0, int(s["end_ns"]) - int(s["start_ns"])))
    order = [s for s in CLIENT_STAGES if s in client_stages] + sorted(
        s for s in client_stages if s not in CLIENT_STAGES)
    return {
        "client_requests": len(client_records),
        "joined": joined,
        # wire + client-stack time invisible to the server: the honest
        # "how much latency is NOT the server" number
        "network_overhead_us": _stage_stats(overhead_ns),
        "client_stages": {name: _stage_stats(client_stages[name])
                          for name in order},
    }


# -- text rendering ---------------------------------------------------------

def _fmt_val(v) -> str:
    return "-" if v is None or v != v else f"{v:.1f}"  # None/NaN-safe


def _stage_table(rows: List[Tuple[str, Dict[str, float]]],
                 share: bool) -> List[str]:
    head = (f"  {'stage':<16}{'count':>7}{'mean_us':>12}{'p50_us':>12}"
            f"{'p90_us':>12}{'p99_us':>12}")
    if share:
        head += f"{'share%':>9}"
    lines = [head]
    for name, st in rows:
        line = (f"  {name:<16}{st['count']:>7}{_fmt_val(st['mean_us']):>12}"
                f"{_fmt_val(st['p50_us']):>12}{_fmt_val(st['p90_us']):>12}"
                f"{_fmt_val(st['p99_us']):>12}")
        if share:
            line += f"{_fmt_val(st.get('share_pct', float('nan'))):>9}"
        lines.append(line)
    return lines


def format_text(summary: Dict[str, Any]) -> str:
    lines: List[str] = []
    n_models = len(summary["models"])
    lines.append(f"== server trace: {summary['requests']} request(s), "
                 f"{n_models} model(s) ==")
    for model, entry in summary["models"].items():
        lines.append("")
        lines.append(f"model={model}  requests={entry['count']}")
        req = entry["request"]
        lines.append(
            f"  {'REQUEST':<16}{req['count']:>7}"
            f"{_fmt_val(req['mean_us']):>12}{_fmt_val(req['p50_us']):>12}"
            f"{_fmt_val(req['p90_us']):>12}{_fmt_val(req['p99_us']):>12}")
        lines.extend(_stage_table(list(entry["stages"].items()), share=True))
        if "queue_share_pct" in entry:
            lines.append(
                f"  queue share: "
                f"{_fmt_val(entry['queue_share_pct'])}% of request time")
        gen = entry.get("generation")
        if gen:
            ttft, itl = gen["ttft_us"], gen["itl_us"]
            lines.append(
                f"  generation: streams={gen['streams']} "
                f"tokens={gen['tokens']} failed={gen['failed']} "
                f"cancelled={gen['cancelled']}")
            lines.append(
                f"    TTFT us: p50 {_fmt_val(ttft['p50_us'])}  "
                f"p99 {_fmt_val(ttft['p99_us'])}   "
                f"ITL us: p50 {_fmt_val(itl['p50_us'])}  "
                f"p99 {_fmt_val(itl['p99_us'])}")
        buckets = entry.get("buckets")
        if buckets:
            # the buckets view: which tick shapes the sampled requests
            # rode, at what occupancy/pad waste — bucket-geometry tuning
            # reads straight off this table
            lines.append(f"  {'bucket':<10}{'records':>9}{'avg_batch':>11}"
                         f"{'pad%':>7}{'qdepth':>8}{'asm_us':>9}")
            for bucket, b in sorted(buckets.items(), key=lambda kv:
                                    int(kv[0])):
                lines.append(
                    f"  {bucket:<10}{b['records']:>9}"
                    f"{_fmt_val(b['avg_batch']):>11}"
                    f"{_fmt_val(b['pad_waste_pct']):>7}"
                    f"{_fmt_val(b['avg_queue_depth']):>8}"
                    f"{_fmt_val(b['avg_assembly_us']):>9}")
        costs = entry.get("costs")
        if costs:
            # who spent the device time among the traced requests — the
            # sampled-view companion to /v2/debug/costs
            lines.append(f"  {'tenant':<16}{'records':>9}{'device_us':>12}"
                         f"{'tokens':>8}{'us/tok':>8}")
            for tenant, c in costs.items():
                lines.append(
                    f"  {tenant or '-':<16}{c['records']:>9}"
                    f"{_fmt_val(c['device_us']):>12}{c['tokens']:>8}"
                    f"{_fmt_val(c['us_per_token']):>8}")
    join = summary.get("join")
    if join is not None:
        lines.append("")
        lines.append(
            f"== client join: {join['joined']}/{summary['requests']} server "
            f"trace(s) joined on request id ==")
        ov = join["network_overhead_us"]
        lines.append(
            "  network overhead (client REQUEST - server REQUEST): "
            f"count {ov['count']}  mean_us {_fmt_val(ov['mean_us'])}  "
            f"p50_us {_fmt_val(ov['p50_us'])}  "
            f"p99_us {_fmt_val(ov['p99_us'])}")
        lines.extend(
            _stage_table(list(join["client_stages"].items()), share=False))
    return "\n".join(lines) + "\n"


# -- Chrome trace-event export ----------------------------------------------

def chrome_trace(server_records: List[dict],
                 client_records: Optional[List[dict]] = None) -> Dict[str, Any]:
    """Chrome trace-event JSON (the object form: ``{"traceEvents": [...]}``)
    loadable in Perfetto / chrome://tracing.  Server and client records get
    separate pids (their monotonic clocks do not align); timestamps are
    rebased per source so the view starts at t=0.

    Stream records additionally render:

    * **token instants** (``FIRST_TOKEN`` / strided ``TOKEN[n]``) on the
      sequence's own lane, and
    * a **decode-worker pid** with one lane per (model, bucket) holding a
      span per fused dispatch (deduped on ``tick_seq`` across the traced
      sequences that rode it), occupancy in ``args``.

    Sequence lanes and tick lanes join on ``tick_seq`` — each sequence
    span carries its ``tick_seqs`` list, each tick span its ``tick_seq``
    — so pad-waste and prefill/decode interleaving read visually."""
    events: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "server"}},
    ]

    # one shared base for EVERY server-side lane (request spans, token
    # instants, decode ticks live on the same monotonic clock — rebasing
    # them separately would break the visual tick<->sequence alignment
    # this view exists for)
    ticks: Dict[Tuple[str, int], dict] = {}
    starts = []
    for rec in server_records:
        starts.extend(s for _, s, _ in record_spans(rec))
        starts.extend(ns for _, ns in token_events(rec))
        model = str(rec.get("model_name", ""))
        for t in rec.get("ticks", []):
            if "tick_seq" in t:
                ticks.setdefault((model, int(t["tick_seq"])), t)
    starts.extend(int(t.get("start_ns", 0)) for t in ticks.values())
    base = min(starts) if starts else 0

    for rec in server_records:
        tid = int(rec.get("id", 0))
        args: Dict[str, Any] = {
            "model": rec.get("model_name", ""),
            "request_id": rec.get("triton_request_id", "")}
        seqs = sorted({int(t["tick_seq"]) for t in rec.get("ticks", [])
                       if "tick_seq" in t})
        if seqs:
            args["tick_seqs"] = seqs
        if "outcome" in rec:
            args["outcome"] = rec["outcome"]
        cost = rec.get("cost")
        for name, start, end in record_spans(rec):
            span_args = args
            if isinstance(cost, dict) and name in ("COMPUTE", "DECODE"):
                # cost stamps ride the device-time spans: click a
                # COMPUTE/DECODE slice in Perfetto and read who paid
                # for it and at what unit cost
                span_args = dict(args)
                for k in ("tenant", "device_us", "tokens"):
                    if k in cost:
                        span_args[f"cost_{k}"] = cost[k]
            events.append({
                "name": name,
                "ph": "X",
                "ts": (start - base) / 1e3,       # microseconds
                "dur": max(0, end - start) / 1e3,
                "pid": 1,
                "tid": tid,
                "cat": "server",
                "args": span_args,
            })
        for n, ns in token_events(rec):
            events.append({
                "name": "FIRST_TOKEN" if n == 0 else f"TOKEN[{n}]",
                "ph": "i",
                "s": "t",                         # thread-scoped instant
                "ts": (ns - base) / 1e3,
                "pid": 1,
                "tid": tid,
                "cat": "server",
                "args": {"token": n},
            })

    if ticks:
        events.append({"ph": "M", "name": "process_name", "pid": 3,
                       "args": {"name": "decode worker"}})
        lanes: Dict[Tuple[str, int], int] = {}
        for (model, seq), t in sorted(ticks.items()):
            lane = lanes.setdefault((model, int(t.get("bucket", 0))),
                                    len(lanes) + 1)
            events.append({
                "name": f"tick {seq}",
                "ph": "X",
                "ts": (int(t.get("start_ns", 0)) - base) / 1e3,
                "dur": max(0, int(t.get("end_ns", 0))
                           - int(t.get("start_ns", 0))) / 1e3,
                "pid": 3,
                "tid": lane,
                "cat": "tick",
                "args": {"model": model,
                         **{k: t[k] for k in ("tick_seq", "bucket", "batch",
                                              "padded", "steps", "requests")
                            if k in t}},
            })

    if client_records is not None:
        events.insert(1, {"ph": "M", "name": "process_name", "pid": 2,
                          "args": {"name": "client"}})
        tids: Dict[str, int] = {}
        cstarts = [s for rec in client_records
                   for _, s, _ in record_spans(rec)]
        cbase = min(cstarts) if cstarts else 0
        for rec in client_records:
            rid = str(rec.get("request_id", ""))
            ctid = tids.setdefault(rid, len(tids) + 1)
            for name, start, end in record_spans(rec):
                events.append({
                    "name": name,
                    "ph": "X",
                    "ts": (start - cbase) / 1e3,
                    "dur": max(0, end - start) / 1e3,
                    "pid": 2,
                    "tid": ctid,
                    "cat": "client",
                    "args": {"model": rec.get("model", ""),
                             "request_id": rid},
                })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- CLI --------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="trace_summary",
        description="Summarize server trace files (per-model/per-stage "
                    "latency breakdown), join client trace files on "
                    "triton-request-id, export Chrome trace-event JSON.")
    parser.add_argument("server", help="server trace file (JSON Lines, "
                        "written via trace_level=TIMESTAMPS)")
    parser.add_argument("--client", default=None, metavar="PATH",
                        help="client trace file (telemetry().enable_tracing) "
                             "joined on triton-request-id")
    parser.add_argument("--format", default="text",
                        choices=["text", "json", "chrome"],
                        help="text table (default), summary JSON, or Chrome "
                             "trace-event JSON for Perfetto")
    parser.add_argument("-o", "--output", default=None, metavar="PATH",
                        help="write to a file instead of stdout")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="no output, exit status only (scripted use; "
                             "-o files are still written)")
    args = parser.parse_args(argv)

    def fail(msg: str) -> int:
        # one line on stderr, nonzero exit — never an unhandled traceback
        # (a missing/empty trace file is an operator mistake, not a crash)
        if not args.quiet:
            print(f"error: {msg}", file=sys.stderr)
        return 1

    try:
        server_records = load_trace_file(args.server)
        client_records = (load_trace_file(args.client)
                          if args.client else None)
    except (OSError, ValueError) as e:
        return fail(str(e))
    if not server_records:
        return fail(f"{args.server}: empty trace file (no records — was "
                    "trace_level=TIMESTAMPS set while traffic ran?)")

    if args.format == "chrome":
        out = json.dumps(chrome_trace(server_records, client_records),
                         indent=2)
    elif args.format == "json":
        out = json.dumps(summarize(server_records, client_records), indent=2)
    else:
        out = format_text(summarize(server_records, client_records))
    if args.output:
        with open(args.output, "w") as f:
            f.write(out if out.endswith("\n") else out + "\n")
    elif not args.quiet:
        sys.stdout.write(out if out.endswith("\n") else out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
