"""Incident-bundle postmortem renderer (``triton-incident-report``).

``server/incident.py`` writes trigger-driven bundle directories (profile
window, thread dump, every subsystem snapshot); this tool turns one into
the document an on-call engineer actually reads::

    python -m triton_client_tpu.tools.incident_report <bundle-dir>
    python -m triton_client_tpu.tools.incident_report --latest <incident-dir>

Sections, in triage order:

* **header** — trigger class + reason, when, which process/replica,
  which capture files made it (and which snapshots failed);
* **trigger timeline** — the recorder's recent-trigger history with this
  bundle's trigger as the terminal entry;
* **host profile** — the hottest folded stacks per thread role from the
  boosted capture window, plus loop-lag and GC-pause summaries;
* **hottest models** — device time per model (cost ledger) with each
  model's bucket roofline verdicts (device_stats);
* **pinned flights** — the outlier table (slow / failed / SLO-breach /
  chaos flights with their reasons) closest to the incident.

stdlib-only on purpose: the bundle is plain JSON + text, and the tool
must run anywhere the operator copied the directory to.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

#: Top folded stacks shown per thread role.
TOP_STACKS = 5
#: Pinned flights shown in the outlier table.
TOP_FLIGHTS = 12
#: Hottest models shown.
TOP_MODELS = 8


def _load_json(bundle: str, name: str) -> Optional[Any]:
    path = os.path.join(bundle, name)
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _load_text(bundle: str, name: str) -> Optional[str]:
    try:
        with open(os.path.join(bundle, name), encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


def parse_folded(text: str) -> List[Tuple[str, str, int]]:
    """Collapsed-stack lines (``role;frame;frame N``) ->
    ``[(role, stack, samples)]`` sorted hottest-first."""
    out: List[Tuple[str, str, int]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        try:
            n = int(count)
        except ValueError:
            continue
        role, _, frames = stack.partition(";")
        out.append((role, frames, n))
    out.sort(key=lambda t: -t[2])
    return out


def _fmt_ts(ts: Optional[float]) -> str:
    if not ts:
        return "?"
    return time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime(ts)) + "Z"


def _leaf(stack: str, keep: int = 3) -> str:
    """The last ``keep`` frames — the part of a folded stack a human
    scans a table by."""
    frames = stack.split(";")
    tail = ";".join(frames[-keep:])
    return ("...;" + tail) if len(frames) > keep else tail


def _section(title: str) -> List[str]:
    return ["", title, "-" * len(title)]


def render_report(bundle: str) -> str:
    manifest = _load_json(bundle, "manifest.json") or {}
    lines: List[str] = []
    trigger = manifest.get("trigger", "?")
    lines.append("=" * 72)
    lines.append(f"INCIDENT POSTMORTEM — {os.path.basename(bundle.rstrip(os.sep))}")
    lines.append("=" * 72)
    lines.append(f"trigger:  {trigger}"
                 + (f" — {manifest['reason']}" if manifest.get("reason")
                    else ""))
    lines.append(f"when:     {manifest.get('iso') or _fmt_ts(manifest.get('ts'))}")
    lines.append(f"process:  pid {manifest.get('pid', '?')}"
                 + (f"  replica {manifest['replica']}"
                    if manifest.get("replica") else ""))
    cap = manifest.get("capture") or {}
    if cap:
        lines.append(f"capture:  {cap.get('profile_hz', '?')} Hz profile "
                     f"over {cap.get('profile_window_s', '?')}s window")
    ok = [f["name"] for f in manifest.get("files", []) if "error" not in f]
    bad = [(f["name"], f["error"]) for f in manifest.get("files", [])
           if "error" in f]
    lines.append(f"files:    {len(ok)} captured"
                 + (f", {len(bad)} FAILED" if bad else ""))
    for name, err in bad:
        lines.append(f"          ! {name}: {err}")

    # -- trigger timeline --------------------------------------------------
    incident = _load_json(bundle, "incident.json") or {}
    timeline = list(incident.get("recent") or [])
    lines.extend(_section("Trigger timeline"))
    for entry in timeline[-10:]:
        lines.append(f"  {_fmt_ts(entry.get('ts'))}  "
                     f"{entry.get('trigger', '?'):<15} "
                     f"{entry.get('reason', '')}"
                     f"  -> {entry.get('bundle', '')}")
    lines.append(f"  {_fmt_ts(manifest.get('ts'))}  {trigger:<15} "
                 f"{manifest.get('reason', '')}  -> THIS BUNDLE")
    suppressed = incident.get("suppressed") or {}
    if suppressed:
        supp = ", ".join(f"{k}={v}" for k, v in sorted(suppressed.items()))
        lines.append(f"  (rate-limited away before this point: {supp})")

    # -- host profile ------------------------------------------------------
    lines.extend(_section("Host profile (capture window)"))
    folded = _load_text(bundle, "profile.folded")
    if folded:
        stacks = parse_folded(folded)
        total = sum(n for _, _, n in stacks) or 1
        by_role: Dict[str, List[Tuple[str, int]]] = {}
        for role, stack, n in stacks:
            by_role.setdefault(role, []).append((stack, n))
        for role in sorted(by_role,
                           key=lambda r: -sum(n for _, n in by_role[r])):
            role_total = sum(n for _, n in by_role[role])
            lines.append(f"  [{role}] {role_total} samples "
                         f"({100.0 * role_total / total:.0f}%)")
            for stack, n in by_role[role][:TOP_STACKS]:
                lines.append(f"    {n:>6}  {_leaf(stack)}")
    else:
        lines.append("  (no profile captured)")

    profiler = _load_json(bundle, "profiler.json") or {}
    lags = profiler.get("loop_lag") or {}
    if lags:
        lines.append("  event-loop lag:")
        for name, st in sorted(lags.items()):
            series = st.get("series") or []
            worst = max((p.get("lag_us", 0.0) for p in series),
                        default=st.get("max_us", 0.0))
            lines.append(f"    {name}: last {st.get('last_us', 0.0):.0f}us"
                         f"  window-max {st.get('max_us', 0.0):.0f}us"
                         f"  series-max {worst:.0f}us"
                         f" over {len(series)} probes")
    gc_info = profiler.get("gc") or {}
    if gc_info:
        parts = [f"gen{g}: {v.get('pause_us_total', 0.0) / 1e3:.1f}ms"
                 f"/{v.get('collections', 0)} collections"
                 for g, v in sorted(gc_info.items())]
        lines.append("  GC pauses: " + "  ".join(parts))

    # -- hottest models ----------------------------------------------------
    lines.extend(_section("Hottest models (device time, roofline)"))
    costs = _load_json(bundle, "costs.json") or {}
    device = _load_json(bundle, "device_stats.json") or {}
    per_model: Dict[str, float] = {}
    for m, tenants in (costs.get("models") or {}).items():
        per_model[m] = sum(float(c.get("device_us", 0.0))
                           for c in tenants.values()
                           if isinstance(c, dict))
    if per_model:
        ticks = device.get("ticks") or {}
        for m, us in sorted(per_model.items(),
                            key=lambda kv: -kv[1])[:TOP_MODELS]:
            verdicts = []
            for bucket, entry in sorted((ticks.get(m) or {}).items()):
                roof = entry.get("roofline") if isinstance(entry, dict) \
                    else None
                if roof:
                    v = roof.get("verdict", "?")
                    pct = roof.get("pct_of_peak")
                    verdicts.append(
                        f"@{bucket}:{'comp' if v == 'compute_bound' else 'mem'}"
                        + (f" {pct:.0f}%" if pct is not None else ""))
            lines.append(f"  {m:<24}{us / 1e3:>10.1f} ms device"
                         + ("  " + " ".join(verdicts) if verdicts else ""))
    else:
        lines.append("  (no cost ledger data)")

    # -- pinned flights ----------------------------------------------------
    lines.extend(_section("Pinned flights (outliers at capture)"))
    recorder = _load_json(bundle, "flight_recorder.json") or {}
    outliers = list(recorder.get("outliers") or [])
    if outliers:
        lines.append(f"  {'SEQ':>6}  {'MODEL':<20}{'TOTALms':>9}"
                     f"{'AGEs':>7}  {'REASON':<14}{'OUTCOME':<10}CHAOS")
        for o in outliers[-TOP_FLIGHTS:]:
            total_ms = (o.get("total_us") or 0.0) / 1e3
            lines.append(
                f"  {o.get('seq', '?'):>6}  {o.get('model', '?'):<20}"
                f"{total_ms:>9.2f}{(o.get('age_s') or 0):>7.1f}  "
                f"{(o.get('capture_reason') or '-'):<14}"
                f"{(o.get('outcome') or '?'):<10}"
                f"{o.get('chaos') or '-'}")
    else:
        lines.append("  (no pinned flights)")

    # -- governor / memory -------------------------------------------------
    memory = _load_json(bundle, "memory.json") or {}
    if memory:
        lines.extend(_section("Memory governor"))
        budget = memory.get("budget_bytes")
        live = memory.get("effective_budget_bytes", budget)
        lines.append(f"  budget: {budget or 'unbounded'}"
                     + (f"  effective: {live}" if live != budget else "")
                     + ("  [PRESSURE ACTIVE]"
                        if memory.get("pressure_active") else ""))
        if memory.get("pressure_events"):
            lines.append(f"  pressure windows seen: "
                         f"{memory['pressure_events']}")
        inflight = memory.get("inflight_by_model") or {}
        for m, b in sorted(inflight.items(), key=lambda kv: -kv[1])[:5]:
            lines.append(f"  inflight {m}: {b} bytes")
        if memory.get("shed_total"):
            lines.append(f"  shed: {memory['shed_total']} total")

    # -- config fingerprint (tail) -----------------------------------------
    config = _load_json(bundle, "config.json") or {}
    if config:
        lines.extend(_section("Process fingerprint"))
        lines.append(f"  python {config.get('python', '?')} on "
                     f"{config.get('platform', '?')}")
        env = config.get("env") or {}
        for k in sorted(env):
            lines.append(f"  {k}={env[k]}")
    lines.append("")
    return "\n".join(lines)


def find_latest(incident_dir: str) -> Optional[str]:
    """Newest bundle in an incident directory (bundle names sort
    chronologically by construction)."""
    try:
        entries = sorted(e for e in os.listdir(incident_dir)
                         if e.startswith("incident-")
                         and os.path.isdir(os.path.join(incident_dir, e)))
    except OSError:
        return None
    return os.path.join(incident_dir, entries[-1]) if entries else None


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="render an incident bundle into a postmortem")
    parser.add_argument("bundle",
                        help="bundle directory (or, with --latest, the "
                        "incident directory holding bundles)")
    parser.add_argument("--latest", action="store_true",
                        help="treat BUNDLE as the incident dir and render "
                        "its newest bundle")
    parser.add_argument("-o", "--output", default=None,
                        help="write the report here instead of stdout")
    args = parser.parse_args(argv)
    bundle = args.bundle
    if args.latest:
        found = find_latest(bundle)
        if found is None:
            print(f"no bundles under {bundle}", file=sys.stderr)
            return 1
        bundle = found
    if not os.path.isfile(os.path.join(bundle, "manifest.json")):
        print(f"{bundle}: not an incident bundle (no manifest.json)",
              file=sys.stderr)
        return 1
    report = render_report(bundle)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(report)
    else:
        sys.stdout.write(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
