"""``triton-lint``: project-native static analysis for the TPU serving stack.

A stdlib-``ast`` framework (no dependencies — tools contract) whose rules
encode the semantic invariants this codebase has repeatedly violated and
hand-caught in review:

======================  =====================================================
ASYNC-BLOCK             no blocking calls (sleep / sync IO / sync clients /
                        indefinite Lock.acquire) inside ``async def`` bodies;
                        executor hops recognized
LOCK-ORDER              lock-acquisition cycles, nested non-reentrant
                        acquisition, unlocked writes to lock-guarded fields
EXC-CONTRACT            the four client cores raise only
                        InferenceServerException from public methods
SPAN-PAIR               every TraceContext/Span start reaches an
                        emit/end/handoff
METRICS-DECL            every nv_* family declared exactly once, references
                        resolve, label sets consistent
TEST-DETERMINISM        no unseeded global RNG or wall-clock-vs-quantile
                        races in tests
======================  =====================================================

Suppress one finding with ``# tpu-lint: disable=RULE <reason>`` on (or one
line above) the offending line; grandfather legacy findings in the
checked-in ``.tpu-lint-baseline.json``.  The tier-1 gate
(``tests/test_lint.py``) runs the full suite over the repo and fails on
any non-baselined finding.  See ARCHITECTURE.md "Static analysis".
"""

from ._cli import main
from ._engine import (Finding, Project, SourceFile, build_project,
                      rule_help, rule_names, run_rules)

__all__ = [
    "main",
    "Finding",
    "Project",
    "SourceFile",
    "build_project",
    "rule_names",
    "rule_help",
    "run_rules",
]
