"""``python -m triton_client_tpu.tools.lint`` — parity with the other
stdlib operator tools on boxes where the console script isn't on PATH."""

import sys

from ._cli import main

sys.exit(main())
