"""ASYNC-BLOCK: no blocking calls on the event loop.

Historical bug class: ``/metrics`` rendered inline on the event loop and
``/v2/debug/*`` serialized multi-MB JSON there (fixed in PR 7 by executor
hops); ``ServerLog`` file appends called directly from async control-plane
handlers while the request paths carefully hopped to the executor.  One
blocking call on the loop stalls EVERY in-flight request for its duration
— on a tunneled TPU link a single synchronous device read is a full RTT
serializing all concurrent traffic behind it.

What fires, inside ``async def`` bodies only:

* ``time.sleep`` (any import spelling) — ``await asyncio.sleep`` is the
  non-blocking sibling.
* sync file IO: the ``open`` builtin.
* sync transport clients: ``requests.*``, ``urllib.request.urlopen``,
  ``socket.socket``/``socket.create_connection``, ``subprocess.*``,
  ``os.system``.
* project-native: ``ServerLog`` emits — ``.info/.warning/.error/.verbose``
  called on a receiver whose dotted path is or ends with ``log`` (the
  ``core.log`` surface does synchronous file/stderr writes; async code
  must route through ``log_off_loop``).
* indefinite lock acquisition: non-awaited ``<x>.acquire()`` with neither
  ``blocking=False`` nor a ``timeout=`` where ``x`` names a lock.

Executor hops are recognized structurally: nested ``def``/``lambda``
bodies are skipped (that is exactly the ``run_in_executor`` idiom — the
blocking call runs on a worker, not the loop), and passing a bound method
*as an argument* (``log_off_loop(core.log.info, msg)``) is not a call.
"""

from __future__ import annotations

import ast

from .._ast_util import (awaited_ids, dotted_name, iter_body_nodes,
                         iter_functions, module_aliases, resolve_call_name)
from .._engine import Finding, Project, register_rule

#: Fully-qualified call targets that block (import-alias aware).
_BLOCKING_QUALIFIED = {
    "time.sleep": "time.sleep blocks the event loop; "
                  "use `await asyncio.sleep(...)`",
    "os.system": "os.system blocks the event loop",
    "urllib.request.urlopen": "sync HTTP on the event loop; use the aio "
                              "client or an executor hop",
    "socket.create_connection": "sync socket IO on the event loop",
    "socket.socket": "sync socket on the event loop",
    "subprocess.run": "subprocess blocks the event loop",
    "subprocess.call": "subprocess blocks the event loop",
    "subprocess.check_call": "subprocess blocks the event loop",
    "subprocess.check_output": "subprocess blocks the event loop",
    "requests.get": "sync HTTP on the event loop",
    "requests.post": "sync HTTP on the event loop",
    "requests.put": "sync HTTP on the event loop",
    "requests.delete": "sync HTTP on the event loop",
    "requests.request": "sync HTTP on the event loop",
    "requests.Session": "sync HTTP session on the event loop",
}

_LOG_METHODS = {"info", "warning", "error", "verbose"}


def _is_log_receiver(node: ast.AST) -> bool:
    """True for ``log``, ``self.log``, ``self._core.log``, ... — the
    ServerLog attribute surface."""
    d = dotted_name(node)
    return d is not None and (d == "log" or d.endswith(".log"))


def _lockish(node: ast.AST) -> bool:
    d = dotted_name(node)
    return d is not None and "lock" in d.lower()


def _acquire_bounded(call: ast.Call) -> bool:
    """``acquire(blocking=False)`` / ``acquire(timeout=...)`` /
    ``acquire(False)`` / the positional ``acquire(True, 5)`` form are all
    bounded — only the indefinite form fires."""
    for kw in call.keywords:
        if kw.arg in ("blocking", "timeout"):
            return True
    if len(call.args) >= 2:
        return True  # acquire(blocking, timeout) positional signature
    if call.args:
        first = call.args[0]
        if isinstance(first, ast.Constant) and first.value is False:
            return True
    return False


@register_rule(
    "ASYNC-BLOCK",
    "no time.sleep / sync IO / sync clients / indefinite Lock.acquire "
    "inside async def bodies (executor hops recognized)")
def check(project: Project):
    for f in project.files:
        if f.tree is None:
            continue
        mods, names = module_aliases(f.tree)
        for _cls, fn in iter_functions(f.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            awaited = awaited_ids(fn)
            for node in iter_body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                qual = resolve_call_name(node, mods, names)
                if qual in _BLOCKING_QUALIFIED:
                    yield Finding(
                        "ASYNC-BLOCK", f.relpath, node.lineno,
                        f"{_BLOCKING_QUALIFIED[qual]} (async def "
                        f"{fn.name})",
                        symbol=f.symbol_at(node.lineno))
                    continue
                if qual == "open" or (isinstance(node.func, ast.Name)
                                      and node.func.id == "open"):
                    yield Finding(
                        "ASYNC-BLOCK", f.relpath, node.lineno,
                        f"sync file IO (open) on the event loop (async "
                        f"def {fn.name}); hop to the executor",
                        symbol=f.symbol_at(node.lineno))
                    continue
                if isinstance(node.func, ast.Attribute):
                    attr = node.func.attr
                    if attr in _LOG_METHODS \
                            and _is_log_receiver(node.func.value):
                        yield Finding(
                            "ASYNC-BLOCK", f.relpath, node.lineno,
                            f"ServerLog .{attr}() does sync file/stderr "
                            f"IO on the event loop (async def {fn.name}); "
                            "use log_off_loop(...)",
                            symbol=f.symbol_at(node.lineno))
                        continue
                    if attr == "acquire" and id(node) not in awaited \
                            and _lockish(node.func.value) \
                            and not _acquire_bounded(node):
                        yield Finding(
                            "ASYNC-BLOCK", f.relpath, node.lineno,
                            f"indefinite Lock.acquire() on the event loop "
                            f"(async def {fn.name}); use "
                            "blocking=False/timeout= or an executor hop",
                            symbol=f.symbol_at(node.lineno))
