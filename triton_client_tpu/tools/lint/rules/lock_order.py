"""LOCK-ORDER: lock discipline across the server's shared-state classes.

Historical bug class: PR 7 review caught ``/metrics`` triple-summing a
deque under the device-stats lock while executor threads appended to it,
and the SLO engine resolving model objectives (which takes registry locks)
*inside* its own lock — the comment at ``device_stats.py`` "resolve
OUTSIDE the lock" is the hand-enforced version of this rule.  The batcher
thread × event loop × scrape path all share these structures; a
lock-order inversion deadlocks the data plane, and an unlocked write to a
lock-guarded field is a torn read on the scrape path.

Two checks:

* **acquisition graph** — ``with lockB`` lexically nested inside ``with
  lockA`` (plus one level of same-class ``self.method()`` resolution)
  builds edges ``A -> B``.  A self-edge on a non-reentrant lock is an
  instant deadlock; a cycle between distinct locks is an ordering
  inversion waiting for the right interleaving.
* **guard consistency** — within a class owning a lock, an attribute
  written under ``with <lock>`` in one method and written outside any
  lock block in another (``__init__`` excluded: construction happens
  before sharing) is flagged — the unguarded write races every locked
  reader.

Lock identity is file-qualified ``path:ClassName.attr`` for
``self.<attr>`` context managers whose name contains "lock", and
``path:<expression text>`` otherwise — module-level locks participate in
the graph, and same-named locks in different files stay distinct nodes
(see ``_lock_id``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .._ast_util import dotted_name
from .._engine import Finding, Project, register_rule


def _lock_exprs(with_node: ast.With) -> List[str]:
    out = []
    for item in with_node.items:
        d = dotted_name(item.context_expr)
        if d is not None and "lock" in d.lower():
            out.append(d)
    return out


def _lock_id(cls_name: Optional[str], expr: str,
             relpath: str = "") -> str:
    """File-qualified lock identity: four classes in this codebase share
    the name ``InferenceServerClient`` — without the path qualifier,
    unrelated same-named locks in different files would merge into one
    graph node and fabricate lock-order cycles.  (The flip side, a lock
    object genuinely shared across files under different spellings, was
    never resolvable lexically — documented limit.)"""
    if cls_name and expr.startswith("self."):
        return f"{relpath}:{cls_name}.{expr[len('self.'):]}"
    return f"{relpath}:{expr}" if relpath else expr


class _ClassInfo:
    def __init__(self, name: str) -> None:
        self.name = name
        # lock attr -> reentrant? (self._lock = threading.Lock()/RLock())
        self.locks: Dict[str, bool] = {}
        # method name -> list of (lock expr, held set at acquisition, node)
        self.acquisitions: Dict[str, List[Tuple[str, Tuple[str, ...], int]]] = {}
        # method name -> set of lock exprs acquired at its top level
        self.method_locks: Dict[str, Set[str]] = {}
        # method name -> [(self-call name, held locks, lineno)]
        self.calls_while_held: Dict[str, List[Tuple[str, Tuple[str, ...],
                                                    int]]] = {}
        # attr -> True if ever written under a lock; writes outside
        self.guarded_attrs: Set[str] = set()
        self.unguarded_writes: List[Tuple[str, str, int]] = []


def _scan_class(cls: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(cls.name)
    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        acquisitions: List[Tuple[str, Tuple[str, ...], int]] = []
        toplevel: Set[str] = set()
        calls: List[Tuple[str, Tuple[str, ...], int]] = []
        writes: List[Tuple[str, bool, int]] = []

        def walk(node, held: Tuple[str, ...]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                new_held = held
                if isinstance(child, ast.With):
                    names = _lock_exprs(child)
                    for n in names:
                        acquisitions.append((n, held, child.lineno))
                        if not held:
                            toplevel.add(n)
                    new_held = held + tuple(names)
                if isinstance(child, ast.Call):
                    d = dotted_name(child.func)
                    if d and d.startswith("self.") and "." not in d[5:] \
                            and held:
                        calls.append((d[5:], held, child.lineno))
                if isinstance(child, (ast.Assign, ast.AugAssign)):
                    targets = (child.targets if isinstance(child, ast.Assign)
                               else [child.target])
                    for t in targets:
                        # descend one level into tuple/list unpacking:
                        # `a, self.x = ..., None` writes self.x too
                        elts = (list(t.elts)
                                if isinstance(t, (ast.Tuple, ast.List))
                                else [t])
                        for tt in elts:
                            if isinstance(tt, ast.Attribute) \
                                    and isinstance(tt.value, ast.Name) \
                                    and tt.value.id == "self":
                                writes.append((tt.attr, bool(held),
                                               child.lineno))
                    # lock construction: self.X = threading.Lock()/RLock()
                    if isinstance(child, ast.Assign) \
                            and isinstance(child.value, ast.Call):
                        vd = dotted_name(child.value.func) or ""
                        if vd.endswith("RLock") or vd.endswith("Lock"):
                            for t in child.targets:
                                if isinstance(t, ast.Attribute) \
                                        and isinstance(t.value, ast.Name) \
                                        and t.value.id == "self":
                                    info.locks[t.attr] = vd.endswith("RLock")
                walk(child, new_held)

        walk(fn, ())
        info.acquisitions[fn.name] = acquisitions
        info.method_locks[fn.name] = toplevel
        info.calls_while_held[fn.name] = calls
        for attr, under_lock, lineno in writes:
            if under_lock:
                info.guarded_attrs.add(attr)
        # methods named *_locked are called with the lock already held —
        # the codebase's own convention (_prune_locked, _close_locked);
        # __init__ writes happen before the object is shared
        if fn.name != "__init__" and not fn.name.endswith("_locked"):
            for attr, under_lock, lineno in writes:
                if not under_lock:
                    info.unguarded_writes.append((fn.name, attr, lineno))
    return info


def _module_lock_kinds(tree: ast.AST) -> Dict[str, bool]:
    """Module-level ``X = threading.Lock()/RLock()`` -> reentrancy."""
    out: Dict[str, bool] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            vd = dotted_name(node.value.func) or ""
            if vd.endswith("Lock"):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = vd.endswith("RLock")
    return out


def _module_function_edges(tree: ast.AST):
    """Lexical with-lock nesting in functions OUTSIDE classes:
    yields (holder, acquired, lineno) plus same-lock re-acquisitions."""
    class_funcs = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    class_funcs.add(id(sub))
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or id(node) in class_funcs:
            continue

        def walk(n, held):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                new_held = held
                if isinstance(child, ast.With):
                    names = _lock_exprs(child)
                    for nm in names:
                        for h in held:
                            yield (h, nm, child.lineno)
                    new_held = held + tuple(names)
                yield from walk(child, new_held)

        yield from walk(node, ())


@register_rule(
    "LOCK-ORDER",
    "lock-acquisition cycles / nested non-reentrant acquisition / writes "
    "to lock-guarded fields outside the lock")
def check(project: Project):
    # edges: (holder lock id, acquired lock id) -> first (path, line)
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for f in project.files:
        if f.tree is None:
            continue
        mod_locks = _module_lock_kinds(f.tree)
        for holder, acquired, lineno in _module_function_edges(f.tree):
            if holder == acquired:
                if not mod_locks.get(acquired, False):
                    yield Finding(
                        "LOCK-ORDER", f.relpath, lineno,
                        f"nested acquisition of non-reentrant lock "
                        f"{acquired} (already held) — instant deadlock",
                        symbol=f.symbol_at(lineno))
            else:
                edges.setdefault((_lock_id(None, holder, f.relpath),
                                  _lock_id(None, acquired, f.relpath)),
                                 (f.relpath, lineno))
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = _scan_class(node)
            reentrant = {attr for attr, re_ in info.locks.items() if re_}
            def _is_reentrant(expr: str) -> bool:
                # self attrs consult the class's lock constructions;
                # module-level names consult the module's
                if expr.startswith("self."):
                    return expr[len("self."):] in reentrant
                return mod_locks.get(expr, False)

            for method, acqs in info.acquisitions.items():
                for expr, held, lineno in acqs:
                    lid = _lock_id(info.name, expr, f.relpath)
                    for h in held:
                        hid = _lock_id(info.name, h, f.relpath)
                        if hid == lid:
                            if not _is_reentrant(expr):
                                yield Finding(
                                    "LOCK-ORDER", f.relpath, lineno,
                                    f"nested acquisition of non-reentrant "
                                    f"lock {lid} (already held) — instant "
                                    "deadlock",
                                    symbol=f.symbol_at(lineno))
                        else:
                            edges.setdefault((hid, lid),
                                             (f.relpath, lineno))
            # one level of intra-class call resolution: holding L, calling
            # self.m() where m acquires M at its top level => edge L -> M
            for method, calls in info.calls_while_held.items():
                for callee, held, lineno in calls:
                    for acquired in info.method_locks.get(callee, ()):
                        lid = _lock_id(info.name, acquired, f.relpath)
                        for h in held:
                            hid = _lock_id(info.name, h, f.relpath)
                            if hid == lid:
                                if not _is_reentrant(acquired):
                                    yield Finding(
                                        "LOCK-ORDER", f.relpath, lineno,
                                        f"self.{callee}() re-acquires "
                                        f"non-reentrant lock {lid} already "
                                        f"held here — instant deadlock",
                                        symbol=f.symbol_at(lineno))
                            else:
                                edges.setdefault((hid, lid),
                                                 (f.relpath, lineno))
            # guard consistency
            if info.locks:
                for method, attr, lineno in info.unguarded_writes:
                    if attr in info.guarded_attrs \
                            and attr not in info.locks:
                        yield Finding(
                            "LOCK-ORDER", f.relpath, lineno,
                            f"write to self.{attr} outside any lock block "
                            f"({info.name}.{method}); the same field is "
                            "written under a lock elsewhere — torn "
                            "read for locked readers",
                            symbol=f.symbol_at(lineno))
    # cycles in the cross-file lock graph (A->B with B->A anywhere)
    seen = set()
    for (a, b), (path, lineno) in sorted(edges.items()):
        if (b, a) in edges and (b, a) not in seen:
            seen.add((a, b))
            other_path, other_line = edges[(b, a)]
            yield Finding(
                "LOCK-ORDER", path, lineno,
                f"lock-order cycle: {a} -> {b} here but {b} -> {a} at "
                f"{other_path}:{other_line} — deadlock under the right "
                "interleaving",
                symbol="<graph>")
