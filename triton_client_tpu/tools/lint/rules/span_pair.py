"""SPAN-PAIR: every span/trace start reaches a completion.

Historical bug class: the span-structured tracing of PR 2/3 lives and
dies by pairing — a ``TraceContext`` that is started but never emitted
loses the request from the trace file AND from the flight recorder's
completion pipeline (``emit`` is what hands the record to
``FlightRecorder.complete``, which feeds the SLO burn windows).  An
unclosed ``Span`` object emits as a zero-length point, silently
corrupting queue-share math in ``trace_summary``.

Intra-procedural checks (documented limitation: a context handed to
another function is trusted — the rule targets the start-and-forget
shape, not whole-program escape analysis):

* a call to ``.begin_span(...)`` or ``.begin_root(...)`` requires
  completion evidence in the same function: a ``.end(...)`` /
  ``.finish()`` / ``.emit()`` / ``.emit_async()`` / ``.mark_failed(...)``
  call, or handoff (``<resp>.trace = <ctx>`` / reading
  ``.trace_handoff``).
* a ``TraceContext`` obtained from ``maybe_start(...)`` /
  ``start_shadow(...)`` — or a streaming context from
  ``maybe_start_stream(...)`` / ``start_stream_shadow(...)`` — and
  *assigned to a name* requires the same completion evidence in the
  function — or the variable escaping as a call argument / return value
  (handoff to the completing layer).  The streaming helpers are held to
  the same contract because a stream context that never reaches ``emit``
  loses the WHOLE generation (every token event, every tick join) from
  the trace file and the SLO pipeline, not just one request.
* a journey scope from ``begin_journey(...)`` requires an
  ``end_journey(...)`` in the same function — or the scope escaping as a
  return value / call argument.  A leaked journey scope is worse than a
  lost span: the contextvar keeps the journey alive past its retry loop,
  so UNRELATED later requests on the same thread/task inherit its trace
  id and every journey after the leak collapses into one giant bogus
  trace.
"""

from __future__ import annotations

import ast

from .._ast_util import dotted_name, iter_body_nodes, iter_functions
from .._engine import Finding, Project, register_rule

_STARTERS_SPAN = {"begin_span", "begin_root"}
_STARTERS_CTX = {"maybe_start", "start_shadow",
                 "maybe_start_stream", "start_stream_shadow"}
# mark_failed counts as completion: the streaming error paths stamp the
# failure and the envelope's finally emits — in-function evidence of
# either is the pairing this rule wants
_CLOSERS = {"end", "finish", "emit", "emit_async", "mark_failed"}
_STARTER_JOURNEY = "begin_journey"
_CLOSER_JOURNEY = "end_journey"


def _call_name(func: ast.AST) -> str:
    """The terminal name of a call target: ``begin_journey`` for both the
    bare imported form and ``tel.begin_journey``-style attributes."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _journey_closed(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and _call_name(node.func) == _CLOSER_JOURNEY:
            return True
    return False


def _completion_evidence(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            if node.func.attr in _CLOSERS:
                return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "trace":
                    return True
        if isinstance(node, ast.Attribute) and node.attr == "trace_handoff":
            return True
    return False


def _escapes(fn: ast.AST, name: str) -> bool:
    """The context variable leaves the function: returned, yielded, or
    passed as an argument — the completing layer owns it now."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Return, ast.Yield)) \
                and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id == name:
                    return True
        if isinstance(node, ast.Call):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id == name:
                        return True
    return False


@register_rule(
    "SPAN-PAIR",
    "every TraceContext/Span start reaches an emit/end/handoff in its "
    "function (start-and-forget loses the request from trace + flight "
    "recorder + SLO pipelines)")
def check(project: Project):
    for f in project.files:
        if f.tree is None:
            continue
        rp = f.relpath.replace("\\", "/")
        if rp.endswith("server/trace.py") or rp.endswith("_telemetry.py"):
            continue  # the implementations themselves define these methods
        for _cls, fn in iter_functions(f.tree):
            has_completion = None  # computed lazily per function
            journey_closed = None
            # own-body only: a starter inside a nested def is that
            # function's responsibility (iter_functions visits it too)
            for node in iter_body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                if _call_name(node.func) == _STARTER_JOURNEY:
                    if journey_closed is None:
                        journey_closed = _journey_closed(fn)
                    if journey_closed:
                        continue
                    target = _assigned_name(fn, node)
                    if target is not None and _escapes(fn, target):
                        continue
                    yield Finding(
                        "SPAN-PAIR", f.relpath, node.lineno,
                        f"begin_journey(...) with no end_journey in "
                        f"{fn.name}() — the leaked journey scope makes "
                        "every later request on this context share one "
                        "trace id",
                        symbol=f.symbol_at(node.lineno))
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                if attr in _STARTERS_SPAN:
                    if has_completion is None:
                        has_completion = _completion_evidence(fn)
                    if not has_completion:
                        yield Finding(
                            "SPAN-PAIR", f.relpath, node.lineno,
                            f".{attr}(...) with no end/finish/emit/handoff "
                            f"in {fn.name}() — the span never closes",
                            symbol=f.symbol_at(node.lineno))
                elif attr in _STARTERS_CTX:
                    d = dotted_name(node.func) or ""
                    if not (d.endswith("tracer." + attr)
                            or d.startswith("self.tracer.")
                            or "tracer" in d):
                        continue  # e.g. cluster's _maybe_start_probing
                    # find the assigned name, if any
                    target = _assigned_name(fn, node)
                    if has_completion is None:
                        has_completion = _completion_evidence(fn)
                    if has_completion:
                        continue
                    if target is not None and _escapes(fn, target):
                        continue
                    yield Finding(
                        "SPAN-PAIR", f.relpath, node.lineno,
                        f"TraceContext from {attr}(...) never reaches "
                        f"emit/finish/handoff in {fn.name}() — the request "
                        "vanishes from trace, flight recorder, and SLO "
                        "pipelines",
                        symbol=f.symbol_at(node.lineno))


def _assigned_name(fn: ast.AST, call: ast.Call):
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and node.value is call:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    return t.id
    return None
