"""WIRE-COPY: no tensor-payload copies on the wire serialize paths.

Historical bug class: ISSUE 10's profile found ~half of every RPC was
client-framework overhead, and a big slice of it was redundant payload
copies on the wire path — the BYTES codec joined 2N per-element chunks
into a ``bytes`` and then round-tripped it through ``np.frombuffer(...)
.tobytes()`` (a second full copy), the HTTP body grew by ``+=``
concatenation (quadratic), and fixed-dtype tensors were ``tobytes()``'d
even where a memoryview handoff reaches the transport.  The fast-path
refactor removed them; this rule keeps them out.  ISSUE 11 extended the
same contract to the server frontends: their response encoders
``.tobytes()``-materialized every output tensor, which the server wire
fast path replaced with memoryview segments — the rule now covers both
ends of the socket.

What fires, inside the four client cores (files under an ``http`` or
``grpc`` path segment) AND the server serialize modules
(``server/http_server.py``, ``server/grpc_server.py``,
``server/wire.py``), and only within serialize-path functions
(``set_data_from_numpy``, ``_get_binary_data``/``_get_raw_data``,
``get_inference_request*``, ``stamp``/``assemble*``, ``encode_*``/
``_encode_*``, ``build_*response*``, ``wire_segment``, anything named
``*serialize*``):

* ``<x>.tobytes()`` — copies the whole tensor; use
  ``utils.as_wire_memoryview`` (HTTP) or pragma the one protobuf-required
  materialization (gRPC).
* ``bytes(x)`` with a non-constant argument — same copy, different
  spelling.
* ``b"".join(...)`` (any bytes-literal receiver) — per-element chunk
  gather; build into one preallocated buffer
  (``utils.serialize_byte_tensor_raw``) instead.

Legitimate sites carry a reasoned pragma (``# tpu-lint:
disable=WIRE-COPY <why>``): protobuf bytes fields require a ``bytes``
materialization (client request AND server response), and the final
header+payload gather into the HTTP body is the one copy the transport
demands — on both ends.  The rule ships with an EMPTY baseline — new
copies can't ride in grandfathered.
"""

from __future__ import annotations

import ast
import re

from .._ast_util import iter_body_nodes, iter_functions
from .._engine import Finding, Project, register_rule

#: A file is in scope when a whole path segment is one of the client-core
#: package names (``triton_client_tpu/http/...``, ``.../grpc/aio/...``)
#: OR it is one of the server serialize modules (the frontends and the
#: response-template module).
_CORE_SEGMENT = re.compile(r"(^|/)(http|grpc)(/|$)")
_SERVER_FILES = re.compile(
    r"(^|/)server/(http_server|grpc_server|wire)\.py$")

#: Serialize-path function names (exact or substring rules below).
_SERIALIZE_FNS = {
    "set_data_from_numpy",
    "_get_binary_data",
    "_get_raw_data",
    "generate_request_body",
    "wire_segment",
}
_SERIALIZE_PREFIXES = ("get_inference_request", "stamp", "_stamp",
                       "assemble", "encode_", "_encode", "build_pb_response",
                       "build_http_response")


def _on_serialize_path(fn_name: str) -> bool:
    if fn_name in _SERIALIZE_FNS:
        return True
    if any(fn_name.startswith(p) for p in _SERIALIZE_PREFIXES):
        return True
    return "serialize" in fn_name


def _is_bytes_literal(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, bytes)


@register_rule(
    "WIRE-COPY",
    "no .tobytes()/bytes(...)/b\"\".join on tensor payloads inside the "
    "client cores' or server frontends' serialize paths (pragma the "
    "single required copy)")
def check(project: Project):
    for f in project.files:
        if f.tree is None:
            continue
        relpath = f.relpath.replace("\\", "/")
        if not (_CORE_SEGMENT.search(relpath)
                or _SERVER_FILES.search(relpath)):
            continue
        for _cls, fn in iter_functions(f.tree):
            if not _on_serialize_path(fn.name):
                continue
            for node in iter_body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and func.attr == "tobytes":
                    yield Finding(
                        "WIRE-COPY", f.relpath, node.lineno,
                        f".tobytes() copies the whole tensor payload "
                        f"(serialize path {fn.name}); hand off a "
                        "memoryview (utils.as_wire_memoryview) or pragma "
                        "the one required materialization",
                        symbol=f.symbol_at(node.lineno))
                elif isinstance(func, ast.Name) and func.id == "bytes" \
                        and node.args \
                        and not isinstance(node.args[0], ast.Constant):
                    yield Finding(
                        "WIRE-COPY", f.relpath, node.lineno,
                        f"bytes(...) copies the payload (serialize path "
                        f"{fn.name}); keep the buffer/memoryview or "
                        "pragma the one required materialization",
                        symbol=f.symbol_at(node.lineno))
                elif isinstance(func, ast.Attribute) \
                        and func.attr == "join" \
                        and _is_bytes_literal(func.value):
                    yield Finding(
                        "WIRE-COPY", f.relpath, node.lineno,
                        f"bytes-join of per-element chunks (serialize "
                        f"path {fn.name}); build into one preallocated "
                        "buffer (utils.serialize_byte_tensor_raw) or "
                        "pragma the single final gather",
                        symbol=f.symbol_at(node.lineno))
