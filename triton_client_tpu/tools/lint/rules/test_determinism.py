"""TEST-DETERMINISM: tests must not depend on wall-clock luck or global RNG.

Historical bug class: PR 3's flight-recorder watchdog tests originally
slept real time to push a request past a *streaming-quantile* threshold —
a loaded CI host oversleeps, the quantile moves, the test flakes; they
were rewritten onto synthetic spans ("no wall-clock sleeps against
quantiles").  PR 2 fixed trace-count test-order coupling from shared
global state.  This rule pins those lessons:

* **unseeded global RNG** — module-level ``random.*`` / ``np.random.*``
  calls (``random.Random(seed)``, ``np.random.default_rng(seed)`` and
  ``jax.random.PRNGKey`` chains are fine: the receiver must be the bare
  module for the finding to fire).  Global RNG state couples tests to
  execution order.
* **wall-clock vs quantiles** — an argless ``time.time()`` call in a test
  function that also queries a streaming quantile (``.quantile(...)``):
  comparing wall-clock arithmetic against an estimator fed by real
  latencies is the PR 3 flake shape.
* **sleeps racing quantiles** — ``time.sleep(...)`` in a test function
  that also queries ``.quantile(...)`` or configures
  ``capture_slower_than`` thresholds, unless the test is ``slow``-marked
  (soaks excepted).  Fixed-duration service sleeps against *absolute*
  thresholds are fine — the flake is sleeping against a moving estimate.

Scope: files under ``tests/`` (or named ``test_*.py``) only.
"""

from __future__ import annotations

import ast
from typing import Set

from .._ast_util import (decorator_names, is_test_file, iter_body_nodes,
                         iter_functions, module_aliases, resolve_call_name)
from .._engine import Finding, Project, register_rule

_SEEDED_RANDOM_ATTRS = {"Random", "SystemRandom", "seed", "getstate",
                        "setstate"}
_SEEDED_NP_ATTRS = {"default_rng", "RandomState", "seed", "Generator",
                    "PRNGKey"}
_QUANTILE_MARKERS = {"quantile"}


def _slow_marked(fn: ast.AST) -> bool:
    return any("slow" in d for d in decorator_names(fn))


def _fn_markers(fn: ast.AST) -> Set[str]:
    """Which hazard context the function body carries: streaming-quantile
    queries / watchdog threshold configuration."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            if node.func.attr in _QUANTILE_MARKERS:
                out.add("quantile")
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and "capture_slower_than" in node.value:
            out.add("watchdog")
        if isinstance(node, (ast.Name, ast.Attribute)):
            name = node.attr if isinstance(node, ast.Attribute) else node.id
            if name == "capture_slower_than":
                out.add("watchdog")
    return out


def _rng_findings(f, node: ast.Call, qual: str):
    """Unseeded *global* RNG: the receiver must be the bare module path —
    a call chain through default_rng(0)/Random(seed)/PRNGKey(...) has no
    static dotted name, so seeded generators never fire."""
    if qual.startswith("random.") and qual.count(".") == 1:
        attr = qual.split(".", 1)[1]
        if attr not in _SEEDED_RANDOM_ATTRS:
            yield Finding(
                "TEST-DETERMINISM", f.relpath, node.lineno,
                f"unseeded global RNG {qual}(...) — use "
                "random.Random(seed) / np.random.default_rng(seed) so "
                "tests don't couple through shared RNG state",
                symbol=f.symbol_at(node.lineno))
    elif qual.startswith(("numpy.random.", "np.random.")):
        attr = qual.rsplit(".", 1)[1]
        if attr not in _SEEDED_NP_ATTRS:
            yield Finding(
                "TEST-DETERMINISM", f.relpath, node.lineno,
                f"unseeded global RNG {qual}(...) — use "
                "np.random.default_rng(seed)",
                symbol=f.symbol_at(node.lineno))


@register_rule(
    "TEST-DETERMINISM",
    "tests: no unseeded global RNG, no wall-clock time.time()/time.sleep "
    "racing streaming quantiles outside slow-marked soaks")
def check(project: Project):
    for f in project.files:
        if f.tree is None or not is_test_file(f.relpath):
            continue
        mods, names = module_aliases(f.tree)
        # module/class-level RNG (shared fixture data baked at import
        # time couples every test in the file to collection order)
        in_function = set()
        for _cls, fn in iter_functions(f.tree):
            for node in ast.walk(fn):
                in_function.add(id(node))
        for node in ast.walk(f.tree):
            if id(node) in in_function or not isinstance(node, ast.Call):
                continue
            qual = resolve_call_name(node, mods, names)
            if qual is None:
                continue
            yield from _rng_findings(f, node, qual)
        for _cls, fn in iter_functions(f.tree):
            markers = None
            slow = None
            # own-body only: calls inside nested defs are attributed to
            # the nested function (iter_functions visits it too)
            for node in iter_body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                qual = resolve_call_name(node, mods, names)
                if qual is None:
                    continue
                # -- unseeded global RNG --------------------------------
                if qual.startswith(("random.", "numpy.random.",
                                    "np.random.")):
                    yield from _rng_findings(f, node, qual)
                    continue
                # -- wall clock vs streaming quantiles ------------------
                if qual in ("time.time", "time.sleep"):
                    if markers is None:
                        markers = _fn_markers(fn)
                    if not markers:
                        continue
                    if slow is None:
                        slow = _slow_marked(fn)
                    if slow:
                        continue
                    if qual == "time.time" and not node.args \
                            and "quantile" in markers:
                        yield Finding(
                            "TEST-DETERMINISM", f.relpath, node.lineno,
                            "argless time.time() compared in a function "
                            "that queries streaming quantiles — inject a "
                            "synthetic clock (`now=`) instead",
                            symbol=f.symbol_at(node.lineno))
                    elif qual == "time.sleep":
                        yield Finding(
                            "TEST-DETERMINISM", f.relpath, node.lineno,
                            "time.sleep racing a streaming-quantile "
                            "threshold — drive the estimator with "
                            "synthetic spans/time instead (PR 3 flake "
                            "class), or mark the test slow",
                            symbol=f.symbol_at(node.lineno))
