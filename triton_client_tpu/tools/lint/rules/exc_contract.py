"""EXC-CONTRACT: the four client cores raise only InferenceServerException.

Historical bug class: PR 4 found ``InferAsyncRequest.get_result`` leaking
raw ``grpc.FutureTimeoutError`` (and the HTTP sibling leaking the
concurrent.futures timeout) instead of the typed
``InferenceServerException(status="StatusCode.DEADLINE_EXCEEDED")`` every
caller matches on.  A naked transport exception breaks retry
classification, the cluster layer's failure accounting, and every caller
that catches the documented type.

Scope: the four client cores (``http/_client.py``,
``http/aio/__init__.py``, ``grpc/_client.py``, ``grpc/aio/__init__.py``)
plus ``grpc/_infer_stream.py``.  Connection-class errors deliberately
propagate raw — the resilience layer classifies them by type name
(``_resilience._CONNECTION_EXC_NAMES``) — so the rule targets the
*status-carrying* transport surfaces:

* every ``self._client_stub.<RPC>(...)`` call must sit inside a ``try``
  whose handlers include ``grpc.RpcError`` and convert it (the handler
  body references ``raise_error_grpc`` / ``get_error_grpc`` /
  ``InferenceServerException``).  ``.future(...)`` handles are exempt
  (errors surface through the future's ``result()``), as are un-awaited
  aio calls (stream-call construction does not raise transport errors).
* every ``<future>.result(...)`` call in the gRPC cores must sit inside a
  ``try`` handling ``FutureTimeoutError`` (or a converting RpcError
  handler alongside) — the exact PR 4 leak.
* every *public* method of an HTTP client class that touches the wire
  directly (``self._get`` / ``self._post`` / ``self._pool.request`` /
  ``self._session.*``) must call ``raise_if_error`` somewhere in its body
  (nested ``_call`` closures count).  Delegation through one level of
  ``self._helper()`` is resolved: a private helper's wire-touching (and
  its conversion, if any) is attributed to the public caller, so a
  public method whose helper hits the transport without converting
  still fires.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from .._ast_util import dotted_name, iter_functions
from .._engine import Finding, Project, register_rule

_CLIENT_CORE_SUFFIXES = (
    "http/_client.py",
    "http/aio/__init__.py",
    "grpc/_client.py",
    "grpc/aio/__init__.py",
    "grpc/_infer_stream.py",
)

_CONVERTERS = {"raise_error_grpc", "get_error_grpc",
               "InferenceServerException", "raise_error"}

_HTTP_TRANSPORT_HEADS = ("self._get", "self._post", "self._pool.request",
                         "self._session.get", "self._session.post",
                         "self._session.request")


def _is_client_core(relpath: str) -> bool:
    rp = relpath.replace("\\", "/")
    return any(rp.endswith(s) for s in _CLIENT_CORE_SUFFIXES)


def _handler_names(handler: ast.ExceptHandler) -> Set[str]:
    out: Set[str] = set()
    t = handler.type
    if t is None:
        out.add("<bare>")
        return out
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in nodes:
        d = dotted_name(n)
        if d:
            out.add(d.rsplit(".", 1)[-1])
    return out


def _handler_converts(handler: ast.ExceptHandler) -> bool:
    """A handler satisfies the contract when it converts (calls a
    converter / raises the typed exception) or absorbs (never bare
    re-``raise``s the transport exception — swallowing into telemetry is
    not a leak).  Only a bare ``raise`` hands the naked transport
    exception to the caller."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d and d.rsplit(".", 1)[-1] in _CONVERTERS:
                return True
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise) and node.exc is None:
            return False
        if isinstance(node, ast.Raise) and isinstance(node.exc, ast.Name) \
                and node.exc.id == handler.name:
            return False  # `raise e` — same leak as a bare re-raise
    return True


class _TryStack(ast.NodeVisitor):
    """Visit calls with the stack of enclosing Try handlers available.
    Nested function/lambda bodies are skipped: they run in their own
    frames (callbacks, closures) where the lexical Try does not catch —
    ``iter_functions`` visits them as functions in their own right."""

    def __init__(self):
        self.stack: List[ast.Try] = []
        self.hits: List[Tuple[ast.Call, List[ast.Try]]] = []

    def visit_FunctionDef(self, node):
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass

    def visit_Try(self, node: ast.Try):
        self.stack.append(node)
        for stmt in node.body:
            self.visit(stmt)
        self.stack.pop()
        for h in node.handlers:
            self.visit(h)
        for stmt in node.orelse + node.finalbody:
            self.visit(stmt)

    def visit_Call(self, node: ast.Call):
        self.hits.append((node, list(self.stack)))
        self.generic_visit(node)


def _covering_handlers(tries: List[ast.Try], wanted: Set[str]) -> bool:
    for t in tries:
        for h in t.handlers:
            names = _handler_names(h)
            if names & wanted or "<bare>" in names or "Exception" in names:
                # naming the right exception is not enough: a handler
                # that catches FutureTimeoutError and bare re-raises it
                # is exactly the PR 4 leak
                if _handler_converts(h):
                    return True
    return False


def _grpc_checks(f, tree):
    is_aio = "aio" in f.relpath.replace("\\", "/").split("/")
    for _cls, fn in iter_functions(tree):
        visitor = _TryStack()
        for stmt in fn.body:
            visitor.visit(stmt)
        awaited = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Await) and isinstance(node.value,
                                                          ast.Call):
                awaited.add(id(node.value))
        for call, tries in visitor.hits:
            d = dotted_name(call.func) or ""
            if "_client_stub." in d:
                if d.endswith(".future"):
                    continue  # errors surface through the future handle
                if is_aio and id(call) not in awaited:
                    # aio call-object construction raises nothing; errors
                    # surface at await/read() — which IS checked
                    continue
                if not _covering_handlers(tries, {"RpcError"}):
                    yield Finding(
                        "EXC-CONTRACT", f.relpath, call.lineno,
                        f"{d}(...) not wrapped in a grpc.RpcError handler "
                        "that converts to InferenceServerException",
                        symbol=f.symbol_at(call.lineno))
            elif d.endswith(".result") and call.func and \
                    isinstance(call.func, ast.Attribute):
                # futures: the PR 4 leak — result() without a
                # FutureTimeoutError guard re-raises the raw timeout class
                if not _covering_handlers(
                        tries, {"RpcError", "FutureTimeoutError",
                                "TimeoutError", "FutureCancelledError"}):
                    yield Finding(
                        "EXC-CONTRACT", f.relpath, call.lineno,
                        f"{d}(...) without a FutureTimeoutError/RpcError "
                        "guard — a transport timeout leaks raw instead of "
                        "the typed deadline exception",
                        symbol=f.symbol_at(call.lineno))


def _http_checks(f, tree):
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        # first pass: per-method wire/convert facts + private self-calls,
        # so delegation through one level of self._helper() is attributed
        # to the public caller instead of silently passing
        info = {}
        for fn in node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            touches_wire = False
            converts = False
            self_calls = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    d = dotted_name(sub.func) or ""
                    if any(d == h or d.startswith(h + ".")
                           for h in _HTTP_TRANSPORT_HEADS):
                        touches_wire = True
                    if d.rsplit(".", 1)[-1] in ("raise_if_error",
                                                "raise_error"):
                        converts = True
                    if d.startswith("self._") and d.count(".") == 1:
                        self_calls.add(d.split(".", 1)[1])
            info[fn.name] = (fn, touches_wire, converts, self_calls)
        for name, (fn, touches_wire, converts, self_calls) in info.items():
            if name.startswith("_"):
                continue  # private helpers flagged via their public callers
            for callee in self_calls:
                entry = info.get(callee)
                if entry is not None and entry[1]:
                    # the private helper touches the wire on this public
                    # method's behalf: its conversion (or lack of it)
                    # is this method's
                    touches_wire = True
                    converts = converts or entry[2]
            if touches_wire and not converts:
                yield Finding(
                    "EXC-CONTRACT", f.relpath, fn.lineno,
                    f"public method {fn.name}() touches the HTTP transport "
                    "(directly or via a private helper) but never calls "
                    "raise_if_error — error statuses leak as raw "
                    "bodies/exceptions",
                    symbol=f.symbol_at(fn.lineno))


@register_rule(
    "EXC-CONTRACT",
    "client cores raise only InferenceServerException from public methods "
    "(gRPC stub calls wrapped, future results timeout-guarded, HTTP "
    "statuses funneled through raise_if_error)")
def check(project: Project):
    for f in project.files:
        if f.tree is None or not _is_client_core(f.relpath):
            continue
        rp = f.relpath.replace("\\", "/")
        if "grpc/" in rp:
            yield from _grpc_checks(f, f.tree)
        else:
            yield from _http_checks(f, f.tree)
