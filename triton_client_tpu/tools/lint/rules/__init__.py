"""Rule registration: importing this package registers every built-in
checker with the engine's registry."""

from . import (async_block, exc_contract, lock_order, metrics_decl,  # noqa: F401
               span_pair, test_determinism, wire_copy)
