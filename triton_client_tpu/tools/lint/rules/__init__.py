"""Rule registration: importing this package registers every built-in
checker with the engine's registry."""

from . import (async_block, device_sync, exc_contract, lock_order,  # noqa: F401
               metrics_decl, span_pair, test_determinism, wire_copy)
