"""METRICS-DECL: every metric family declared exactly once, referenced
families exist, label sets are consistent.

Historical bug class: before PR 7's metrics refactor, families were
declared ad hoc at multiple render sites and the text/JSON surfaces
drifted (a family added to one but not the other); the metrics-registry
lint bolted into ``tests/test_tools_import.py`` froze the invariant
dynamically.  This rule is that lint generalized and made static — it
runs without importing the server (no jax), so it also guards code paths
a unit test process never loads.

Model:

* the **server registry** is the file named ``server/metrics.py`` (any
  file whose basename is ``metrics.py`` defining ``collect_families``):
  every string constant that *is exactly* an ``nv_*`` family name
  (whole-string match — mentions inside help prose don't count) is a
  declaration and must be unique.
* the **client registry** is ``_telemetry.py``: same treatment for the
  ``nv_client_*`` families it renders.
* every other scanned file that references a whole-string ``nv_*``
  constant must reference a declared family — a renamed or typo'd family
  in ``triton-top``, ``bench`` glue, or a frontend fails here instead of
  silently scraping nothing.
* label-set consistency: inside the server registry, sample-label dicts
  written literally in the same ``families.append((<name>, ...))`` call
  must agree on their key set per family.

Test files are excluded from the reference scan (fixtures legitimately
invent family names), and docstrings never count.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .._ast_util import is_test_file
from .._engine import Finding, Project, register_rule

_FAMILY_RE = re.compile(r"^nv_[a-z0-9_]+$")


def _docstring_ids(tree: ast.AST) -> Set[int]:
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


def _family_constants(tree: ast.AST) -> List[Tuple[str, int]]:
    """(name, lineno) for every whole-string nv_* constant outside
    docstrings."""
    docs = _docstring_ids(tree)
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and id(node) not in docs and _FAMILY_RE.match(node.value):
            out.append((node.value, node.lineno))
    return out


def _defines_collect_families(tree: ast.AST) -> bool:
    return any(isinstance(n, ast.FunctionDef)
               and n.name == "collect_families" for n in ast.walk(tree))


def _label_sets(tree: ast.AST) -> Dict[str, List[Tuple[Set[str], int]]]:
    """family -> [(label key set, lineno)] from ``families.append((name,
    ...))`` calls whose label dicts are literal with constant keys."""
    out: Dict[str, List[Tuple[Set[str], int]]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "append" and node.args):
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Tuple) and arg.elts):
            continue
        first = arg.elts[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and _FAMILY_RE.match(first.value)):
            continue
        family = first.value
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Dict) and sub.keys and all(
                    isinstance(k, ast.Constant) and isinstance(k.value, str)
                    for k in sub.keys):
                out.setdefault(family, []).append(
                    ({k.value for k in sub.keys}, sub.lineno))
    return out


@register_rule(
    "METRICS-DECL",
    "every nv_* family declared exactly once in its registry "
    "(metrics.collect_families / _telemetry), all references declared, "
    "literal label sets consistent per family")
def check(project: Project):
    server_reg = None
    client_reg = None
    for f in project.files:
        if f.tree is None:
            continue
        base = f.relpath.replace("\\", "/").rsplit("/", 1)[-1]
        if base == "metrics.py" and _defines_collect_families(f.tree):
            server_reg = f
        elif base == "_telemetry.py":
            client_reg = f

    declared: Set[str] = set()
    for reg, label in ((server_reg, "server"), (client_reg, "client")):
        if reg is None:
            continue
        counts: Dict[str, List[int]] = {}
        for name, lineno in _family_constants(reg.tree):
            counts.setdefault(name, []).append(lineno)
        for name, linenos in sorted(counts.items()):
            declared.add(name)
            if len(linenos) > 1:
                yield Finding(
                    "METRICS-DECL", reg.relpath, linenos[1],
                    f"family {name} declared {len(linenos)} times in the "
                    f"{label} registry (first at line {linenos[0]}) — one "
                    "declaration, one HELP, one TYPE",
                    symbol=reg.symbol_at(linenos[1]))
        if reg is server_reg:
            for family, sets in sorted(_label_sets(reg.tree).items()):
                base_keys = sets[0][0]
                for keys, lineno in sets[1:]:
                    if keys != base_keys:
                        yield Finding(
                            "METRICS-DECL", reg.relpath, lineno,
                            f"family {family} emits label set "
                            f"{sorted(keys)} here but {sorted(base_keys)} "
                            f"at line {sets[0][1]} — label drift splits "
                            "the family",
                            symbol=reg.symbol_at(lineno))

    if not declared:
        return  # no registry in this run: nothing to check references against

    for f in project.files:
        if f.tree is None or f is server_reg or f is client_reg:
            continue
        if is_test_file(f.relpath):
            continue
        for name, lineno in _family_constants(f.tree):
            if name not in declared:
                yield Finding(
                    "METRICS-DECL", f.relpath, lineno,
                    f"reference to undeclared metric family {name} — not "
                    "in metrics.collect_families or the client telemetry "
                    "registry (renamed? typo?)",
                    symbol=f.symbol_at(lineno))
