"""DEVICE-SYNC: no blocking host<->device syncs inside the decode tick.

Historical bug class: ISSUE 12's profile of the continuous-batching
generation path (85.5 tok/s batched vs 236.1 independent at c=8) found
the decode worker re-crossing the host/device boundary every tick — it
re-uploaded host-side control state (``jnp.asarray`` of tokens/active/
auto/penalty rows) before each dispatch and then blocked on a
synchronous fused ``np.asarray`` readback.  The decode-tick fast path
moved the control state onto the device (donated through the fused
multi-step kernel) and double-buffered the readback
(``start_readback``/``finish_readback``); this rule keeps blocking
syncs from creeping back into the tick.

What fires, inside ``models/decode.py`` ONLY and only within the
worker-loop/tick-path functions (``_worker_loop`` and everything
lexically nested in it, ``_resolve*``, ``_dispatch*``, the
device-fault recovery and readback-watchdog paths (``_recover*``,
``_watch*``, ``_maybe_inject*`` — they interleave with live ticks on
the worker and gen-reader threads, so a blocking sync there stalls
every in-flight generation exactly like one in the tick itself), and
the shared ``finish_readback`` resolve helper):

* ``np.asarray(...)`` / ``np.array(...)`` — on a device array this is a
  blocking D2H round trip; resolve through the started readback
  (``finish_readback`` on a resolver thread) instead.
* ``jax.device_get(...)`` — same sync, different spelling.
* ``<x>.item()`` — scalar D2H sync per call.
* ``<x>.block_until_ready()`` — an explicit barrier; the tick pipeline
  exists to avoid exactly this.

The deliberate sites carry a reasoned pragma (``# tpu-lint:
disable=DEVICE-SYNC <why>``): the double-buffer has exactly ONE
blocking resolve point (``finish_readback``, reached on reader threads
after ``start_readback`` already put the transfer in flight).  Python
``int(x)``/``float(x)`` on device arrays also sync but are statically
indistinguishable from host conversions — out of scope, documented
here.  The rule ships with an EMPTY baseline — new syncs can't ride in
grandfathered.
"""

from __future__ import annotations

import ast
import re

from .._ast_util import module_aliases, resolve_call_name
from .._engine import Finding, Project, register_rule

#: Only the decode model module is in scope: the rule encodes the decode
#: worker's residency contract, not a repo-wide numpy policy.
_DECODE_FILE = re.compile(r"(^|/)models/decode\.py$")

#: Tick-path root functions: the worker loop (everything nested in it
#: runs on the worker thread), the pipelined resolvers, the
#: device-fault recovery / readback-watchdog / chaos-injection paths
#: (they share the worker and gen-reader threads with live ticks), and
#: the shared blocking resolve helper.
_ROOT_EXACT = {"_worker_loop", "finish_readback"}
_ROOT_PREFIXES = ("_resolve", "_dispatch", "_recover", "_watch",
                  "_maybe_inject")

#: Fully-qualified call targets that are blocking syncs on device arrays.
_SYNC_CALLS = {
    "numpy.asarray": "np.asarray blocks on a full D2H round trip",
    "numpy.array": "np.array blocks on a full D2H round trip",
    "jax.device_get": "jax.device_get is a blocking D2H sync",
}

#: Method names that sync regardless of receiver spelling.
_SYNC_METHODS = {
    "item": ".item() pays a blocking scalar D2H sync",
    "block_until_ready": ".block_until_ready() is an explicit device "
                         "barrier",
}


def _is_tick_root(name: str) -> bool:
    return name in _ROOT_EXACT or any(
        name.startswith(p) for p in _ROOT_PREFIXES)


@register_rule(
    "DEVICE-SYNC",
    "no blocking host<->device syncs (np.asarray/jax.device_get/.item()/"
    "block_until_ready) inside models/decode.py's worker-loop/tick-path "
    "functions, including the device-fault recovery and readback-watchdog "
    "paths (pragma the one double-buffer resolve point)")
def check(project: Project):
    for f in project.files:
        if f.tree is None:
            continue
        relpath = f.relpath.replace("\\", "/")
        if not _DECODE_FILE.search(relpath):
            continue
        mods, names = module_aliases(f.tree)
        seen: set = set()
        for node in ast.walk(f.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_tick_root(node.name):
                continue
            # the WHOLE lexical extent is in scope, nested defs included:
            # a helper defined inside the worker loop runs on the worker
            # thread (the resolvers are themselves roots, with their own
            # pragma'd resolve point)
            for call in ast.walk(node):
                if not isinstance(call, ast.Call) or id(call) in seen:
                    continue
                seen.add(id(call))
                target = resolve_call_name(call, mods, names)
                if target in _SYNC_CALLS:
                    yield Finding(
                        "DEVICE-SYNC", f.relpath, call.lineno,
                        f"{_SYNC_CALLS[target]} inside the decode tick "
                        f"path ({node.name}); start_readback at dispatch "
                        "and finish_readback on a resolver thread, or "
                        "pragma a deliberate resolve point",
                        symbol=f.symbol_at(call.lineno))
                elif isinstance(call.func, ast.Attribute) \
                        and call.func.attr in _SYNC_METHODS \
                        and not call.args and not call.keywords:
                    yield Finding(
                        "DEVICE-SYNC", f.relpath, call.lineno,
                        f"{_SYNC_METHODS[call.func.attr]} inside the "
                        f"decode tick path ({node.name}); keep the value "
                        "on device or ride the fused tick readback",
                        symbol=f.symbol_at(call.lineno))
