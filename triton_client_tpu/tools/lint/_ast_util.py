"""Shared ast helpers for the triton-lint rules (stdlib only)."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

__all__ = [
    "dotted_name",
    "module_aliases",
    "resolve_call_name",
    "iter_body_nodes",
    "awaited_ids",
    "iter_functions",
    "decorator_names",
    "is_test_file",
]


def is_test_file(relpath: str) -> bool:
    """Shared test-file predicate — rules that scope to (or exempt)
    tests must agree on what a test file is."""
    rp = relpath.replace("\\", "/")
    base = rp.rsplit("/", 1)[-1]
    return "/tests/" in f"/{rp}" or base.startswith("test_")


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a pure Name/Attribute chain; None when any link is a
    call/subscript (so ``np.random.default_rng(0).normal`` is NOT the
    module path ``np.random.normal``)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def module_aliases(tree: ast.AST) -> Tuple[Dict[str, str], Dict[str, str]]:
    """(module alias map, from-import map): ``import time as t`` ->
    ``{"t": "time"}``; ``from time import sleep as zz`` ->
    ``{"zz": "time.sleep"}``."""
    mods: Dict[str, str] = {}
    names: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    mods[a.asname] = a.name
                else:
                    # ``import urllib.request`` binds the name ``urllib``
                    # — to itself, NOT to ``urllib.request`` (that would
                    # double the submodule in resolved dotted chains)
                    head = a.name.split(".")[0]
                    mods[head] = head
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name != "*":
                    names[a.asname or a.name] = f"{node.module}.{a.name}"
    return mods, names


def resolve_call_name(call: ast.Call, mods: Dict[str, str],
                      names: Dict[str, str]) -> Optional[str]:
    """The fully-qualified name of a call target when statically known:
    import aliases resolved (``t.sleep`` -> ``time.sleep``; bare ``sleep``
    imported from time -> ``time.sleep``).  None for dynamic targets."""
    d = dotted_name(call.func)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    if not rest:
        return names.get(d, d)
    if head in mods:
        return f"{mods[head]}.{rest}"
    if head in names:
        # ``from urllib import request`` then ``request.urlopen(...)``
        return f"{names[head]}.{rest}"
    return d


def iter_body_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Every node lexically in ``fn``'s own body, NOT descending into
    nested function/lambda definitions — the executor-hop recognition:
    code inside a nested ``def`` handed to ``run_in_executor`` runs off
    the calling context."""
    stack = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # nested definition: its body runs elsewhere
        yield node
        stack.extend(ast.iter_child_nodes(node))


def awaited_ids(fn: ast.AST) -> Set[int]:
    """ids() of Call nodes that are directly awaited in ``fn``'s body —
    ``await q.get()`` is the asyncio call, not a blocking one."""
    out: Set[int] = set()
    for node in iter_body_nodes(fn):
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            out.add(id(node.value))
    return out


def iter_functions(tree: ast.AST) -> Iterator[Tuple[Optional[ast.ClassDef],
                                                    ast.AST]]:
    """Yield ``(enclosing class or None, function node)`` for every
    function/async function in the module, at any nesting depth."""

    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, child)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield cls, child
                yield from walk(child, cls)
            else:
                yield from walk(child, cls)

    yield from walk(tree, None)


def decorator_names(fn: ast.AST) -> List[str]:
    out = []
    for dec in getattr(fn, "decorator_list", []):
        node = dec.func if isinstance(dec, ast.Call) else dec
        d = dotted_name(node)
        if d:
            out.append(d)
    return out
