"""The ``triton-lint`` command line (stdlib-only, like every operator tool).

Usage:

    triton-lint [PATHS...]                # lint (default: the repo root)
    triton-lint --rule METRICS-DECL       # one rule
    triton-lint --format json             # stable machine shape
    triton-lint --write-baseline          # grandfather current findings
    triton-lint --list-rules

Exit codes: 0 = clean (baselined findings alone don't fail), 1 = fresh
findings (or stale baseline entries — the baseline only ever shrinks),
2 = usage error.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import rules as _rules  # noqa: F401 — registration side effect
from ._engine import (DEFAULT_BASELINE_NAME, apply_baseline, baseline_entry,
                      build_project, collect_files, common_root,
                      entry_fingerprint, load_baseline, render_json,
                      render_text, rule_help, run_rules,
                      write_baseline_entries)


def _walk_up_for_root(start: str) -> Optional[str]:
    """Nearest ancestor (inclusive) holding a pyproject.toml or a
    baseline file — the repo root."""
    d = start
    while True:
        if os.path.exists(os.path.join(d, "pyproject.toml")) or \
                os.path.exists(os.path.join(d, DEFAULT_BASELINE_NAME)):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def _default_paths() -> List[str]:
    """Walk up from cwd for the repo root; lint that.  Falls back to cwd
    — ``triton-lint`` with no arguments just works from anywhere in the
    repo."""
    return [_walk_up_for_root(os.getcwd()) or os.getcwd()]


def _anchor_root(paths: List[str]) -> str:
    """The root findings fingerprint against and the default baseline
    resolves from: the enclosing repo root when the input paths live in
    one, else their common root.  A path-scoped run
    (``triton-lint triton_client_tpu/server``) must fingerprint findings
    identically to a full-repo run, or the repo-root baseline can never
    match them."""
    common = common_root(paths)
    return _walk_up_for_root(common) or common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="triton-lint",
        description="project-native static analysis: the semantic "
                    "invariants this codebase has repeatedly violated, "
                    "as checkers")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the "
                        "enclosing repo root)")
    p.add_argument("--rule", action="append", dest="rules", metavar="RULE",
                   help="run only this rule (repeatable)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", metavar="PATH",
                   help="baseline file (default: <root>/"
                        f"{DEFAULT_BASELINE_NAME} when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for name, help_text in sorted(rule_help().items()):
            print(f"{name}: {help_text}")
        return 0
    paths = args.paths or _default_paths()
    root = _anchor_root(paths)
    try:
        pairs = collect_files(paths, root=root)
    except FileNotFoundError as e:
        print(f"triton-lint: {e}", file=sys.stderr)
        return 2
    if not pairs:
        print("triton-lint: no python files found", file=sys.stderr)
        return 2
    project = build_project(paths, pairs=pairs)
    try:
        findings = run_rules(project, rules=args.rules)
    except ValueError as e:
        print(f"triton-lint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(root,
                                                  DEFAULT_BASELINE_NAME)
    # Staleness ("the baseline only ever shrinks") is a FULL-TREE
    # property: a path-scoped run cannot tell whether a finding outside
    # its scan still reproduces — cross-file rules (METRICS-DECL,
    # LOCK-ORDER cycles) need files the scope excludes.  So scoped runs
    # never judge stale and a scoped --write-baseline merges by
    # fingerprint union; only a full-root run shrinks the file.  Rule
    # scoping is different: the full tree is scanned, so staleness
    # within the selected rules is sound.
    scoped = any(os.path.relpath(os.path.abspath(p), root) not in (".", "")
                 for p in paths)
    selected = {r.upper() for r in args.rules} if args.rules else None

    def rule_in_scope(e) -> bool:
        return selected is None \
            or str(e.get("rule", "")).upper() in selected

    if args.write_baseline:
        entries = [baseline_entry(fd) for fd in findings]
        if (selected or scoped) and os.path.exists(baseline_path):
            try:
                old = load_baseline(baseline_path)
            except (ValueError, OSError) as e:
                print(f"triton-lint: bad baseline: {e}", file=sys.stderr)
                return 2
            if scoped:
                have = {entry_fingerprint(e) for e in entries}
                entries += [e for e in old
                            if entry_fingerprint(e) not in have]
            else:
                entries += [e for e in old if not rule_in_scope(e)]
        write_baseline_entries(baseline_path, entries)
        print(f"wrote {len(entries)} finding(s) to {baseline_path}")
        return 0
    stale = []
    if not args.no_baseline and os.path.exists(baseline_path):
        try:
            entries = load_baseline(baseline_path)
        except (ValueError, OSError) as e:
            print(f"triton-lint: bad baseline: {e}", file=sys.stderr)
            return 2
        stale = apply_baseline(findings,
                               [e for e in entries if rule_in_scope(e)])
        if scoped:
            stale = []

    render = render_json if args.format == "json" else render_text
    print(render(findings, stale_baseline=stale,
                 files_scanned=len(project.files)))
    fresh = [fd for fd in findings if not fd.baselined]
    return 1 if (fresh or stale) else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
