"""The ``triton-lint`` engine: file model, rule registry, pragmas, baseline.

This is a *project-native* static-analysis framework (stdlib ``ast`` only —
the tools package is dependency-free by contract).  Generic linters catch
style; the rules registered here encode semantic invariants this codebase
has repeatedly violated and hand-fixed in review — blocking calls on the
event loop, lock discipline in the stats collectors, the typed exception
contract of the four client cores, span lifecycle, metrics-registry drift,
and test determinism.  Each rule module documents the historical bug it
encodes (see ARCHITECTURE.md "Static analysis").

Framework pieces:

* :class:`Finding` — one diagnostic: ``(rule, path, line, message)`` plus a
  ``symbol`` (the enclosing ``Class.function`` scope) used for stable
  baseline fingerprints (line numbers churn; symbols rarely do).
* :class:`SourceFile` — one parsed file: source, ast, and the suppression
  pragmas scanned from its comments.
* :class:`Project` — the whole lint run's file set.  Rules receive the
  project, so cross-file rules (lock graphs, the metrics registry) see
  everything in one pass.
* **pragmas** — ``# tpu-lint: disable=RULE[,RULE2] <reason>`` on the
  finding's line (or the line above) suppresses it.  A pragma without a
  reason is itself reported (rule ``PRAGMA``): an unexplained suppression
  is exactly the review debt this tool exists to prevent.
* **baseline** — a checked-in JSON file of grandfathered findings, matched
  by ``(rule, path, symbol, message)`` fingerprint.  New findings fail the
  gate; baselined ones report separately.  ``--write-baseline`` refreshes
  it; stale entries (baselined but no longer found) are reported so the
  baseline only ever shrinks.
"""

from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from io import StringIO
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "SourceFile",
    "Project",
    "register_rule",
    "rule_names",
    "rule_help",
    "run_rules",
    "load_baseline",
    "baseline_entry",
    "entry_fingerprint",
    "apply_baseline",
    "render_text",
    "render_json",
    "collect_files",
    "build_project",
    "DEFAULT_BASELINE_NAME",
]

DEFAULT_BASELINE_NAME = ".tpu-lint-baseline.json"

_PRAGMA_RE = re.compile(
    r"#\s*tpu-lint:\s*disable=([A-Za-z0-9_,-]+)\s*(.*)$")


class Finding:
    """One diagnostic.  ``symbol`` is the enclosing scope (``Class.fn`` /
    ``fn`` / ``<module>``) — with ``rule``, ``path`` and ``message`` it
    forms the baseline fingerprint, deliberately excluding the line number
    so unrelated edits above a grandfathered finding don't un-baseline it."""

    __slots__ = ("rule", "path", "line", "message", "symbol", "baselined")

    def __init__(self, rule: str, path: str, line: int, message: str,
                 symbol: str = "<module>") -> None:
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.message = message
        self.symbol = symbol
        self.baselined = False

    def fingerprint(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.symbol,
                _normalize_message(self.message))

    def as_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "baselined": self.baselined,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Finding({self.rule}, {self.path}:{self.line}, {self.message!r})"


class SourceFile:
    """One parsed source file plus its suppression pragmas."""

    def __init__(self, path: str, relpath: str, source: str) -> None:
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        # lineno -> (set of rule names, reason text)
        self.pragmas: Dict[int, Tuple[set, str]] = {}
        try:
            self.tree = ast.parse(source)
        except SyntaxError as e:
            self.parse_error = f"{e.msg} (line {e.lineno})"
        self._scan_pragmas()

    # -- pragmas -----------------------------------------------------------
    def _scan_pragmas(self) -> None:
        """Comment scan via tokenize so pragmas inside string literals are
        never honored (a string containing the pragma text must not
        suppress anything)."""
        try:
            tokens = tokenize.generate_tokens(StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _PRAGMA_RE.search(tok.string)
                if m:
                    rules = {r.strip().upper()
                             for r in m.group(1).split(",") if r.strip()}
                    self.pragmas[tok.start[0]] = (rules, m.group(2).strip())
        except (tokenize.TokenError, SyntaxError):
            # unparseable tail or tokenize-level IndentationError (a
            # SyntaxError subclass ast.parse may not raise first); the
            # PARSE finding already reports the file
            pass

    def suppressed(self, rule: str, line: int) -> bool:
        """A finding is suppressed by a pragma on its own line or the line
        directly above (the decorator/comment-line idiom)."""
        for ln in (line, line - 1):
            entry = self.pragmas.get(ln)
            if entry and rule.upper() in entry[0]:
                return True
        return False

    # -- scope lookup ------------------------------------------------------
    def symbol_at(self, line: int) -> str:
        """The ``Class.function`` scope enclosing ``line`` (for baseline
        fingerprints)."""
        if self.tree is None:
            return "<module>"
        best: List[str] = []

        def walk(node, stack):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    end = getattr(child, "end_lineno", child.lineno)
                    if child.lineno <= line <= (end or child.lineno):
                        new = stack + [child.name]
                        if len(new) > len(best):
                            best[:] = new
                        walk(child, new)
                else:
                    walk(child, stack)

        walk(self.tree, [])
        return ".".join(best) if best else "<module>"


class Project:
    """The lint run's file set, in scan order.  Rules receive the whole
    project so cross-file rules (lock graphs, the metrics registry) see
    everything in one pass."""

    def __init__(self, files: Sequence[SourceFile]) -> None:
        self.files = list(files)


# -- rule registry ----------------------------------------------------------

#: name -> (check callable, one-line help).  A check takes the Project and
#: yields Findings; suppression/baseline filtering happen in the runner.
_RULES: Dict[str, Tuple[Callable[[Project], Iterable[Finding]], str]] = {}

#: Engine-level pseudo-rules: selectable and in the default set like any
#: registered rule, but produced by the runner itself.
_ENGINE_RULES: Dict[str, str] = {
    "PARSE": "a file the linter was asked to check does not parse",
    "PRAGMA": "a suppression pragma must carry a reason "
              "(# tpu-lint: disable=RULE <why>)",
}


def register_rule(name: str, help_text: str):
    """Decorator registering ``fn(project) -> Iterable[Finding]`` under
    ``name`` (upper-case by convention, e.g. ``ASYNC-BLOCK``)."""

    def deco(fn):
        _RULES[name] = (fn, help_text)
        return fn

    return deco


def rule_names() -> List[str]:
    return sorted(set(_RULES) | set(_ENGINE_RULES))


def rule_help() -> Dict[str, str]:
    out = {name: help_text for name, (_fn, help_text) in _RULES.items()}
    out.update(_ENGINE_RULES)
    return out


def run_rules(project: Project,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the selected rules (default: all) over the project.  ``PARSE``
    (syntax errors) and ``PRAGMA`` (reasonless suppressions) are
    engine-level pseudo-rules — in the default set, and selectable/
    excludable exactly like registered rules, so a single-rule run never
    fails on an unrelated file."""
    # dedupe while preserving order: a repeated --rule flag must not run
    # the rule twice and double every finding
    selected = list(dict.fromkeys(r.upper() for r in rules)) if rules \
        else rule_names()
    unknown = [r for r in selected
               if r not in _RULES and r not in _ENGINE_RULES]
    if unknown:
        raise ValueError(
            f"unknown rule(s): {', '.join(unknown)} "
            f"(known: {', '.join(rule_names())})")
    findings: List[Finding] = []
    for f in project.files:
        if f.parse_error is not None and "PARSE" in selected:
            findings.append(Finding(
                "PARSE", f.relpath, 1, f"syntax error: {f.parse_error}"))
        if "PRAGMA" in selected:
            for line, (rules_set, reason) in sorted(f.pragmas.items()):
                if not reason:
                    findings.append(Finding(
                        "PRAGMA", f.relpath, line,
                        "suppression pragma without a reason "
                        "(# tpu-lint: disable=RULE <why>)",
                        symbol=f.symbol_at(line)))
    for name in selected:
        if name in _ENGINE_RULES:
            continue
        fn, _help = _RULES[name]
        for finding in fn(project):
            findings.append(finding)
    out = []
    by_path = {f.relpath: f for f in project.files}
    for fd in findings:
        src = by_path.get(fd.path)
        if src is not None and fd.rule != "PRAGMA" \
                and src.suppressed(fd.rule, fd.line):
            continue
        out.append(fd)
    out.sort(key=lambda fd: (fd.path, fd.line, fd.rule, fd.message))
    return out


# -- baseline ---------------------------------------------------------------

_MSG_LINE_REFS = (re.compile(r"\bline \d+"), re.compile(r"(\.py):\d+"))


def _normalize_message(msg: str) -> str:
    """Messages may cite line numbers for humans ("first at line 12",
    "core.py:88"); the baseline fingerprint must not — line churn above a
    grandfathered finding would otherwise un-baseline it AND strand its
    entry as stale.  Stored entries keep the raw message; matching
    normalizes both sides."""
    msg = _MSG_LINE_REFS[0].sub("line <n>", msg)
    return _MSG_LINE_REFS[1].sub(r"\1:<n>", msg)


def baseline_entry(fd: Finding) -> Dict[str, str]:
    return {"rule": fd.rule, "path": fd.path, "symbol": fd.symbol,
            "message": fd.message}


def entry_fingerprint(e: Dict[str, str]) -> Tuple[str, str, str, str]:
    """A stored entry's fingerprint, normalized the same way
    :meth:`Finding.fingerprint` is."""
    return (str(e.get("rule", "")), str(e.get("path", "")),
            str(e.get("symbol", "")),
            _normalize_message(str(e.get("message", ""))))


def load_baseline(path: str) -> List[Dict[str, str]]:
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "findings" not in data \
            or not isinstance(data["findings"], list) \
            or not all(isinstance(e, dict) for e in data["findings"]):
        raise ValueError(
            f"baseline {path} must be an object with a 'findings' list "
            "of objects")
    return data["findings"]


def apply_baseline(findings: List[Finding],
                   entries: List[Dict[str, str]]) -> List[Dict[str, str]]:
    """Mark baselined findings in place; return the STALE baseline entries
    (grandfathered findings that no longer occur — prune them, the
    baseline only ever shrinks).  Each entry absorbs one finding."""
    budget: Dict[Tuple[str, str, str, str], int] = {}
    raw_by_key: Dict[Tuple[str, str, str, str], List[Dict[str, str]]] = {}
    for e in entries:
        key = entry_fingerprint(e)
        budget[key] = budget.get(key, 0) + 1
        raw_by_key.setdefault(key, []).append(e)
    for fd in findings:
        key = fd.fingerprint()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            fd.baselined = True
    stale = []
    for key, left in sorted(budget.items()):
        # report the raw stored entries (readable messages), newest last
        for e in raw_by_key[key][len(raw_by_key[key]) - left:]:
            stale.append({"rule": str(e.get("rule", "")),
                          "path": str(e.get("path", "")),
                          "symbol": str(e.get("symbol", "")),
                          "message": str(e.get("message", ""))})
    return stale


def write_baseline(path: str, findings: List[Finding]) -> None:
    write_baseline_entries(path, [baseline_entry(fd) for fd in findings])


def write_baseline_entries(path: str,
                           entries: List[Dict[str, str]]) -> None:
    data = {
        "version": 1,
        "comment": "grandfathered triton-lint findings; do not add entries "
                   "— fix the code or carry a reasoned pragma instead",
        "findings": sorted(
            entries, key=lambda e: (e.get("rule", ""), e.get("path", ""),
                                    e.get("symbol", ""),
                                    e.get("message", ""))),
    }
    with open(path, "w") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


# -- reporters --------------------------------------------------------------

def render_text(findings: List[Finding],
                stale_baseline: Optional[List[Dict[str, str]]] = None,
                files_scanned: int = 0) -> str:
    lines = []
    fresh = [fd for fd in findings if not fd.baselined]
    base = [fd for fd in findings if fd.baselined]
    for fd in fresh:
        lines.append(f"{fd.path}:{fd.line}: {fd.rule} [{fd.symbol}] "
                     f"{fd.message}")
    for fd in base:
        lines.append(f"{fd.path}:{fd.line}: {fd.rule} [baselined] "
                     f"{fd.message}")
    for e in (stale_baseline or []):
        lines.append(f"stale baseline entry: {e['rule']} {e['path']} "
                     f"[{e['symbol']}] {e['message']}")
    lines.append(
        f"{len(fresh)} finding(s), {len(base)} baselined, "
        f"{len(stale_baseline or [])} stale baseline entr(ies), "
        f"{files_scanned} file(s) scanned")
    return "\n".join(lines)


def render_json(findings: List[Finding],
                stale_baseline: Optional[List[Dict[str, str]]] = None,
                files_scanned: int = 0) -> str:
    """The stable machine shape (pinned by tests/test_lint.py — scripts may
    depend on every key here)."""
    fresh = [fd for fd in findings if not fd.baselined]
    payload = {
        "version": 1,
        "files_scanned": files_scanned,
        "findings": [fd.as_dict() for fd in findings],
        "counts": {
            "total": len(findings),
            "fresh": len(fresh),
            "baselined": len(findings) - len(fresh),
            "by_rule": _count_by_rule(fresh),
        },
        "stale_baseline": list(stale_baseline or []),
    }
    return json.dumps(payload, indent=2, sort_keys=True)


def _count_by_rule(findings: List[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for fd in findings:
        out[fd.rule] = out.get(fd.rule, 0) + 1
    return out


# -- file collection --------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".eggs", "build", "dist", "node_modules",
              "venv", "site-packages"}


def _skip_dir(name: str) -> bool:
    # hidden directories cover .git/.venv/.tox/.claude/...; an in-repo
    # virtualenv must never leak third-party code into the zero-finding
    # gate (or the walk time)
    return name.startswith(".") or name in _SKIP_DIRS


def collect_files(paths: Sequence[str],
                  root: Optional[str] = None) -> List[Tuple[str, str]]:
    """Expand the CLI path arguments into ``(abspath, relpath)`` pairs.
    Directories walk recursively for ``*.py``; relpaths are relative to
    ``root`` when given (the CLI passes the enclosing repo root so a
    path-scoped run fingerprints findings identically to a full run and
    matches the repo-root baseline), else to the common root of the
    *input* paths.  A path that does not exist raises
    ``FileNotFoundError`` — a renamed file in a CI invocation must fail
    loudly, never report an empty-but-green run."""
    abspaths: List[str] = []
    for p in paths:
        ap = os.path.abspath(p)
        if not os.path.exists(ap):
            raise FileNotFoundError(f"no such file or directory: {p}")
        if os.path.isdir(ap):
            for walk_dir, dirs, files in os.walk(ap):
                dirs[:] = sorted(d for d in dirs if not _skip_dir(d))
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        abspaths.append(os.path.join(walk_dir, fn))
        else:
            # an explicitly-passed FILE is always linted, extension or
            # not (extensionless scripts are python too) — silently
            # skipping a path the operator named would be an
            # empty-but-green run for that file
            abspaths.append(ap)
    seen = set()
    uniq = []
    for ap in abspaths:
        if ap not in seen:
            seen.add(ap)
            uniq.append(ap)
    if not uniq:
        return []
    root = root or common_root(paths)
    return [(ap, os.path.relpath(ap, root)) for ap in uniq]


def common_root(paths: Sequence[str]) -> str:
    """The shared root of the INPUT paths (files contribute their
    directory)."""
    dirs = []
    for p in paths:
        ap = os.path.abspath(p)
        dirs.append(ap if os.path.isdir(ap) else os.path.dirname(ap))
    return os.path.commonpath(dirs) if dirs else os.getcwd()


def build_project(paths: Sequence[str],
                  pairs: Optional[List[Tuple[str, str]]] = None) -> Project:
    """Build the project from ``paths``; pass ``pairs`` (a prior
    ``collect_files`` result) to avoid walking the tree twice."""
    files = []
    for ap, rel in (pairs if pairs is not None else collect_files(paths)):
        try:
            with open(ap, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        except OSError:
            continue
        files.append(SourceFile(ap, rel, source))
    return Project(files)
