"""Locate-or-build helper for the framework's native (C++) shared libraries.

The wheel ships prebuilt ``.so``s next to their Python consumers (like the
reference wheel bundles ``libcshm.so``, setup.py:78-80).  In a source checkout
the library is built on first use with ``g++`` into ``native/build/`` so tests
and examples are hermetic — no separate build step required.
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LOCK = threading.Lock()


def find_or_build(
    lib_name: str,
    sources: List[str],
    extra_flags: Optional[List[str]] = None,
) -> str:
    """Return an absolute path to ``lib_name`` (e.g. ``libcshm.so``).

    Search order: alongside this package (wheel layout), then
    ``native/build/`` (source layout, compiled on demand).
    """
    packaged = os.path.join(os.path.dirname(os.path.abspath(__file__)), lib_name)
    if os.path.exists(packaged):
        return packaged

    built = os.path.join(_BUILD_DIR, lib_name)
    srcs = [os.path.join(_REPO_ROOT, s) for s in sources]
    with _LOCK:
        if _is_fresh(built, srcs):
            return built
        os.makedirs(_BUILD_DIR, exist_ok=True)
        # Compile to a process-unique temp name and rename into place so
        # concurrent processes (e.g. parallel test workers) never dlopen a
        # half-written .so.
        tmp_out = f"{built}.{os.getpid()}.tmp"
        cmd = [
            "g++",
            "-std=c++17",
            "-O2",
            "-fPIC",
            "-shared",
            "-Wall",
            "-Wextra",
            *srcs,
            "-o",
            tmp_out,
            "-lrt",
            "-pthread",
        ] + (extra_flags or [])
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp_out, built)
    return built


def _is_fresh(lib_path: str, sources: List[str]) -> bool:
    if not os.path.exists(lib_path):
        return False
    lib_mtime = os.path.getmtime(lib_path)
    return all(os.path.getmtime(s) <= lib_mtime for s in sources if os.path.exists(s))
