"""perf_analyzer-equivalent load generator.

The reference repo ships only stub READMEs for perf_analyzer
(src/c++/perf_analyzer/README.md:28-30 — source relocated), so this tool is
designed from its CLI contract (SURVEY.md "critical absences"): closed-loop
concurrency sweeps AND open-loop request-rate sweeps
(``--request-rate-range`` with constant/Poisson arrivals) reporting
infer/sec and latency percentiles, with
``--shared-memory={none,system,cuda,xla}`` data-path modes (BASELINE north
star: the ``cuda`` mode maps to TPU xla shared memory).

Open-loop latency is measured from each request's SCHEDULED send time, so
server queue buildup counts against the percentiles instead of throttling
the generator — closed-loop numbers are subject to coordinated omission
(the sweep only sends as fast as the server answers) and BASELINE.md labels
which rows are which.

Usage:
    python -m triton_client_tpu.perf_analyzer -m simple -u localhost:8001 \
        -i grpc --concurrency-range 1:8:2 --shared-memory system
    python -m triton_client_tpu.perf_analyzer -m simple -u localhost:8000 \
        --request-rate-range 100:400:100 --request-distribution poisson
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

import numpy as np

from ._telemetry import LatencyHistogram, telemetry
from .utils import triton_to_np_dtype

_SHM_MODES = ("none", "system", "cuda", "xla")


@dataclass
class _Stats:
    # log-bucketed shared histogram (telemetry layer) instead of a raw
    # sample list: constant memory at any request count, thread-safe
    # observe, same quantile math as the client metrics
    latency: LatencyHistogram = field(default_factory=LatencyHistogram)
    count: int = 0
    errors: int = 0
    # requests shed by server admission control (HTTP 429 / gRPC
    # RESOURCE_EXHAUSTED) — a subset of errors, reported separately so a
    # sweep shows WHERE a concurrency level starts overrunning the server
    rejected: int = 0
    first_error: Optional[str] = None


def _is_rejected(err: Exception) -> bool:
    from ._resilience import normalized_status

    return normalized_status(err) in ("429", "RESOURCE_EXHAUSTED")


def _retries_recorded(model_name: str) -> int:
    """Cumulative client-layer retries for ``model_name`` from the
    process-wide telemetry registry (delta'd around each sweep level)."""
    return sum(r.get("retries", 0)
               for r in telemetry().snapshot()["requests"]
               if r["model"] == model_name)


def _cluster_recorded():
    """Cumulative cluster-layer routing counters: per-endpoint request
    totals plus hedge issue/win counts (delta'd around each level, like
    retries)."""
    snap = telemetry().snapshot()
    dist = {e["endpoint"]: e["success"] + e["failure"]
            for e in snap["endpoints"]}
    hedges = sum(h["hedges"] for h in snap["hedges"])
    wins = sum(h["wins"] for h in snap["hedges"])
    return dist, hedges, wins


def _make_client_factory(protocol, url, concurrency,
                         balancing="least_outstanding", hedge_ms=0.0):
    """(protocol module, client factory, shared cluster client) for one
    sweep level.  ``url`` may be a single endpoint or a list — two or
    more endpoints switch the sweep onto ONE ``ClusterClient`` shared by
    every worker (health-checked balancing, per-endpoint counters,
    optional hedging at ``hedge_ms``).  Shared, not per-worker: the
    least-outstanding policy and the breakers route on pool state, and a
    private pool per worker only ever sees that worker's single in-flight
    request — which would silently degrade the policy to random choice.
    The caller owns (and closes) the shared client; per-worker sessions
    must not."""
    urls = list(url) if isinstance(url, (list, tuple)) else [url]
    if protocol == "grpc":
        import triton_client_tpu.grpc as protocol_mod

        client_kwargs = {}
    else:
        import triton_client_tpu.http as protocol_mod

        client_kwargs = {"concurrency": concurrency}
    if len(urls) > 1:
        from .cluster import ClusterClient, HedgePolicy

        # min_samples pinned high: --hedge-ms promises a FIXED delay, and
        # HedgePolicy would otherwise switch to the observed p95 as soon
        # as 16 samples accumulate (i.e. during warmup)
        hedge = (HedgePolicy(default_delay_s=hedge_ms / 1e3,
                             min_samples=1 << 30)
                 if hedge_ms > 0 else None)
        shared = ClusterClient(
            urls, protocol=protocol, policy=balancing, hedge=hedge,
            client_kwargs=client_kwargs,
            # hedged attempts run on the client's executor: it must cover
            # concurrency primaries + their backups, or levels above the
            # default pool size would measure the executor, not the fleet
            hedge_workers=max(32, 2 * concurrency))
        return protocol_mod, (lambda: shared), shared
    make_client = lambda: protocol_mod.InferenceServerClient(
        urls[0], **client_kwargs)
    return protocol_mod, make_client, None


def _parse_concurrency_range(spec: str):
    parts = [int(p) for p in spec.split(":")]
    start = parts[0]
    end = parts[1] if len(parts) > 1 else start
    step = parts[2] if len(parts) > 2 else 1
    return list(range(start, end + 1, max(step, 1)))


def _parse_shapes(shape_args: List[str]) -> Dict[str, List[int]]:
    shapes = {}
    for s in shape_args or []:
        name, sep, dims = s.rpartition(":")
        if not sep or not name or not dims:
            raise ValueError(
                f"invalid --shape '{s}': expected <input name>:<d1>[,<d2>...]"
            )
        shapes[name] = [int(d) for d in dims.split(",")]
    return shapes


def _resolve_model(client, protocol: str, model_name: str, model_version: str):
    if protocol == "grpc":
        md = client.get_model_metadata(model_name, model_version, as_json=True)
        cfg = client.get_model_config(model_name, model_version, as_json=True)
        if "config" in cfg:
            cfg = cfg["config"]
    else:
        md = client.get_model_metadata(model_name, model_version)
        cfg = client.get_model_config(model_name, model_version)
    max_batch = int(cfg.get("max_batch_size", 0))
    inputs = []
    for i in md["inputs"]:
        shape = [int(s) for s in i["shape"]]
        inputs.append({"name": i["name"], "datatype": i["datatype"], "shape": shape})
    outputs = [o["name"] for o in md["outputs"]]
    return inputs, outputs, max_batch


def _make_data(inputs, shapes, batch: int, max_batch: int, rng, string_length=16):
    arrays = {}
    for spec in inputs:
        dims = list(shapes.get(spec["name"], []))
        if not dims:
            dims = list(spec["shape"])
            if max_batch > 0:
                dims = dims[1:]  # strip batch dim; re-added below
            dims = [d if d > 0 else 1 for d in dims]
        if max_batch > 0:
            dims = [batch] + dims
        dt = triton_to_np_dtype(spec["datatype"])
        if spec["datatype"] == "BYTES":
            arr = np.array(
                [b"x" * string_length for _ in range(int(np.prod(dims)))],
                dtype=np.object_,
            ).reshape(dims)
        elif np.issubdtype(dt, np.floating):
            arr = rng.random(dims).astype(dt)
        elif dt == np.bool_:
            arr = rng.integers(0, 2, dims).astype(np.bool_)
        else:
            arr = rng.integers(0, 127, dims).astype(dt)
        arrays[spec["name"]] = arr
    return arrays


class _ShmSetup:
    """Per-worker shared-memory regions (registered under unique names)."""

    def __init__(self, mode, protocol_mod, client, arrays, outputs, worker_id,
                 output_byte_size):
        self.mode = mode
        self.handles = {}
        self.client = client
        self.names = []
        self.output_byte_size = output_byte_size
        if mode == "none":
            return
        if mode == "system":
            from .utils import shared_memory as shm

            self._shm = shm
        else:
            from .utils import xla_shared_memory as shm

            self._shm = shm
        try:
            self._create_regions(arrays, outputs, worker_id, client)
        except Exception:
            self.cleanup()  # release whatever was created before the failure
            raise

    def _create_regions(self, arrays, outputs, worker_id, client):
        for name, arr in arrays.items():
            payload = _serialize(arr)
            region = f"pa_in_{worker_id}_{name}"
            if self.mode == "system":
                h = self._shm.create_shared_memory_region(
                    region, f"/{region}", payload.nbytes)
                self._shm.set_shared_memory_region(h, [payload])
                client.register_system_shared_memory(
                    region, f"/{region}", payload.nbytes)
            else:
                h = self._shm.create_shared_memory_region(region, payload.nbytes, 0)
                self._shm.set_shared_memory_region(h, [arr])
                client.register_cuda_shared_memory(
                    region, self._shm.get_raw_handle(h), 0, payload.nbytes)
            self.handles[("in", name)] = (region, h, payload.nbytes)
            self.names.append(region)
        for name in outputs:
            region = f"pa_out_{worker_id}_{name}"
            if self.mode == "system":
                h = self._shm.create_shared_memory_region(
                    region, f"/{region}", self.output_byte_size)
                client.register_system_shared_memory(
                    region, f"/{region}", self.output_byte_size)
            else:
                h = self._shm.create_shared_memory_region(region, self.output_byte_size, 0)
                client.register_cuda_shared_memory(
                    region, self._shm.get_raw_handle(h), 0, self.output_byte_size)
            self.handles[("out", name)] = (region, h, self.output_byte_size)
            self.names.append(region)

    def attach(self, infer_inputs, requested_outputs):
        if self.mode == "none":
            return
        for inp in infer_inputs:
            region, _h, nbytes = self.handles[("in", inp.name())]
            inp.set_shared_memory(region, nbytes)
        for out in requested_outputs:
            region, _h, nbytes = self.handles[("out", out.name())]
            out.set_shared_memory(region, nbytes)

    def cleanup(self):
        if self.mode == "none":
            return
        for (kind, _tname), (region, h, _n) in self.handles.items():
            try:
                if self.mode == "system":
                    self.client.unregister_system_shared_memory(region)
                else:
                    self.client.unregister_cuda_shared_memory(region)
            except Exception:
                pass
            try:
                self._shm.destroy_shared_memory_region(h)
            except Exception:
                pass


def _serialize(arr: np.ndarray) -> np.ndarray:
    if arr.dtype == np.object_ or arr.dtype.kind in ("S", "U"):
        from .utils import serialize_byte_tensor

        return serialize_byte_tensor(arr)
    return np.ascontiguousarray(arr)


def _build_inputs(protocol_mod, arrays, shm_mode):
    from .utils import np_to_triton_dtype

    infer_inputs = []
    for name, arr in arrays.items():
        dt = ("BYTES" if arr.dtype == np.object_
              else np_to_triton_dtype(arr.dtype))
        inp = protocol_mod.InferInput(name, list(arr.shape), dt)
        if shm_mode == "none":
            inp.set_data_from_numpy(arr)
        infer_inputs.append(inp)
    return infer_inputs


def _worker(protocol_mod, make_client, model_name, model_version, arrays,
            outputs, shm_mode, output_byte_size, worker_id, stop, measuring,
            stats: _Stats, lock, streaming=False, retry_policy=None,
            owns_client=True, qos_class=None):
    try:
        _worker_impl(protocol_mod, make_client, model_name, model_version,
                     arrays, outputs, shm_mode, output_byte_size, worker_id,
                     stop, measuring, stats, lock, streaming, retry_policy,
                     owns_client, qos_class)
    except Exception as e:
        # Setup failures (bad model, shm registration, stream open) must be
        # visible in the report, not a silently dead worker thread.
        with lock:
            stats.errors += 1
            if stats.first_error is None:
                stats.first_error = f"worker setup: {type(e).__name__}: {e}"


class _InferSession:
    """One worker's client + inputs + shm regions + infer callable — shared
    by the closed-loop (concurrency) and open-loop (request-rate) drivers.

    ``qos_class`` is an optional ``(priority, tenant)`` pair stamped on
    every request this session sends (mixed-tier sweeps assign one class
    per worker)."""

    def __init__(self, protocol_mod, make_client, model_name, model_version,
                 arrays, outputs, shm_mode, output_byte_size, worker_id,
                 streaming, retry_policy=None, owns_client=True,
                 qos_class=None):
        self._client = make_client()
        # False when make_client hands out a SHARED client (cluster
        # sweeps): the level owns its lifetime, not this worker
        self._owns_client = owns_client
        self._shm_setup = None
        self._stream_open = False
        try:
            infer_inputs = _build_inputs(protocol_mod, arrays, shm_mode)
            requested = [protocol_mod.InferRequestedOutput(o) for o in outputs]
            self._shm_setup = _ShmSetup(shm_mode, protocol_mod, self._client,
                                        arrays, outputs, worker_id,
                                        output_byte_size)
            self._shm_setup.attach(infer_inputs, requested)

            priority, tenant = qos_class if qos_class else (0, None)
            if streaming:
                # Async streaming mode (reference perf_analyzer --streaming):
                # requests ride one bidi gRPC stream per worker; completion
                # is the callback on the stream reader thread.
                import queue as _queue

                done: "_queue.Queue" = _queue.Queue()
                self._client.start_stream(
                    callback=lambda result, error: done.put(error))
                self._stream_open = True
                # completions owed from timed-out requests: they must be
                # discarded when they eventually land, or every later
                # request would be paired with its predecessor's completion
                stale = [0]
                client = self._client

                def one_infer():
                    client.async_stream_infer(
                        model_name, infer_inputs, outputs=requested,
                        model_version=model_version, priority=priority)
                    try:
                        while True:
                            err = done.get(timeout=120)
                            if stale[0] > 0:
                                stale[0] -= 1
                                continue
                            if err is not None:
                                raise err
                            return
                    except _queue.Empty:
                        stale[0] += 1
                        raise TimeoutError("stream completion timed out")
            else:
                client = self._client
                # wire fast path: compile the request template once per
                # session (specs are fixed for the whole sweep) so each
                # call re-stamps id/deadline/bytes instead of rebuilding
                # the header.  ONLY a client without prepare()
                # (ClusterClient, custom factories) falls back to the
                # slow path — a real template-compile failure must
                # surface as a worker setup error, not silently downgrade
                # the sweep it claims to measure.
                prep = None
                try:
                    prepare = client.prepare
                except AttributeError:
                    prepare = None
                if prepare is not None:
                    prep = prepare(
                        model_name, infer_inputs,
                        model_version=model_version, outputs=requested,
                        priority=priority)
                if prep is not None:
                    fast = prep

                    def one_infer():
                        fast.infer(retry_policy=retry_policy, tenant=tenant)
                else:
                    def one_infer():
                        # retry_policy=None is the no-resilience default;
                        # with --retries the sweep measures the retry
                        # layer under load
                        client.infer(model_name, infer_inputs,
                                     outputs=requested,
                                     model_version=model_version,
                                     retry_policy=retry_policy,
                                     priority=priority, tenant=tenant)

            self.infer = one_infer
        except Exception:
            self.close()
            raise

    def close(self):
        if self._stream_open:
            try:
                self._client.stop_stream()
            except Exception:
                pass
        if self._shm_setup is not None:
            self._shm_setup.cleanup()
        if self._owns_client:
            try:
                self._client.close()
            except Exception:
                pass


def _worker_impl(protocol_mod, make_client, model_name, model_version, arrays,
                 outputs, shm_mode, output_byte_size, worker_id, stop,
                 measuring, stats: _Stats, lock, streaming=False,
                 retry_policy=None, owns_client=True, qos_class=None):
    session = _InferSession(protocol_mod, make_client, model_name,
                            model_version, arrays, outputs, shm_mode,
                            output_byte_size, worker_id, streaming,
                            retry_policy, owns_client, qos_class)
    one_infer = session.infer
    try:
        n = 0
        errs = 0
        rejected = 0
        first_error = None
        while not stop.is_set():
            t0 = time.perf_counter()
            err = None
            try:
                one_infer()
            except Exception as e:
                err = e
            dt_s = time.perf_counter() - t0
            # `measuring` is cleared at the deadline, so completions landing
            # after the window closes are not counted (would inflate infer/sec)
            if measuring.is_set():
                if err is None:
                    stats.latency.observe(dt_s)  # thread-safe, lock-cheap
                    n += 1
                else:
                    errs += 1
                    if _is_rejected(err):
                        rejected += 1
                    if first_error is None:
                        first_error = f"{type(err).__name__}: {err}"
        with lock:
            stats.count += n
            stats.errors += errs
            stats.rejected += rejected
            if stats.first_error is None and first_error is not None:
                stats.first_error = first_error
    finally:
        session.close()


def run_level(protocol, url, model_name, model_version, concurrency, arrays,
              outputs, shm_mode, output_byte_size, measure_s, warmup_s=1.0,
              extra_percentile=None, streaming=False, retry_policy=None,
              balancing="least_outstanding", hedge_ms=0.0,
              qos_classes=None):
    """One closed-loop level.  ``qos_classes`` is an optional list of
    ``(priority, tenant)`` pairs for mixed-tier sweeps: worker ``w`` sends
    as class ``w % len(classes)``, stats are kept per class, and the
    result gains a per-class ``classes`` breakdown next to the merged
    totals."""
    protocol_mod, make_client, shared_client = _make_client_factory(
        protocol, url, concurrency, balancing, hedge_ms)
    cluster_mode = isinstance(url, (list, tuple)) and len(url) > 1

    classes = list(qos_classes) if qos_classes else [(0, None)]
    class_stats = [_Stats() for _ in classes]
    lock = threading.Lock()
    stop = threading.Event()
    measuring = threading.Event()
    threads = [
        threading.Thread(
            target=_worker,
            args=(protocol_mod, make_client, model_name, model_version, arrays,
                  outputs, shm_mode, output_byte_size, w, stop, measuring,
                  class_stats[w % len(classes)], lock, streaming,
                  retry_policy, shared_client is None,
                  classes[w % len(classes)]),
            daemon=True,
        )
        for w in range(concurrency)
    ]
    for t in threads:
        t.start()
    time.sleep(warmup_s)
    # retry delta scoped to the measure window, like count/errors —
    # warmup-window retries must not inflate the reported level
    retries_before = _retries_recorded(model_name)
    if cluster_mode:
        dist_before, hedges_before, wins_before = _cluster_recorded()
    measuring.set()
    t0 = time.perf_counter()
    time.sleep(measure_s)
    measuring.clear()
    elapsed = time.perf_counter() - t0
    stop.set()
    for t in threads:
        t.join(timeout=30)
    if shared_client is not None:
        shared_client.close()
    # merge per-class stats into the level totals (single-class sweeps
    # merge exactly one, i.e. the old behavior)
    stats = _Stats()
    for s in class_stats:
        stats.latency.merge(s.latency)
        stats.count += s.count
        stats.errors += s.errors
        stats.rejected += s.rejected
        if stats.first_error is None:
            stats.first_error = s.first_error
    res = {
        "concurrency": concurrency,
        "throughput": stats.count / elapsed,
        "errors": stats.errors,
        # resilience visibility per level: where the server starts shedding
        # and how hard the client retry layer is working to cover it
        "rejected": stats.rejected,
        "rejected_per_sec": stats.rejected / elapsed,
        "retries": _retries_recorded(model_name) - retries_before,
        "first_error": stats.first_error,
    }
    if len(classes) > 1:
        res["classes"] = [
            dict(priority=cls[0], tenant=cls[1] or "",
                 workers=sum(1 for w in range(concurrency)
                             if w % len(classes) == i),
                 throughput=s.count / elapsed,
                 rejected=s.rejected,
                 rejected_per_sec=s.rejected / elapsed,
                 **_latency_stats(s.latency, extra_percentile))
            for i, (cls, s) in enumerate(zip(classes, class_stats))]
    if cluster_mode:
        dist_after, hedges_after, wins_after = _cluster_recorded()
        res["endpoints"] = {
            e: dist_after.get(e, 0) - dist_before.get(e, 0)
            for e in sorted(set(dist_before) | set(dist_after))}
        res["hedges"] = hedges_after - hedges_before
        res["hedge_wins"] = wins_after - wins_before
    res.update(_latency_stats(stats.latency, extra_percentile))
    return res


def _latency_stats(
    latencies: Union[LatencyHistogram, list], extra_percentile=None
) -> dict:
    """avg/p50/p90/p95/p99 (+ optional extra percentile) in usec, NaN when
    no samples — shared by the closed- and open-loop drivers.  Accepts a
    telemetry ``LatencyHistogram`` (closed loop records straight into one)
    or a list of seconds (open loop, which must window-filter samples by
    scheduled time before aggregating)."""
    if not isinstance(latencies, LatencyHistogram):
        h = LatencyHistogram()
        for v in latencies:
            h.observe(float(v))
        latencies = h
    out = {"avg_us": latencies.mean() * 1e6 if latencies.count
           else float("nan")}
    pcts = [50, 90, 95, 99]
    if extra_percentile is not None and extra_percentile not in pcts:
        pcts.append(extra_percentile)
    for p in pcts:
        out[f"p{p}_us"] = (latencies.percentile(p) * 1e6
                           if latencies.count else float("nan"))
    return out


def _parse_rate_range(spec: str) -> List[float]:
    parts = [float(p) for p in spec.split(":")]
    start = parts[0]
    end = parts[1] if len(parts) > 1 else start
    step = parts[2] if len(parts) > 2 else 1.0
    if start <= 0 or step <= 0:
        raise ValueError(
            f"invalid --request-rate-range '{spec}': rates and step must "
            "be positive")
    out, r = [], start
    while r <= end + 1e-9:
        out.append(r)
        r += step
    return out


def run_rate_level(protocol, url, model_name, model_version, rate, arrays,
                   outputs, shm_mode, output_byte_size, measure_s,
                   warmup_s=1.0, distribution="constant", max_threads=64,
                   extra_percentile=None, streaming=False, retry_policy=None,
                   balancing="least_outstanding", hedge_ms=0.0,
                   qos_classes=None):
    """OPEN-loop load at ``rate`` requests/s (reference perf_analyzer
    --request-rate-range): send times are scheduled up front (constant or
    Poisson inter-arrivals) and latency is measured from the SCHEDULED send
    time, so server queue buildup counts against latency instead of
    throttling the generator — the closed-loop sweep's coordinated-omission
    flattering cannot happen here.  When the server can't keep pace the
    report says so: ``send_lag_*`` (how far actual sends fell behind
    schedule) and ``unsent`` (slots still owed when the window closed)."""
    protocol_mod, make_client, shared_client = _make_client_factory(
        protocol, url, max_threads, balancing, hedge_ms)
    cluster_mode = isinstance(url, (list, tuple)) and len(url) > 1

    # absolute schedule for warmup + window (+1s grace so the last in-window
    # slot exists); fixed seed => the Poisson schedule is reproducible
    horizon = warmup_s + measure_s + 1.0
    n_slots = int(rate * horizon) + 1
    srng = np.random.default_rng(1234)
    if distribution == "poisson":
        gaps = srng.exponential(1.0 / rate, n_slots)
    else:
        gaps = np.full(n_slots, 1.0 / rate)
    sched = np.cumsum(gaps)

    if rate <= 0:
        raise ValueError(f"request rate must be positive, got {rate}")
    lock = threading.Lock()
    stop = threading.Event()
    next_slot = [0]
    sent = []     # (scheduled_rel, send_lag_s)
    done = []     # (scheduled_rel, latency_from_scheduled_s, err or None,
    #               rejected: bool)
    setup_errors = []  # outside the window classification: always reported
    t0_box = [None]
    ready = [0]
    go = threading.Event()

    classes = list(qos_classes) if qos_classes else None

    def worker(worker_id):
        ci = worker_id % len(classes) if classes else 0
        try:
            session = _InferSession(protocol_mod, make_client, model_name,
                                    model_version, arrays, outputs, shm_mode,
                                    output_byte_size, worker_id, streaming,
                                    retry_policy,
                                    owns_client=shared_client is None,
                                    qos_class=(classes[ci]
                                               if classes else None))
        except Exception as e:  # noqa: BLE001 — setup must be visible
            with lock:
                ready[0] += 1
                setup_errors.append(
                    f"worker setup: {type(e).__name__}: {e}")
            return
        # the schedule's t0 is armed only after the sender pool is
        # connected: otherwise pool setup (N clients dialing at once) eats
        # the front of the schedule and a low-rate window reports itself
        # entirely unsent
        with lock:
            ready[0] += 1
        go.wait(timeout=120)
        try:
            while not stop.is_set():
                with lock:
                    k = next_slot[0]
                    if k >= n_slots:
                        return
                    next_slot[0] += 1
                target = t0_box[0] + sched[k]
                # sleep in slices so stop() interrupts a long idle gap
                while True:
                    now = time.perf_counter()
                    if now >= target or stop.is_set():
                        break
                    time.sleep(min(target - now, 0.05))
                if stop.is_set():
                    return  # claimed slot never sent -> counted in `unsent`
                lag = time.perf_counter() - target
                err = None
                rejected = False
                try:
                    session.infer()
                except Exception as e:  # noqa: BLE001 — recorded per slot
                    err = f"{type(e).__name__}: {e}"
                    rejected = _is_rejected(e)
                lat = time.perf_counter() - target
                with lock:
                    sent.append((sched[k], lag))
                    done.append((sched[k], lat, err, rejected, ci))
        finally:
            session.close()

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(max_threads)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 30.0
    while ready[0] < max_threads and time.monotonic() < deadline:
        time.sleep(0.005)
    t0_box[0] = time.perf_counter()
    go.set()
    # classify by SCHEDULED time: the window owns every slot scheduled
    # inside it, including ones the server never got to (that's the point)
    time.sleep(warmup_s)
    # retry delta over the measure window only (same scoping as the
    # closed loop; slots already in flight at the boundary blur it by at
    # most one request's retries)
    retries_before = _retries_recorded(model_name)
    if cluster_mode:
        dist_before, hedges_before, wins_before = _cluster_recorded()
    time.sleep(measure_s)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    if shared_client is not None:
        shared_client.close()
    win_lo, win_hi = warmup_s, warmup_s + measure_s
    owed = int(np.sum((sched >= win_lo) & (sched < win_hi)))
    in_win = [row for row in done if win_lo <= row[0] < win_hi]
    ok = [lat for _s, lat, err, _rej, _ci in in_win if err is None]
    errs = [err for _s, _lat, err, _rej, _ci in in_win if err is not None]
    n_rejected = sum(1 for _s, _lat, _err, rej, _ci in in_win if rej)
    lags = np.asarray([lag for s, lag in sent if win_lo <= s < win_hi])
    res = {
        "request_rate": rate,
        "distribution": distribution,
        "throughput": len(ok) / measure_s,
        "owed": owed,
        "unsent": max(owed - len(in_win), 0),
        # setup failures happen before any slot is scheduled, so they are
        # reported unconditionally — not filtered by the window
        "errors": len(errs) + len(setup_errors),
        "rejected": n_rejected,
        "rejected_per_sec": n_rejected / measure_s,
        "retries": _retries_recorded(model_name) - retries_before,
        "first_error": (setup_errors[0] if setup_errors
                        else errs[0] if errs else None),
        "send_lag_p50_ms": (float(np.percentile(lags, 50) * 1e3)
                            if lags.size else float("nan")),
        "send_lag_p99_ms": (float(np.percentile(lags, 99) * 1e3)
                            if lags.size else float("nan")),
    }
    if cluster_mode:
        dist_after, hedges_after, wins_after = _cluster_recorded()
        res["endpoints"] = {
            e: dist_after.get(e, 0) - dist_before.get(e, 0)
            for e in sorted(set(dist_before) | set(dist_after))}
        res["hedges"] = hedges_after - hedges_before
        res["hedge_wins"] = wins_after - wins_before
    if classes and len(classes) > 1:
        # per-class breakdown, same shape as the closed loop's (workers
        # are pinned to classes, so slot ownership follows the worker)
        res["classes"] = []
        for i, cls in enumerate(classes):
            c_ok = [lat for _s, lat, err, _rej, ci in in_win
                    if ci == i and err is None]
            c_rej = sum(1 for _s, _lat, _err, rej, ci in in_win
                        if ci == i and rej)
            res["classes"].append(dict(
                priority=cls[0], tenant=cls[1] or "",
                workers=sum(1 for w in range(max_threads)
                            if w % len(classes) == i),
                throughput=len(c_ok) / measure_s,
                rejected=c_rej,
                rejected_per_sec=c_rej / measure_s,
                **_latency_stats(c_ok, extra_percentile)))
    res.update(_latency_stats(ok, extra_percentile))
    return res


def _json_sanitize(v):
    """NaN/inf -> None recursively (per-class breakdowns nest dicts in the
    results rows; --export-metrics must stay strict JSON)."""
    if isinstance(v, float) and not np.isfinite(v):
        return None
    if isinstance(v, dict):
        return {k: _json_sanitize(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_json_sanitize(x) for x in v]
    return v


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="perf_analyzer",
        description="Concurrency-sweep load generator (perf_analyzer CLI contract)")
    parser.add_argument("-m", "--model-name", required=True)
    parser.add_argument("-x", "--model-version", default="")
    parser.add_argument("-u", "--url", action="append", default=None,
                        help="server endpoint; repeat (or comma-separate) "
                             "for a fleet — 2+ endpoints drive the "
                             "ClusterClient and report per-endpoint "
                             "request distribution and hedge counts")
    parser.add_argument("-i", "--protocol", default="http",
                        type=str.lower, choices=["http", "grpc"])
    parser.add_argument("-b", "--batch-size", type=int, default=1)
    parser.add_argument("--concurrency-range", default=None,
                        help="start:end:step closed-loop concurrency sweep")
    parser.add_argument("--request-rate-range", default=None,
                        help="start:end:step OPEN-loop request rates "
                             "(req/s); latency measured from the scheduled "
                             "send time (coordinated-omission-free)")
    parser.add_argument("--request-distribution", default="constant",
                        type=str.lower, choices=["constant", "poisson"],
                        help="inter-arrival schedule for --request-rate-range")
    parser.add_argument("--max-threads", type=int, default=64,
                        help="sender pool bound for the open-loop mode")
    parser.add_argument("--measurement-interval", type=int, default=5000,
                        help="measurement window per level (ms)")
    parser.add_argument("--shared-memory", default="none", choices=_SHM_MODES)
    parser.add_argument("--output-shared-memory-size", type=int, default=102400)
    parser.add_argument("--shape", action="append", default=[],
                        help="name:d1,d2,... override for dynamic dims")
    parser.add_argument("--string-length", type=int, default=16)
    parser.add_argument("--streaming", action="store_true",
                        help="drive infers over the bidi gRPC stream "
                             "(gRPC only; reference perf_analyzer flag)")
    parser.add_argument("--balancing", default="least_outstanding",
                        type=str.lower,
                        choices=["round_robin", "least_outstanding"],
                        help="balancing policy when multiple -u endpoints "
                             "are given (default least_outstanding)")
    parser.add_argument("--hedge-ms", type=float, default=0.0,
                        help="hedged requests: issue a backup request to a "
                             "second endpoint after this many ms (0 = off; "
                             "requires multiple -u endpoints)")
    parser.add_argument("--priority", action="append", type=int,
                        default=None, metavar="N",
                        help="v2 request priority (0 = highest); repeat "
                             "together with --tenant for mixed-tier "
                             "sweeps — workers round-robin over the "
                             "(priority, tenant) classes and the table "
                             "reports per-class throughput/p99/shed")
    parser.add_argument("--tenant", action="append", default=None,
                        metavar="NAME",
                        help="QoS tenant id stamped on every request "
                             "(triton-tenant header/metadata); repeatable, "
                             "zipped with --priority into classes")
    parser.add_argument("--retries", type=int, default=0,
                        help="enable the client resilience layer with this "
                             "many max attempts per request (0 = off); the "
                             "table and --export-metrics report retry "
                             "counts and rejected-request rates per level")
    parser.add_argument("--percentile", type=int, default=None,
                        help="report this percentile as the headline latency")
    parser.add_argument("--export-metrics", default=None, metavar="PATH",
                        help="write the sweep results plus the client "
                             "telemetry snapshot (per-model/protocol/method "
                             "counters and latency quantiles) as JSON")
    parser.add_argument("--trace-file", default=None, metavar="PATH",
                        help="enable server-side tracing for the sweep "
                             "(trace_level=TIMESTAMPS into PATH, sampled at "
                             "--trace-rate) and report the per-stage "
                             "breakdown after; PATH must be a path the "
                             "SERVER can write")
    parser.add_argument("--trace-rate", type=int, default=100,
                        help="server sampling rate while --trace-file is on "
                             "(trace every Nth request; default 100)")
    parser.add_argument("-f", "--latency-report-file", default=None)
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    if args.streaming and args.protocol != "grpc":
        parser.error("--streaming requires -i grpc")
    if args.streaming and args.retries:
        # stream submits are fire-and-forget: completion arrives on the
        # stream callback, so per-request retry cannot apply — fail loudly
        # rather than print retry columns that were never measured
        parser.error("--retries is not supported with --streaming")
    if args.concurrency_range and args.request_rate_range:
        parser.error("--concurrency-range and --request-rate-range are "
                     "mutually exclusive (closed- vs open-loop)")
    # QoS classes: zip the repeated --priority/--tenant flags; a shorter
    # list repeats its last value so `--priority 0 --priority 3 --tenant x`
    # means (0, x) and (3, x)
    priorities = args.priority or []
    tenants = args.tenant or []
    if args.streaming and tenants:
        # stream metadata is fixed at start_stream; per-request tenant
        # stamping is a unary-path contract
        parser.error("--tenant is not supported with --streaming")
    n_classes = max(len(priorities), len(tenants), 1)
    qos_classes = None
    if priorities or tenants:
        qos_classes = [
            (priorities[min(i, len(priorities) - 1)] if priorities else 0,
             tenants[min(i, len(tenants) - 1)] if tenants else None)
            for i in range(n_classes)]
    if args.concurrency_range is None and args.request_rate_range is None:
        args.concurrency_range = "1"

    urls: List[str] = []
    for u in (args.url or []):
        urls.extend(p.strip() for p in u.split(",") if p.strip())
    if not urls:
        urls = ["localhost:8001" if args.protocol == "grpc"
                else "localhost:8000"]
    if len(set(urls)) != len(urls):
        parser.error(f"duplicate -u endpoints: {urls}")
    cluster_mode = len(urls) > 1
    if cluster_mode and args.streaming:
        parser.error("--streaming drives one bidi stream per worker and "
                     "is not supported with multiple -u endpoints")
    if cluster_mode and args.shared_memory != "none":
        # a shm region registered on one replica is dangling on the others
        parser.error("--shared-memory requires a single -u endpoint")
    if args.hedge_ms < 0:
        parser.error("--hedge-ms must be >= 0")
    if args.hedge_ms and not cluster_mode:
        parser.error("--hedge-ms needs at least two -u endpoints to hedge "
                     "across")
    if cluster_mode and args.trace_file:
        # the trace control plane reaches ONE server; a breakdown
        # covering ~1/N of a fleet sweep with no warning would be a lie
        parser.error("--trace-file requires a single -u endpoint (server "
                     "tracing is per-server; trace each replica directly)")
    if args.protocol == "grpc":
        import triton_client_tpu.grpc as pm
    else:
        import triton_client_tpu.http as pm

    # metadata resolution + trace control plane: first endpoint that
    # answers — a dead first -u must not kill a sweep the cluster client
    # would have routed around
    resolved = None
    for candidate in urls:
        meta_client = pm.InferenceServerClient(candidate)
        try:
            resolved = _resolve_model(
                meta_client, args.protocol, args.model_name,
                args.model_version)
            url = candidate
            break
        except Exception as e:  # noqa: BLE001 — next replica may answer
            if candidate == urls[-1]:
                raise
            print(f"warning: {candidate} unreachable for metadata "
                  f"({type(e).__name__}); trying the next endpoint",
                  file=sys.stderr)
        finally:
            meta_client.close()
    inputs, outputs, max_batch = resolved
    if args.batch_size > 1 and max_batch == 0:
        print(f"error: model {args.model_name} does not support batching",
              file=sys.stderr)
        return 1

    rng = np.random.default_rng(0)
    try:
        shapes = _parse_shapes(args.shape)
    except ValueError as e:
        parser.error(str(e))
    arrays = _make_data(inputs, shapes, args.batch_size,
                        max_batch, rng, args.string_length)

    measure_s = args.measurement_interval / 1000.0
    open_loop = args.request_rate_range is not None
    results = []
    print(f"*** Measurement Settings ***\n"
          f"  Batch size: {args.batch_size}\n"
          f"  Measurement window: {args.measurement_interval} msec\n"
          f"  Shared memory: {args.shared_memory}\n"
          f"  Load mode: "
          + (f"open-loop ({args.request_distribution} arrivals)"
             if open_loop else "closed-loop (concurrency)") + "\n"
          f"  Protocol: {args.protocol} @ {', '.join(urls)}"
          + (f" [{args.balancing}"
             + (f", hedge {args.hedge_ms:g}ms" if args.hedge_ms else "")
             + "]" if cluster_mode else "") + "\n")

    retry_policy = None
    if args.retries > 0:
        from ._resilience import RetryPolicy

        retry_policy = RetryPolicy(max_attempts=max(1, args.retries),
                                   retry_infer=True)
    elif args.hedge_ms > 0:
        # hedging re-executes the request, so it is gated on idempotency
        # exactly like retry_infer — a 1-attempt policy arms the gate
        # without enabling retries
        from ._resilience import RetryPolicy

        retry_policy = RetryPolicy(max_attempts=1, retry_infer=True)

    def report(res, lead):
        results.append(res)
        headline = (res[f"p{args.percentile}_us"]
                    if args.percentile is not None else res["avg_us"])
        tail = ""
        if res.get("unsent"):
            tail += f", {res['unsent']} unsent"
        if res.get("retries"):
            tail += f", {res['retries']} retries"
        if res.get("rejected"):
            tail += f", rejected {res['rejected_per_sec']:.1f}/s"
        if res.get("hedges"):
            tail += (f", {res['hedges']} hedges"
                     f" ({res.get('hedge_wins', 0)} won)")
        if res["errors"]:
            tail += f" ({res['errors']} errors)"
        print(f"{lead}{res['throughput']:.2f} infer/sec, "
              f"latency {headline:.0f} usec" + tail)
        if res["errors"] and res.get("first_error"):
            print(f"  first error: {res['first_error']}")
        if "endpoints" in res:
            total = sum(res["endpoints"].values()) or 1
            dist = ", ".join(
                f"{e}: {n} ({100.0 * n / total:.0f}%)"
                for e, n in res["endpoints"].items())
            print(f"  endpoint distribution: {dist}")
        if args.verbose:
            line = (f"  p50: {res['p50_us']:.0f} us, "
                    f"p90: {res['p90_us']:.0f} us, "
                    f"p95: {res['p95_us']:.0f} us, "
                    f"p99: {res['p99_us']:.0f} us")
            if "send_lag_p99_ms" in res:
                line += f", send lag p99 {res['send_lag_p99_ms']:.1f} ms"
            print(line)
        for cls in res.get("classes", []):
            label = f"p={cls['priority']}"
            if cls["tenant"]:
                label += f" tenant={cls['tenant']}"
            p99 = cls["p99_us"]
            p99_s = f"{p99:.0f}" if np.isfinite(p99) else "-"
            print(f"    tier {label}: {cls['throughput']:.2f} infer/sec, "
                  f"p99 {p99_s} usec, shed "
                  f"{cls['rejected_per_sec']:.1f}/s "
                  f"({cls['rejected']} total)")

    if args.trace_file:
        # server-side tracing for the whole sweep: the stage breakdown
        # (queue vs batch assembly vs compute vs serialize) is reported
        # next to the client-observed percentiles afterwards.  Enabled
        # HERE — after every argument-validation exit above — so no
        # early `return`/parser.error can leave server-wide tracing on,
        # and the finally below always reaches the matching OFF.
        trace_ctl = pm.InferenceServerClient(url)
        trace_ctl.update_trace_settings(settings={
            "trace_file": [args.trace_file],
            "trace_level": ["TIMESTAMPS"],
            "trace_rate": [str(max(1, args.trace_rate))],
        })
        trace_ctl.close()

    try:
        if open_loop:
            try:
                rates = _parse_rate_range(args.request_rate_range)
            except ValueError as e:
                parser.error(str(e))
            for rate in rates:
                res = run_rate_level(
                    args.protocol, urls if cluster_mode else url,
                    args.model_name, args.model_version,
                    rate, arrays, outputs, args.shared_memory,
                    args.output_shared_memory_size, measure_s,
                    distribution=args.request_distribution,
                    max_threads=args.max_threads,
                    extra_percentile=args.percentile, streaming=args.streaming,
                    retry_policy=retry_policy, balancing=args.balancing,
                    hedge_ms=args.hedge_ms, qos_classes=qos_classes)
                report(res, f"Request rate: {rate:g}/s, completed "
                            "(latency from scheduled send): ")
        else:
            for level in _parse_concurrency_range(args.concurrency_range):
                res = run_level(
                    args.protocol, urls if cluster_mode else url,
                    args.model_name, args.model_version,
                    level, arrays, outputs, args.shared_memory,
                    args.output_shared_memory_size, measure_s,
                    extra_percentile=args.percentile, streaming=args.streaming,
                    retry_policy=retry_policy, balancing=args.balancing,
                    hedge_ms=args.hedge_ms, qos_classes=qos_classes)
                report(res, f"Concurrency: {level}, throughput: ")
    finally:
        if args.trace_file:
            # the sweep turned on SERVER-WIDE tracing — a failed or
            # interrupted sweep must not leave the server sampling every
            # later request into the file forever
            try:
                off_client = pm.InferenceServerClient(url)
                off_client.update_trace_settings(
                    settings={"trace_level": ["OFF"]})
                off_client.close()
            except Exception as e:  # noqa: BLE001 — best effort on teardown
                print(f"warning: could not disable server tracing: {e}",
                      file=sys.stderr)

    trace_summary = None
    if args.trace_file:
        from .tools.trace_summary import (format_text, load_trace_file,
                                          summarize)

        try:
            trace_summary = summarize(load_trace_file(args.trace_file))
            print("\n*** Server trace breakdown "
                  f"({args.trace_file}, every {max(1, args.trace_rate)}th "
                  "request) ***")
            print(format_text(trace_summary), end="")
        except (OSError, ValueError) as e:
            # a trace_file the server could not write (or an unreadable one
            # here) must not fail the sweep that already printed its numbers
            print(f"warning: could not summarize {args.trace_file}: {e}",
                  file=sys.stderr)

    if args.export_metrics:
        snapshot = {
            "model": args.model_name,
            "protocol": args.protocol,
            "urls": urls,
            "shared_memory": args.shared_memory,
            "load_mode": "open_loop" if open_loop else "closed_loop",
            "results": [_json_sanitize(r) for r in results],
            "client_telemetry": telemetry().snapshot(),
        }
        if trace_summary is not None:
            snapshot["server_trace_summary"] = trace_summary
        with open(args.export_metrics, "w") as f:
            json.dump(snapshot, f, indent=2)

    if args.latency_report_file:
        with open(args.latency_report_file, "w") as f:
            if open_loop:
                f.write("Request Rate,Inferences/Second,Avg latency,"
                        "p50 latency,p90 latency,p95 latency,p99 latency,"
                        "Unsent\n")
                for r in results:
                    f.write(f"{r['request_rate']:g},{r['throughput']:.2f},"
                            f"{r['avg_us']:.0f},{r['p50_us']:.0f},"
                            f"{r['p90_us']:.0f},{r['p95_us']:.0f},"
                            f"{r['p99_us']:.0f},{r['unsent']}\n")
            else:
                f.write("Concurrency,Inferences/Second,Avg latency,"
                        "p50 latency,p90 latency,p95 latency,p99 latency\n")
                for r in results:
                    f.write(f"{r['concurrency']},{r['throughput']:.2f},"
                            f"{r['avg_us']:.0f},{r['p50_us']:.0f},{r['p90_us']:.0f},"
                            f"{r['p95_us']:.0f},{r['p99_us']:.0f}\n")
    failed = all(r["throughput"] == 0 for r in results)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
