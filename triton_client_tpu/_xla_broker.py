"""Process-local broker for XLA shared-memory regions.

cudaIPC lets two processes map the *same* device allocation
(cudaIpcGetMemHandle / cudaIpcOpenMemHandle — reference
cuda_shared_memory/__init__.py:130-170).  PjRt has no cross-process buffer
import, and jax.Arrays are immutable — so the TPU-native region is a **slot**:
a mutable cell holding the current immutable device buffer.  "Writing" a
region rebinds the slot; readers always see the latest buffer.

* Co-located client+server (same process — the recommended TPU serving
  topology and our hermetic-test path): both sides share the slot object via
  this broker → tensor data stays in TPU HBM end to end, zero copies.
* Cross-process: the slot is backed by a POSIX host-shm staging region; the
  writer stages once and the reader does a single host↔device DMA (the
  TPU-realistic analog of cudaIpcOpenMemHandle; SURVEY.md §7 hard parts (a)).

This module is deliberately tiny and dependency-free: both
``utils.xla_shared_memory`` (client half) and ``server.shm`` (server half)
import it without pulling in each other.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class RegionSlot:
    """Mutable cell for an immutable device buffer + its type metadata."""

    def __init__(self, uuid: str, byte_size: int, device_id: int):
        self.uuid = uuid
        self.byte_size = byte_size
        self.device_id = device_id
        self.lock = threading.Lock()
        # Current contents: a jax.Array (any dtype/shape, nbytes<=byte_size)
        # plus the Triton dtype/shape it was last written as.
        self.array = None
        self.datatype: Optional[str] = None
        self.shape: Optional[tuple] = None

    def bind(self, array, datatype: Optional[str], shape: Optional[tuple]) -> None:
        with self.lock:
            self.array = array
            self.datatype = datatype
            self.shape = tuple(shape) if shape is not None else None

    def get(self):
        with self.lock:
            return self.array, self.datatype, self.shape


class XlaBroker:
    def __init__(self):
        self._slots: Dict[str, RegionSlot] = {}
        self._lock = threading.Lock()
        # Set by an in-process server at startup so clients default to the
        # zero-copy slot path; cross-process clients fall back to staging.
        self.server_present = False

    def create(self, uuid: str, byte_size: int, device_id: int) -> RegionSlot:
        with self._lock:
            slot = RegionSlot(uuid, byte_size, device_id)
            self._slots[uuid] = slot
            return slot

    def lookup(self, uuid: str) -> Optional[RegionSlot]:
        with self._lock:
            return self._slots.get(uuid)

    def drop(self, uuid: str) -> None:
        with self._lock:
            self._slots.pop(uuid, None)


_broker = XlaBroker()


def broker() -> XlaBroker:
    return _broker
