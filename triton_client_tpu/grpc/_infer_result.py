"""gRPC-protocol ``InferResult``.

Parity target: reference ``tritonclient/grpc/_infer_result.py`` (159 LoC):
reads ``raw_output_contents[index]`` positionally (:63-97); ``as_numpy``
deserializes BYTES/BF16."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..protocol import inference_pb2 as pb
from ..utils import (
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    triton_to_np_dtype,
)


class InferResult:
    def __init__(self, result: pb.ModelInferResponse):
        self._result = result

    def as_numpy(self, name: str) -> Optional[np.ndarray]:
        for index, output in enumerate(self._result.outputs):
            if output.name != name:
                continue
            shape = [int(s) for s in output.shape]
            if index >= len(self._result.raw_output_contents):
                return None
            buf = self._result.raw_output_contents[index]
            if not buf and "shared_memory_region" in output.parameters:
                return None  # data lives in the region
            if output.datatype == "BYTES":
                return deserialize_bytes_tensor(buf).reshape(shape)
            if output.datatype == "BF16":
                return deserialize_bf16_tensor(buf).reshape(shape)
            dt = triton_to_np_dtype(output.datatype)
            if dt is None:
                return None
            return np.frombuffer(buf, dtype=dt).reshape(shape)
        return None

    def get_output(self, name: str, as_json: bool = False):
        """The output pb (or its JSON dict) by name (reference :99-133)."""
        for output in self._result.outputs:
            if output.name == name:
                if as_json:
                    from google.protobuf import json_format

                    return json_format.MessageToDict(output, preserving_proto_field_name=True)
                return output
        return None

    def get_response(self, as_json: bool = False):
        if as_json:
            from google.protobuf import json_format

            return json_format.MessageToDict(self._result, preserving_proto_field_name=True)
        return self._result
