"""gRPC-protocol ``InferInput``.

Parity target: reference ``tritonclient/grpc/_infer_input.py`` (219 LoC) —
wraps ``ModelInferRequest.InferInputTensor``; raw bytes travel positionally
in ``raw_input_contents`` (:160-174)."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..protocol import inference_pb2 as pb
from ..utils import (
    np_to_triton_dtype,
    raise_error,
    serialize_bf16_tensor,
    serialize_byte_tensor_raw,
)


class InferInput:
    def __init__(self, name: str, shape: List[int], datatype: str):
        self._input = pb.ModelInferRequest.InferInputTensor(name=name, datatype=datatype)
        self._input.shape.extend(int(s) for s in shape)
        self._raw_content: Optional[bytes] = None
        # bumped by set_shape: lets a template detect a shape change
        # with one int compare on the stamp hot path
        self._shape_epoch = 0

    def name(self) -> str:
        return self._input.name

    def datatype(self) -> str:
        return self._input.datatype

    def shape(self) -> List[int]:
        return list(self._input.shape)

    def set_shape(self, shape: List[int]) -> "InferInput":
        self._input.ClearField("shape")
        self._input.shape.extend(int(s) for s in shape)
        self._shape_epoch += 1
        return self

    def set_data_from_numpy(self, input_tensor: np.ndarray) -> "InferInput":
        """Attach tensor data (always the raw representation on gRPC,
        reference :94-158)."""
        if not isinstance(input_tensor, np.ndarray):
            raise_error("input_tensor must be a numpy array")
        dtype = np_to_triton_dtype(input_tensor.dtype)
        expected = self._input.datatype
        if expected != dtype and not (expected == "BF16" and dtype == "FP32"):
            raise_error(
                f"got unexpected datatype {dtype} from numpy array, expected {expected}"
            )
        if list(input_tensor.shape) != list(self._input.shape):
            raise_error(
                f"got unexpected numpy array shape [{str(input_tensor.shape)[1:-1]}], "
                f"expected [{str(list(self._input.shape))[1:-1]}]"
            )
        self._input.parameters.pop("shared_memory_region", None)
        self._input.parameters.pop("shared_memory_byte_size", None)
        self._input.parameters.pop("shared_memory_offset", None)
        # protobuf bytes fields only accept ``bytes`` (upb rejects
        # memoryview/bytearray), so each branch is the ONE required
        # materialization — no intermediate chunk objects or re-copies.
        if expected == "BYTES":
            # tpu-lint: disable=WIRE-COPY protobuf requires bytes; single materialization of the prealloc'd codec buffer
            self._raw_content = bytes(serialize_byte_tensor_raw(input_tensor))
        elif expected == "BF16":
            # tpu-lint: disable=WIRE-COPY protobuf requires bytes; the serializer returns a zero-copy view
            self._raw_content = serialize_bf16_tensor(input_tensor).tobytes()
        else:
            # tpu-lint: disable=WIRE-COPY protobuf requires bytes; numpy -> wire in one copy
            self._raw_content = input_tensor.tobytes()
        return self

    def set_shared_memory(self, region_name: str, byte_size: int, offset: int = 0):
        """Reference data in a registered shm region (:176-207)."""
        self._input.ClearField("contents")
        self._raw_content = None
        self._input.parameters["shared_memory_region"].string_param = region_name
        self._input.parameters["shared_memory_byte_size"].int64_param = byte_size
        if offset != 0:
            self._input.parameters["shared_memory_offset"].int64_param = offset
        return self

    def _get_tensor_pb(self) -> pb.ModelInferRequest.InferInputTensor:
        return self._input

    def _get_raw_data(self) -> Optional[bytes]:
        return self._raw_content
