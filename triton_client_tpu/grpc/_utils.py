"""gRPC client helpers (reference ``tritonclient/grpc/_utils.py``, 159 LoC)."""

from __future__ import annotations

import grpc

from ..protocol import inference_pb2 as pb
from ..utils import raise_error

_RESERVED_PARAMS = (
    "sequence_id",
    "sequence_start",
    "sequence_end",
    "priority",
    "binary_data_output",
)


def get_error_grpc(rpc_error: grpc.RpcError):
    """Map an RpcError to InferenceServerException (reference :33-45).

    Server pushback in ``retry-after-ms`` trailing metadata (sent with shed
    load / drain refusals) lands on ``retry_after_s`` so the resilience
    layer's backoff can honor it."""
    from ..utils import InferenceServerException

    exc = InferenceServerException(
        msg=rpc_error.details(),
        status=str(rpc_error.code()),
        debug_details=rpc_error.debug_error_string()
        if hasattr(rpc_error, "debug_error_string")
        else None,
    )
    try:
        for key, value in (rpc_error.trailing_metadata() or ()):
            if key == "retry-after-ms":
                exc.retry_after_s = float(value) / 1e3
    except Exception:
        pass  # no trailing metadata on this error shape
    return exc


def raise_error_grpc(rpc_error: grpc.RpcError):
    raise get_error_grpc(rpc_error) from None


#: In-band stream-error "[NNN] " prefix -> the unary status spelling, so
#: stream failures classify identically (retry gating, perf_analyzer's
#: rejected counting, DEADLINE matching).
_STREAM_STATUS = {
    "400": "StatusCode.INVALID_ARGUMENT",
    "404": "StatusCode.NOT_FOUND",
    "429": "StatusCode.RESOURCE_EXHAUSTED",
    "500": "StatusCode.INTERNAL",
    "503": "StatusCode.UNAVAILABLE",
    "504": "StatusCode.DEADLINE_EXCEEDED",
}


def stream_error_to_exception(message: str):
    """Typed exception for one in-band ``ModelStreamInferResponse``
    error.  The server prefixes InferError messages with their HTTP
    status (``"[429] ..."``) because the bidi wire carries no per-message
    grpc code; unprefixed messages (defensive/model-raised strings) stay
    status-less."""
    import re

    from ..utils import InferenceServerException

    m = re.match(r"\[(\d{3})\] ", message)
    status = _STREAM_STATUS.get(m.group(1)) if m else None
    return InferenceServerException(msg=message, status=status)


def get_inference_request(
    model_name,
    inputs,
    model_version,
    request_id,
    outputs,
    sequence_id,
    sequence_start,
    sequence_end,
    priority,
    timeout,
    parameters,
) -> pb.ModelInferRequest:
    """Build a ModelInferRequest pb (reference :80-143): tensors + positional
    raw_input_contents; sequence_id may be int64 **or string** (string ids go
    in ``sequence_id`` as string_param, reference :105-111)."""
    request = pb.ModelInferRequest(model_name=model_name, model_version=model_version)
    if request_id:
        request.id = request_id
    if sequence_id:
        if isinstance(sequence_id, str):
            request.parameters["sequence_id"].string_param = sequence_id
        else:
            request.parameters["sequence_id"].int64_param = sequence_id
        request.parameters["sequence_start"].bool_param = sequence_start
        request.parameters["sequence_end"].bool_param = sequence_end
    if priority:
        request.parameters["priority"].uint64_param = priority
    if timeout is not None:
        request.parameters["timeout"].int64_param = timeout

    for input_tensor in inputs:
        request.inputs.append(input_tensor._get_tensor_pb())
        raw = input_tensor._get_raw_data()
        if raw is not None:
            request.raw_input_contents.append(raw)
    if outputs is not None:
        for output_tensor in outputs:
            request.outputs.append(output_tensor._get_tensor_pb())

    if parameters:
        for key, value in parameters.items():
            if key in _RESERVED_PARAMS:
                raise_error(
                    f"Parameter {key!r} is a reserved parameter and cannot be specified."
                )
            if isinstance(value, bool):
                request.parameters[key].bool_param = value
            elif isinstance(value, int):
                request.parameters[key].int64_param = value
            elif isinstance(value, float):
                request.parameters[key].double_param = value
            elif isinstance(value, str):
                request.parameters[key].string_param = value
            else:
                raise_error(f"Unsupported parameter type for {key!r}")
    return request


# compression name -> grpc enum (reference :146-158)
def get_grpc_compression(algorithm):
    if algorithm is None or algorithm == "none":
        return grpc.Compression.NoCompression
    if algorithm == "deflate":
        return grpc.Compression.Deflate
    if algorithm == "gzip":
        return grpc.Compression.Gzip
    raise_error(f"unsupported compression algorithm {algorithm!r}")
