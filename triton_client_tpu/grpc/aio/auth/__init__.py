"""Auth plugins for the aio gRPC client (reference ``tritonclient/grpc/aio/auth``)."""

from ...._auth import BasicAuth

__all__ = ["BasicAuth"]
