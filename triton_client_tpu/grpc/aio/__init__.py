"""asyncio gRPC ``InferenceServerClient``.

Parity target: reference ``tritonclient/grpc/aio/__init__.py`` (810 LoC) —
the sync client's full method surface as ``async def`` over a
``grpc.aio`` channel, plus ``stream_infer(inputs_iterator)`` converting an
async iterator of request-kwarg dicts into the bidi stream and returning a
cancellable response iterator yielding ``(InferResult, error)`` tuples
(reference :688-810).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

import grpc

from ..._client import InferenceServerClientBase
from ..._request import Request
from ..._resilience import (RetryPolicy, call_with_retry_async,
                            deadline_exceeded_error, min_timeout,
                            remaining_us)
from ..._telemetry import telemetry, traceparent_from_metadata
from ..._uvloop import maybe_install_uvloop
from ...protocol import inference_pb2 as pb
from ...protocol.service import GRPCInferenceServiceStub
from ...utils import raise_error
from .._client import (KeepAliveOptions, _channel_options, _maybe_json,
                       _with_trace_metadata)
from .._infer_result import InferResult
from .._template import RequestTemplate
from .._utils import (
    get_error_grpc,
    get_grpc_compression,
    get_inference_request,
    raise_error_grpc,
)

__all__ = ["InferenceServerClient", "KeepAliveOptions", "PreparedRequest"]

# optional uvloop (TRITON_TPU_UVLOOP=1; stdlib loop otherwise) — must run
# before any channel/loop is created by this module's callers
maybe_install_uvloop()


class PreparedRequest:
    """Async sibling of the sync gRPC fast-path handle.  Every stamp
    copies the skeleton (``copy=True``): grpc.aio may serialize the
    message after control returns to the event loop, so concurrent
    in-flight requests must never share one mutable message."""

    def __init__(self, client, template: RequestTemplate):
        self._client = client
        self.template = template

    async def infer(self, request_id="", headers=None, tenant=None,
                    client_timeout=None,
                    retry_policy: Optional[RetryPolicy] = None,
                    deadline_s: Optional[float] = None) -> InferResult:
        client = self._client
        policy = retry_policy if retry_policy is not None \
            else client._retry_policy
        if policy is None and deadline_s is None:
            return await client._infer_prepared(
                self, request_id, headers, tenant, client_timeout)
        return await call_with_retry_async(
            policy,
            lambda remaining, _attempt: client._infer_prepared(
                self, request_id, headers, tenant, client_timeout,
                _remaining_s=remaining),
            method="infer", deadline_s=deadline_s,
            retry_meta=(self.template.model_name, "grpc_aio", "infer",
                        request_id), journey=True)


class InferenceServerClient(InferenceServerClientBase):
    """v2 protocol over grpc.aio (reference aio client :92)."""

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        ssl: bool = False,
        root_certificates: Optional[str] = None,
        private_key: Optional[str] = None,
        certificate_chain: Optional[str] = None,
        creds: Optional[grpc.ChannelCredentials] = None,
        keepalive_options: Optional[KeepAliveOptions] = None,
        channel_args=None,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        super().__init__()
        # client-level resilience default (see the sync client): health/
        # metadata retry unconditionally, infer per its retry_infer opt-in
        self._retry_policy = retry_policy
        self._url = url
        self._verbose = verbose
        options = _channel_options(keepalive_options, channel_args)
        if creds is not None:
            self._channel = grpc.aio.secure_channel(url, creds, options=options)
        elif ssl:
            def _read(path):
                if path is None:
                    return None
                with open(path, "rb") as f:
                    return f.read()

            credentials = grpc.ssl_channel_credentials(
                root_certificates=_read(root_certificates),
                private_key=_read(private_key),
                certificate_chain=_read(certificate_chain),
            )
            self._channel = grpc.aio.secure_channel(url, credentials, options=options)
        else:
            self._channel = grpc.aio.insecure_channel(url, options=options)
        self._client_stub = GRPCInferenceServiceStub(self._channel)

    @property
    def url(self) -> str:
        """The ``host:port`` this client talks to — the endpoint label
        the cluster layer keys its routing counters by."""
        return self._url

    # -- lifecycle ---------------------------------------------------------
    async def close(self) -> None:
        await self._channel.close()

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    def _get_metadata(self, headers: Optional[dict]) -> tuple:
        request = Request(dict(headers) if headers else {})
        self._call_plugin(request)
        return tuple(request.headers.items())

    async def _with_retry(self, method_kind: str, fn):
        """Run an idempotent (health/metadata) call under the client-level
        retry policy, if one is configured.  ``fn(timeout)`` receives the
        per-attempt transport timeout."""
        if self._retry_policy is None:
            return await fn(None)

        async def _attempt(remaining, _att):
            return await fn(remaining)

        return await call_with_retry_async(
            self._retry_policy, _attempt, method=method_kind,
            retry_meta=("", "grpc_aio", method_kind, ""))

    # -- health / metadata -------------------------------------------------
    async def is_server_live(self, headers=None, client_timeout=None) -> bool:
        async def _call(remaining):
            try:
                response = await self._client_stub.ServerLive(
                    pb.ServerLiveRequest(),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                return response.live
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return await self._with_retry("health", _call)

    async def is_server_ready(self, headers=None, client_timeout=None) -> bool:
        async def _call(remaining):
            try:
                response = await self._client_stub.ServerReady(
                    pb.ServerReadyRequest(),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                return response.ready
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return await self._with_retry("health", _call)

    async def is_model_ready(
        self, model_name, model_version="", headers=None, client_timeout=None
    ) -> bool:
        async def _call(remaining):
            try:
                response = await self._client_stub.ModelReady(
                    pb.ModelReadyRequest(name=model_name,
                                         version=model_version),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                return response.ready
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return await self._with_retry("health", _call)

    async def get_server_metadata(self, headers=None, as_json=False, client_timeout=None):
        async def _call(remaining):
            try:
                response = await self._client_stub.ServerMetadata(
                    pb.ServerMetadataRequest(),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                return _maybe_json(response, as_json)
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return await self._with_retry("metadata", _call)

    async def get_model_metadata(
        self, model_name, model_version="", headers=None, as_json=False, client_timeout=None
    ):
        async def _call(remaining):
            try:
                response = await self._client_stub.ModelMetadata(
                    pb.ModelMetadataRequest(name=model_name,
                                            version=model_version),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                return _maybe_json(response, as_json)
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return await self._with_retry("metadata", _call)

    async def get_model_config(
        self, model_name, model_version="", headers=None, as_json=False, client_timeout=None
    ):
        async def _call(remaining):
            try:
                response = await self._client_stub.ModelConfig(
                    pb.ModelConfigRequest(name=model_name,
                                          version=model_version),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                return _maybe_json(response, as_json)
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return await self._with_retry("metadata", _call)

    # -- repository --------------------------------------------------------
    async def get_model_repository_index(self, headers=None, as_json=False, client_timeout=None):
        try:
            response = await self._client_stub.RepositoryIndex(
                pb.RepositoryIndexRequest(), metadata=self._get_metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    async def load_model(
        self, model_name, headers=None, config: Optional[str] = None,
        files: Optional[Dict[str, bytes]] = None, client_timeout=None,
    ):
        request = pb.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = config
        if files:
            for path, content in files.items():
                request.parameters[path].bytes_param = content
        try:
            await self._client_stub.RepositoryModelLoad(
                request, metadata=self._get_metadata(headers), timeout=client_timeout
            )
        except grpc.RpcError as e:
            raise_error_grpc(e)

    async def unload_model(
        self, model_name, headers=None, unload_dependents=False, client_timeout=None
    ):
        request = pb.RepositoryModelUnloadRequest(model_name=model_name)
        request.parameters["unload_dependents"].bool_param = unload_dependents
        try:
            await self._client_stub.RepositoryModelUnload(
                request, metadata=self._get_metadata(headers), timeout=client_timeout
            )
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # -- statistics / trace / logging --------------------------------------
    async def get_inference_statistics(
        self, model_name="", model_version="", headers=None, as_json=False, client_timeout=None
    ):
        try:
            response = await self._client_stub.ModelStatistics(
                pb.ModelStatisticsRequest(name=model_name, version=model_version),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    async def update_trace_settings(
        self, model_name=None, settings=None, headers=None, as_json=False, client_timeout=None
    ):
        request = pb.TraceSettingRequest(model_name=model_name or "")
        for key, value in (settings or {}).items():
            if value is not None:
                vals = value if isinstance(value, list) else [str(value)]
                request.settings[key].value.extend(vals)
            else:
                request.settings[key].SetInParent()
        try:
            response = await self._client_stub.TraceSetting(
                request, metadata=self._get_metadata(headers), timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    async def get_trace_settings(
        self, model_name=None, headers=None, as_json=False, client_timeout=None
    ):
        return await self.update_trace_settings(
            model_name, None, headers, as_json, client_timeout
        )

    async def update_log_settings(self, settings, headers=None, as_json=False, client_timeout=None):
        request = pb.LogSettingsRequest()
        for key, value in settings.items():
            if isinstance(value, bool):
                request.settings[key].bool_param = value
            elif isinstance(value, int):
                request.settings[key].uint32_param = value
            else:
                request.settings[key].string_param = str(value)
        try:
            response = await self._client_stub.LogSettings(
                request, metadata=self._get_metadata(headers), timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    async def get_log_settings(self, headers=None, as_json=False, client_timeout=None):
        return await self.update_log_settings({}, headers, as_json, client_timeout)

    async def get_flight_recorder(self, model_name=None, limit=0,
                                  headers=None, client_timeout=None) -> dict:
        """The server's flight-recorder debug snapshot (always-on recent
        ring + pinned tail-latency/failure outliers with span trees) —
        same JSON shape as HTTP's GET /v2/debug/flight_recorder."""
        import json

        from ...protocol import debug_pb2 as pb_debug

        try:
            response = await self._client_stub.FlightRecorder(
                pb_debug.FlightRecorderRequest(
                    model_name=model_name or "", limit=int(limit or 0)),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return json.loads(response.payload_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    async def get_device_stats(self, model_name=None, headers=None,
                               client_timeout=None) -> dict:
        """The server's device/scheduler observability snapshot (duty
        cycle / live MFU / compiles / ticks / transfers / HBM + SLO
        state) — same JSON shape as HTTP's GET /v2/debug/device_stats."""
        import json

        from ...protocol import debug_pb2 as pb_debug

        try:
            response = await self._client_stub.DeviceStats(
                pb_debug.DeviceStatsRequest(model_name=model_name or ""),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return json.loads(response.payload_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    async def get_costs(self, model_name=None, headers=None,
                        client_timeout=None) -> dict:
        """The server's per-tenant cost-attribution ledger (device-time,
        FLOPs, generated tokens, KV byte-seconds per model and tenant)
        — same JSON shape as HTTP's GET /v2/debug/costs."""
        import json

        from ...protocol import debug_pb2 as pb_debug

        try:
            response = await self._client_stub.Costs(
                pb_debug.CostsRequest(model_name=model_name or ""),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return json.loads(response.payload_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # -- shared memory -----------------------------------------------------
    async def get_system_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        try:
            response = await self._client_stub.SystemSharedMemoryStatus(
                pb.SystemSharedMemoryStatusRequest(name=region_name),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    async def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, client_timeout=None
    ):
        try:
            await self._client_stub.SystemSharedMemoryRegister(
                pb.SystemSharedMemoryRegisterRequest(
                    name=name, key=key, offset=offset, byte_size=byte_size
                ),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            telemetry().record_shm_register("grpc_aio", "system", byte_size)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    async def unregister_system_shared_memory(self, name="", headers=None, client_timeout=None):
        try:
            await self._client_stub.SystemSharedMemoryUnregister(
                pb.SystemSharedMemoryUnregisterRequest(name=name),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
        except grpc.RpcError as e:
            raise_error_grpc(e)

    async def get_cuda_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        try:
            response = await self._client_stub.CudaSharedMemoryStatus(
                pb.CudaSharedMemoryStatusRequest(name=region_name),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    async def register_cuda_shared_memory(
        self, name, raw_handle: bytes, device_id: int, byte_size: int,
        headers=None, client_timeout=None,
    ):
        try:
            await self._client_stub.CudaSharedMemoryRegister(
                pb.CudaSharedMemoryRegisterRequest(
                    name=name, raw_handle=raw_handle, device_id=device_id,
                    byte_size=byte_size,
                ),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            telemetry().record_shm_register("grpc_aio", "cuda", byte_size)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    register_xla_shared_memory = register_cuda_shared_memory
    get_xla_shared_memory_status = get_cuda_shared_memory_status

    async def unregister_cuda_shared_memory(self, name="", headers=None, client_timeout=None):
        try:
            await self._client_stub.CudaSharedMemoryUnregister(
                pb.CudaSharedMemoryUnregisterRequest(name=name),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
        except grpc.RpcError as e:
            raise_error_grpc(e)

    unregister_xla_shared_memory = unregister_cuda_shared_memory

    # -- inference ---------------------------------------------------------
    async def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        client_timeout=None,
        headers=None,
        compression_algorithm=None,
        parameters=None,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> InferResult:
        """Async inference (reference aio :634).  ``retry_policy`` /
        ``deadline_s``: same resilience contract as the sync client;
        ``priority``/``tenant``: the QoS identity, re-stamped per
        attempt so retries carry it."""
        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        if policy is None and deadline_s is None:
            return await self._infer_once(
                model_name, inputs, model_version, outputs, request_id,
                sequence_id, sequence_start, sequence_end, priority, timeout,
                client_timeout, headers, compression_algorithm, parameters,
                tenant=tenant)
        return await call_with_retry_async(
            policy,
            lambda remaining, _attempt: self._infer_once(
                model_name, inputs, model_version, outputs, request_id,
                sequence_id, sequence_start, sequence_end, priority, timeout,
                client_timeout, headers, compression_algorithm, parameters,
                tenant=tenant, _remaining_s=remaining),
            method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, "grpc_aio", "infer", request_id),
            journey=True)

    # -- wire fast path ----------------------------------------------------
    def prepare(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        priority=0,
        timeout=None,
        parameters=None,
    ) -> PreparedRequest:
        """Compile the invariant protobuf request once (sync client's
        ``prepare`` contract; stamps always copy — safe for concurrent
        tasks)."""
        return PreparedRequest(self, RequestTemplate(
            model_name, inputs, outputs, model_version, priority, timeout,
            parameters))

    async def _infer_prepared(self, prep: PreparedRequest, request_id,
                              headers, tenant, client_timeout=None,
                              _remaining_s=None, raws=None, _sink=None):
        """One stamped-request RPC (``_sink``: per-flight batch telemetry,
        see the sync client)."""
        tel = telemetry()
        t_ser0 = time.monotonic_ns()
        timeout_us = None
        if _remaining_s is not None and prep.template._timeout is None:
            timeout_us = remaining_us(_remaining_s)
        request = prep.template.stamp(request_id, raws, timeout_us,
                                      copy=True)
        metadata, rid = _with_trace_metadata(
            self._get_metadata(headers), request_id)
        if tenant:
            metadata = metadata + (("triton-tenant", str(tenant)),)
        t_ser1 = time.monotonic_ns()
        req_bytes = request.ByteSize()
        t0 = time.perf_counter()
        try:
            response = await self._client_stub.ModelInfer(
                request,
                metadata=metadata,
                timeout=min_timeout(client_timeout, _remaining_s),
                compression=grpc.Compression.NoCompression,
            )
            t_net1 = time.monotonic_ns()
            if _sink is not None:
                _sink.append((True, time.perf_counter() - t0, req_bytes,
                              response.ByteSize(), rid))
            else:
                tel.record_request(
                    prep.template.model_name, "grpc_aio", "infer",
                    time.perf_counter() - t0, ok=True,
                    request_bytes=req_bytes,
                    response_bytes=response.ByteSize(), request_id=rid)
            result = InferResult(response)
            if tel.tracing_enabled:
                tel.record_infer_spans(
                    rid, prep.template.model_name, "grpc_aio", "infer",
                    t_ser0, t_ser1, t_net1,
                    traceparent=traceparent_from_metadata(metadata))
            return result
        except grpc.RpcError as e:
            if _sink is not None:
                _sink.append((False, time.perf_counter() - t0, req_bytes,
                              0, rid))
            else:
                tel.record_request(
                    prep.template.model_name, "grpc_aio", "infer",
                    time.perf_counter() - t0, ok=False,
                    request_bytes=req_bytes, request_id=rid)
                if tel.tracing_enabled:
                    tel.record_infer_spans(
                        rid, prep.template.model_name, "grpc_aio", "infer",
                        t_ser0, t_ser1, time.monotonic_ns(),
                        traceparent=traceparent_from_metadata(metadata),
                        ok=False)
            raise_error_grpc(e)

    async def infer_many(
        self,
        model_name,
        requests,
        model_version="",
        outputs=None,
        priority=0,
        timeout=None,
        parameters=None,
        request_ids=None,
        headers=None,
        tenant: Optional[str] = None,
        client_timeout=None,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
        window: int = 32,
    ) -> List[InferResult]:
        """Batch submit with a bounded-concurrency gather (``window``
        in-flight at once) — the HTTP aio sibling's contract: one
        template, one retry/deadline envelope, one locked telemetry batch
        per flight, order-preserving results equal to N sequential
        ``infer`` calls."""
        items = list(requests)
        if not items:
            return []
        template = RequestTemplate(
            model_name, items[0], outputs, model_version, priority, timeout,
            parameters)
        prep = PreparedRequest(self, template)
        raws_list = [template.raws_for(item) for item in items]
        ids = list(request_ids) if request_ids else [""] * len(items)
        if len(ids) != len(items):
            raise_error("request_ids length must match requests")
        results: List[Optional[InferResult]] = [None] * len(items)
        done = [False] * len(items)
        tel = telemetry()

        async def flight(remaining, _attempt):
            # ONE deadline for the whole flight, re-derived as each item
            # acquires a window slot (see the http.aio sibling)
            deadline = (time.monotonic() + remaining
                        if remaining is not None else None)
            sem = asyncio.Semaphore(max(1, window))
            sink: list = []

            async def one(i):
                async with sem:
                    rem_i = None
                    if deadline is not None:
                        rem_i = deadline - time.monotonic()
                        if rem_i <= 0:
                            raise deadline_exceeded_error()
                    results[i] = await self._infer_prepared(
                        prep, ids[i], headers, tenant, client_timeout,
                        _remaining_s=rem_i, raws=raws_list[i],
                        _sink=sink)
                    done[i] = True

            pending = [i for i, d in enumerate(done) if not d]
            try:
                outcomes = await asyncio.gather(
                    *(one(i) for i in pending), return_exceptions=True)
            finally:
                tel.record_request_batch(
                    model_name, "grpc_aio", "infer", sink)
            for out in outcomes:
                if isinstance(out, BaseException):
                    raise out
            return results

        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        if policy is None and deadline_s is None:
            return await flight(None, 1)
        return await call_with_retry_async(
            policy, flight, method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, "grpc_aio", "infer", ""))

    async def _infer_once(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        client_timeout=None,
        headers=None,
        compression_algorithm=None,
        parameters=None,
        tenant=None,
        _remaining_s=None,
    ) -> InferResult:
        tel = telemetry()
        t_ser0 = time.monotonic_ns()
        if timeout is None and _remaining_s is not None:
            # remaining deadline budget as the v2 timeout parameter (µs),
            # restamped per attempt (see the sync client)
            timeout = remaining_us(_remaining_s)
        request = get_inference_request(
            model_name, inputs, model_version, request_id, outputs,
            sequence_id, sequence_start, sequence_end, priority, timeout, parameters,
        )
        metadata, rid = _with_trace_metadata(
            self._get_metadata(headers), request_id)
        if tenant:
            # QoS identity: appended last so the explicit kwarg wins
            metadata = metadata + (("triton-tenant", str(tenant)),)
        t_ser1 = time.monotonic_ns()
        req_bytes = request.ByteSize()
        t0 = time.perf_counter()
        try:
            response = await self._client_stub.ModelInfer(
                request,
                metadata=metadata,
                timeout=min_timeout(client_timeout, _remaining_s),
                compression=get_grpc_compression(compression_algorithm),
            )
            t_net1 = time.monotonic_ns()
            tel.record_request(
                model_name, "grpc_aio", "infer", time.perf_counter() - t0,
                ok=True, request_bytes=req_bytes,
                response_bytes=response.ByteSize(), request_id=rid)
            result = InferResult(response)
            if tel.tracing_enabled:
                tel.record_infer_spans(
                    rid, model_name, "grpc_aio", "infer",
                    t_ser0, t_ser1, t_net1,
                    traceparent=traceparent_from_metadata(metadata))
            return result
        except grpc.RpcError as e:
            tel.record_request(
                model_name, "grpc_aio", "infer", time.perf_counter() - t0,
                ok=False, request_bytes=req_bytes, request_id=rid)
            if tel.tracing_enabled:
                # failed attempts stay on the journey's trace — the
                # journeys report counts every attempt, not just winners
                tel.record_infer_spans(
                    rid, model_name, "grpc_aio", "infer", t_ser0, t_ser1,
                    time.monotonic_ns(),
                    traceparent=traceparent_from_metadata(metadata),
                    ok=False)
            raise_error_grpc(e)

    def stream_infer(
        self,
        inputs_iterator,
        stream_timeout=None,
        headers=None,
        compression_algorithm=None,
    ):
        """Bidi streaming: consume an async iterator of request-kwarg dicts,
        return a cancellable async iterator of ``(InferResult, error)``
        (reference aio :688-810)."""
        # one trace context per stream: every request on the stream shares it
        metadata, _rid = _with_trace_metadata(self._get_metadata(headers))

        async def _requests():
            async for kwargs in inputs_iterator:
                if not isinstance(kwargs, dict):
                    raise_error("inputs_iterator is not yielding a dict")
                if "model_name" not in kwargs or "inputs" not in kwargs:
                    raise_error(
                        "model_name and/or inputs is missing from "
                        "inputs_iterator's yielded dict"
                    )
                enable_empty_final = kwargs.pop("enable_empty_final_response", False)
                request = get_inference_request(
                    kwargs["model_name"],
                    kwargs["inputs"],
                    kwargs.get("model_version", ""),
                    kwargs.get("request_id", ""),
                    kwargs.get("outputs"),
                    kwargs.get("sequence_id", 0),
                    kwargs.get("sequence_start", False),
                    kwargs.get("sequence_end", False),
                    kwargs.get("priority", 0),
                    kwargs.get("timeout"),
                    kwargs.get("parameters"),
                )
                if enable_empty_final:
                    request.parameters[
                        "triton_enable_empty_final_response"
                    ].bool_param = True
                telemetry().record_request(
                    kwargs["model_name"], "grpc_aio", "stream_infer", None,
                    ok=True, request_bytes=request.ByteSize(),
                    request_id=kwargs.get("request_id", ""))
                yield request

        call = self._client_stub.ModelStreamInfer(
            _requests(),
            metadata=metadata,
            timeout=stream_timeout,
            compression=get_grpc_compression(compression_algorithm),
        )

        class _ResponseIterator:
            def __init__(self, grpc_call):
                self._call = grpc_call

            def __aiter__(self):
                return self

            async def __anext__(self):
                try:
                    response = await self._call.read()
                except grpc.RpcError as e:
                    raise StopAsyncIteration from e
                if response == grpc.aio.EOF:
                    raise StopAsyncIteration
                if response.error_message:
                    from .._utils import stream_error_to_exception

                    # same in-band status mapping as the sync stream
                    return None, stream_error_to_exception(
                        response.error_message)
                return InferResult(response.infer_response), None

            def cancel(self):
                return self._call.cancel()

        return _ResponseIterator(call)
