"""Synchronous gRPC ``InferenceServerClient``.

Parity target: reference ``tritonclient/grpc/_client.py`` (1936 LoC) — same
method surface: health/metadata/config, repository control, statistics,
trace/log settings, system+cuda(xla) shm RPCs, ``infer``, future-based
``async_infer`` with cancellation (CallContext :101-116), bidi streaming
(``start_stream``/``async_stream_infer``/``stop_stream`` :1743-1935), channel
options (unlimited message size :50-54, keepalive :57-98, custom channel args
:162-213).  Headers travel as gRPC metadata via the plugin hook (:241-248).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import grpc

from .._client import InferenceServerClientBase
from .._request import Request
from .._resilience import (RetryPolicy, call_with_retry,
                           deadline_exceeded_error, min_timeout,
                           remaining_us)
from .._telemetry import (new_trace_context, telemetry,
                          traceparent_from_metadata)
from ..protocol import inference_pb2 as pb
from ..protocol.service import GRPCInferenceServiceStub
from ..utils import raise_error
from ._infer_result import InferResult
from ._infer_stream import _InferStream, _RequestIterator
from ._template import RequestTemplate
from ._utils import (
    get_error_grpc,
    get_grpc_compression,
    get_inference_request,
    raise_error_grpc,
)


class PreparedRequest:
    """Handle for the gRPC wire fast path: a pre-built protobuf request
    (see ``_template.py``) bound to a client.  ``infer()`` re-stamps only
    id/deadline/payloads.  NOT thread-safe (the skeleton message is
    mutated in place) — build one per worker thread; the aio client's
    sibling stamps copies instead."""

    def __init__(self, client, template: RequestTemplate):
        self._client = client
        self.template = template

    def infer(self, request_id="", headers=None, tenant=None,
              client_timeout=None,
              retry_policy: Optional[RetryPolicy] = None,
              deadline_s: Optional[float] = None) -> InferResult:
        """Fast-path inference — same resilience/telemetry/trace contract
        as ``client.infer`` (the v2 timeout parameter is restamped per
        attempt under a deadline budget)."""
        client = self._client
        policy = retry_policy if retry_policy is not None \
            else client._retry_policy
        if policy is None and deadline_s is None:
            return client._infer_prepared(
                self, request_id, headers, tenant, client_timeout)
        return call_with_retry(
            policy,
            lambda remaining, _attempt: client._infer_prepared(
                self, request_id, headers, tenant, client_timeout,
                _remaining_s=remaining),
            method="infer", deadline_s=deadline_s,
            retry_meta=(self.template.model_name, "grpc", "infer",
                        request_id), journey=True)

INT32_MAX = 2**31 - 1
MAX_GRPC_MESSAGE_SIZE = INT32_MAX


class KeepAliveOptions:
    """gRPC keepalive knobs (reference :57-98)."""

    def __init__(
        self,
        keepalive_time_ms: int = INT32_MAX,
        keepalive_timeout_ms: int = 20000,
        keepalive_permit_without_calls: bool = False,
        http2_max_pings_without_data: int = 2,
    ):
        self.keepalive_time_ms = keepalive_time_ms
        self.keepalive_timeout_ms = keepalive_timeout_ms
        self.keepalive_permit_without_calls = keepalive_permit_without_calls
        self.http2_max_pings_without_data = http2_max_pings_without_data


class CallContext:
    """Cancellation handle for an in-flight async_infer (reference :101-116)."""

    def __init__(self, call):
        self._call = call

    def cancel(self) -> bool:
        return self._call.cancel()


class InferAsyncRequest:
    """Future-style handle returned by ``async_infer`` (framework addition
    mirroring the HTTP client's handle; the reference gRPC client is
    callback-only but its C++ sibling returns joinable state)."""

    def __init__(self, call):
        self._call = call

    def get_result(self, block: bool = True, timeout: Optional[float] = None) -> InferResult:
        try:
            # block=False polls: a zero timeout raises immediately when
            # the response hasn't arrived (HTTP-sibling semantics)
            response = self._call.result(timeout=timeout if block else 0)
        except grpc.FutureTimeoutError:
            # typed deadline failure, not the raw gRPC error: callers match
            # the same status string a server-side DEADLINE_EXCEEDED maps to
            from ..utils import InferenceServerException

            raise InferenceServerException(
                msg="timed out waiting for inference response",
                status="StatusCode.DEADLINE_EXCEEDED") from None
        except grpc.RpcError as rpc_error:
            raise_error_grpc(rpc_error)
        return InferResult(response)

    def cancel(self) -> bool:
        return self._call.cancel()


def _channel_options(keepalive_options, channel_args):
    options: List[tuple] = [
        ("grpc.max_send_message_length", MAX_GRPC_MESSAGE_SIZE),
        ("grpc.max_receive_message_length", MAX_GRPC_MESSAGE_SIZE),
        # transport audit (wire fast path): bias the channel for the
        # small-message high-rate infer pattern.  User channel_args
        # override both (dedupe below).
        ("grpc.optimization_target", "throughput"),
        # unlimited metadata soft limit would reject trace+tenant+auth
        # stacks on some proxies; 64KiB covers every header this
        # framework stamps with margin
        ("grpc.max_metadata_size", 1 << 16),
    ]
    if keepalive_options is None:
        keepalive_options = KeepAliveOptions()
    options.extend(
        [
            ("grpc.keepalive_time_ms", keepalive_options.keepalive_time_ms),
            ("grpc.keepalive_timeout_ms", keepalive_options.keepalive_timeout_ms),
            (
                "grpc.keepalive_permit_without_calls",
                int(keepalive_options.keepalive_permit_without_calls),
            ),
            (
                "grpc.http2.max_pings_without_data",
                keepalive_options.http2_max_pings_without_data,
            ),
        ]
    )
    if channel_args is not None:
        user_keys = {k for k, _ in channel_args}
        options = [(k, v) for k, v in options if k not in user_keys]
        options.extend(channel_args)
    return options


def _with_trace_metadata(metadata: tuple, request_id: str = ""):
    """Append trace-propagation metadata (``triton-request-id`` +
    ``traceparent``) unless the caller already supplied them; returns
    (metadata, request_id actually stamped)."""
    present = {k.lower() for k, _ in metadata}
    ctx = new_trace_context(request_id)
    extra = tuple((k, v) for k, v in ctx.items() if k not in present)
    rid = next((v for k, v in metadata if k.lower() == "triton-request-id"),
               ctx["triton-request-id"])
    return metadata + extra, rid


class InferenceServerClient(InferenceServerClientBase):
    """Client for the v2 protocol over gRPC.

    Thread-safe except for streaming: one stream per client at a time
    (reference contract grpc/_client.py:119-124)."""

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        ssl: bool = False,
        root_certificates: Optional[str] = None,
        private_key: Optional[str] = None,
        certificate_chain: Optional[str] = None,
        creds: Optional[grpc.ChannelCredentials] = None,
        keepalive_options: Optional[KeepAliveOptions] = None,
        channel_args: Optional[List[tuple]] = None,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        super().__init__()
        # client-level resilience default: health/metadata calls retry
        # under it unconditionally; infer honors it per its retry_infer
        # opt-in (a per-call retry_policy= overrides)
        self._retry_policy = retry_policy
        self._url = url
        self._verbose = verbose
        options = _channel_options(keepalive_options, channel_args)
        if creds is not None:
            self._channel = grpc.secure_channel(url, creds, options=options)
        elif ssl:
            def _read(path):
                if path is None:
                    return None
                with open(path, "rb") as f:
                    return f.read()

            credentials = grpc.ssl_channel_credentials(
                root_certificates=_read(root_certificates),
                private_key=_read(private_key),
                certificate_chain=_read(certificate_chain),
            )
            self._channel = grpc.secure_channel(url, credentials, options=options)
        else:
            self._channel = grpc.insecure_channel(url, options=options)
        self._client_stub = GRPCInferenceServiceStub(self._channel)
        self._stream: Optional[_InferStream] = None

    @property
    def url(self) -> str:
        """The ``host:port`` this client talks to — the endpoint label
        the cluster layer keys its routing counters by."""
        return self._url

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self.stop_stream()
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _get_metadata(self, headers: Optional[dict]) -> tuple:
        request = Request(dict(headers) if headers else {})
        self._call_plugin(request)
        return tuple(request.headers.items())

    def _with_retry(self, method_kind: str, fn):
        """Run an idempotent (health/metadata) call under the client-level
        retry policy, if one is configured.  ``fn(timeout)`` receives the
        per-attempt transport timeout (client_timeout capped by what's
        left of the policy's deadline budget, when it has one)."""
        if self._retry_policy is None:
            return fn(None)
        return call_with_retry(
            self._retry_policy,
            lambda remaining, _attempt: fn(remaining),
            method=method_kind,
            retry_meta=("", "grpc", method_kind, ""))

    # -- health / metadata -------------------------------------------------
    def is_server_live(self, headers=None, client_timeout=None) -> bool:
        def _call(remaining):
            try:
                response = self._client_stub.ServerLive(
                    pb.ServerLiveRequest(),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                if self._verbose:
                    print(response)
                return response.live
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return self._with_retry("health", _call)

    def is_server_ready(self, headers=None, client_timeout=None) -> bool:
        def _call(remaining):
            try:
                response = self._client_stub.ServerReady(
                    pb.ServerReadyRequest(),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                return response.ready
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return self._with_retry("health", _call)

    def is_model_ready(self, model_name, model_version="", headers=None, client_timeout=None):
        def _call(remaining):
            try:
                response = self._client_stub.ModelReady(
                    pb.ModelReadyRequest(name=model_name,
                                         version=model_version),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                return response.ready
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return self._with_retry("health", _call)

    def get_server_metadata(self, headers=None, as_json=False, client_timeout=None):
        def _call(remaining):
            try:
                response = self._client_stub.ServerMetadata(
                    pb.ServerMetadataRequest(),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                if self._verbose:
                    print(response)
                return _maybe_json(response, as_json)
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return self._with_retry("metadata", _call)

    def get_model_metadata(
        self, model_name, model_version="", headers=None, as_json=False, client_timeout=None
    ):
        def _call(remaining):
            try:
                response = self._client_stub.ModelMetadata(
                    pb.ModelMetadataRequest(name=model_name,
                                            version=model_version),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                return _maybe_json(response, as_json)
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return self._with_retry("metadata", _call)

    def get_model_config(
        self, model_name, model_version="", headers=None, as_json=False, client_timeout=None
    ):
        def _call(remaining):
            try:
                response = self._client_stub.ModelConfig(
                    pb.ModelConfigRequest(name=model_name,
                                          version=model_version),
                    metadata=self._get_metadata(headers),
                    timeout=min_timeout(client_timeout, remaining),
                )
                return _maybe_json(response, as_json)
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return self._with_retry("metadata", _call)

    # -- repository --------------------------------------------------------
    def get_model_repository_index(self, headers=None, as_json=False, client_timeout=None):
        try:
            response = self._client_stub.RepositoryIndex(
                pb.RepositoryIndexRequest(), metadata=self._get_metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def load_model(
        self, model_name, headers=None, config: Optional[str] = None,
        files: Optional[Dict[str, bytes]] = None, client_timeout=None,
    ):
        request = pb.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = config
        if files:
            for path, content in files.items():
                request.parameters[path].bytes_param = content
        try:
            self._client_stub.RepositoryModelLoad(
                request, metadata=self._get_metadata(headers), timeout=client_timeout
            )
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def unload_model(
        self, model_name, headers=None, unload_dependents=False, client_timeout=None
    ):
        request = pb.RepositoryModelUnloadRequest(model_name=model_name)
        request.parameters["unload_dependents"].bool_param = unload_dependents
        try:
            self._client_stub.RepositoryModelUnload(
                request, metadata=self._get_metadata(headers), timeout=client_timeout
            )
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # -- statistics / trace / logging --------------------------------------
    def get_inference_statistics(
        self, model_name="", model_version="", headers=None, as_json=False, client_timeout=None
    ):
        try:
            response = self._client_stub.ModelStatistics(
                pb.ModelStatisticsRequest(name=model_name, version=model_version),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def update_trace_settings(
        self, model_name=None, settings=None, headers=None, as_json=False, client_timeout=None
    ):
        request = pb.TraceSettingRequest(model_name=model_name or "")
        for key, value in (settings or {}).items():
            if value is not None:
                vals = value if isinstance(value, list) else [str(value)]
                request.settings[key].value.extend(vals)
            else:
                request.settings[key].SetInParent()
        try:
            response = self._client_stub.TraceSetting(
                request, metadata=self._get_metadata(headers), timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_trace_settings(self, model_name=None, headers=None, as_json=False, client_timeout=None):
        return self.update_trace_settings(model_name, None, headers, as_json, client_timeout)

    def update_log_settings(self, settings, headers=None, as_json=False, client_timeout=None):
        request = pb.LogSettingsRequest()
        for key, value in settings.items():
            if isinstance(value, bool):
                request.settings[key].bool_param = value
            elif isinstance(value, int):
                request.settings[key].uint32_param = value
            else:
                request.settings[key].string_param = str(value)
        try:
            response = self._client_stub.LogSettings(
                request, metadata=self._get_metadata(headers), timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_log_settings(self, headers=None, as_json=False, client_timeout=None):
        return self.update_log_settings({}, headers, as_json, client_timeout)

    def get_flight_recorder(self, model_name=None, limit=0, headers=None,
                            client_timeout=None) -> dict:
        """The server's flight-recorder debug snapshot (always-on recent
        ring + pinned tail-latency/failure outliers with span trees) —
        same JSON shape as HTTP's GET /v2/debug/flight_recorder."""
        import json

        from ..protocol import debug_pb2 as pb_debug

        try:
            response = self._client_stub.FlightRecorder(
                pb_debug.FlightRecorderRequest(
                    model_name=model_name or "", limit=int(limit or 0)),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return json.loads(response.payload_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_device_stats(self, model_name=None, headers=None,
                         client_timeout=None) -> dict:
        """The server's device/scheduler observability snapshot (duty
        cycle / live MFU / compiles / ticks / transfers / HBM + SLO
        state) — same JSON shape as HTTP's GET /v2/debug/device_stats."""
        import json

        from ..protocol import debug_pb2 as pb_debug

        try:
            response = self._client_stub.DeviceStats(
                pb_debug.DeviceStatsRequest(model_name=model_name or ""),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return json.loads(response.payload_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_costs(self, model_name=None, headers=None,
                  client_timeout=None) -> dict:
        """The server's per-tenant cost-attribution ledger (device-time,
        FLOPs, generated tokens, KV byte-seconds per model and tenant)
        — same JSON shape as HTTP's GET /v2/debug/costs."""
        import json

        from ..protocol import debug_pb2 as pb_debug

        try:
            response = self._client_stub.Costs(
                pb_debug.CostsRequest(model_name=model_name or ""),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return json.loads(response.payload_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # -- shared memory -----------------------------------------------------
    def get_system_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        try:
            response = self._client_stub.SystemSharedMemoryStatus(
                pb.SystemSharedMemoryStatusRequest(name=region_name),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, client_timeout=None
    ):
        try:
            self._client_stub.SystemSharedMemoryRegister(
                pb.SystemSharedMemoryRegisterRequest(
                    name=name, key=key, offset=offset, byte_size=byte_size
                ),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            telemetry().record_shm_register("grpc", "system", byte_size)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def unregister_system_shared_memory(self, name="", headers=None, client_timeout=None):
        try:
            self._client_stub.SystemSharedMemoryUnregister(
                pb.SystemSharedMemoryUnregisterRequest(name=name),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_cuda_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        try:
            response = self._client_stub.CudaSharedMemoryStatus(
                pb.CudaSharedMemoryStatusRequest(name=region_name),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def register_cuda_shared_memory(
        self, name, raw_handle: bytes, device_id: int, byte_size: int,
        headers=None, client_timeout=None,
    ):
        """Register a device-buffer region; ``raw_handle`` comes from
        ``xla_shared_memory.get_raw_handle`` (v2 wire name kept for compat,
        reference :1339-1388)."""
        try:
            self._client_stub.CudaSharedMemoryRegister(
                pb.CudaSharedMemoryRegisterRequest(
                    name=name, raw_handle=raw_handle, device_id=device_id,
                    byte_size=byte_size,
                ),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
            telemetry().record_shm_register("grpc", "cuda", byte_size)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    register_xla_shared_memory = register_cuda_shared_memory
    get_xla_shared_memory_status = get_cuda_shared_memory_status

    def unregister_cuda_shared_memory(self, name="", headers=None, client_timeout=None):
        try:
            self._client_stub.CudaSharedMemoryUnregister(
                pb.CudaSharedMemoryUnregisterRequest(name=name),
                metadata=self._get_metadata(headers), timeout=client_timeout,
            )
        except grpc.RpcError as e:
            raise_error_grpc(e)

    unregister_xla_shared_memory = unregister_cuda_shared_memory

    # -- inference ---------------------------------------------------------
    def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        client_timeout=None,
        headers=None,
        compression_algorithm=None,
        parameters=None,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> InferResult:
        """Synchronous inference (reference :1445-1572).

        ``retry_policy`` (or the client-level one) retries retryable
        failures when ``retry_infer`` is opted in; ``deadline_s`` caps
        total wall-clock across attempts and propagates the remaining
        budget to the server via the v2 ``timeout`` parameter (µs).
        ``priority`` (0 = highest) and ``tenant`` (``triton-tenant``
        metadata) are the QoS identity — re-stamped per attempt."""
        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        if policy is None and deadline_s is None:
            return self._infer_once(
                model_name, inputs, model_version, outputs, request_id,
                sequence_id, sequence_start, sequence_end, priority, timeout,
                client_timeout, headers, compression_algorithm, parameters,
                tenant=tenant)
        return call_with_retry(
            policy,
            lambda remaining, _attempt: self._infer_once(
                model_name, inputs, model_version, outputs, request_id,
                sequence_id, sequence_start, sequence_end, priority, timeout,
                client_timeout, headers, compression_algorithm, parameters,
                tenant=tenant, _remaining_s=remaining),
            method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, "grpc", "infer", request_id),
            journey=True)

    # -- wire fast path ----------------------------------------------------
    def prepare(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        priority=0,
        timeout=None,
        parameters=None,
    ) -> PreparedRequest:
        """Compile the invariant protobuf request once (see
        ``_template.py``); the returned handle's ``infer()`` re-stamps
        only id/deadline/tensor payloads.  ``inputs`` must already carry
        data; NOT thread-safe — one per worker thread."""
        return PreparedRequest(self, RequestTemplate(
            model_name, inputs, outputs, model_version, priority, timeout,
            parameters))

    def _infer_prepared(self, prep: PreparedRequest, request_id, headers,
                        tenant, client_timeout=None, _remaining_s=None,
                        raws=None, _sink=None):
        """One stamped-request RPC.  ``_sink`` defers the telemetry record
        to the caller's per-flight batch (``infer_many``) — same contract
        as the HTTP sibling."""
        tel = telemetry()
        t_ser0 = time.monotonic_ns()
        timeout_us = None
        if _remaining_s is not None and prep.template._timeout is None:
            timeout_us = remaining_us(_remaining_s)
        request = prep.template.stamp(request_id, raws, timeout_us)
        metadata, rid = _with_trace_metadata(
            self._get_metadata(headers), request_id)
        if tenant:
            metadata = metadata + (("triton-tenant", str(tenant)),)
        t_ser1 = time.monotonic_ns()
        req_bytes = request.ByteSize()
        t0 = time.perf_counter()
        try:
            response = self._client_stub.ModelInfer(
                request,
                metadata=metadata,
                timeout=min_timeout(client_timeout, _remaining_s),
                compression=grpc.Compression.NoCompression,
            )
            t_net1 = time.monotonic_ns()
            if _sink is not None:
                _sink.append((True, time.perf_counter() - t0, req_bytes,
                              response.ByteSize(), rid))
            else:
                tel.record_request(
                    prep.template.model_name, "grpc", "infer",
                    time.perf_counter() - t0, ok=True,
                    request_bytes=req_bytes,
                    response_bytes=response.ByteSize(), request_id=rid)
            result = InferResult(response)
            if tel.tracing_enabled:
                tel.record_infer_spans(
                    rid, prep.template.model_name, "grpc", "infer",
                    t_ser0, t_ser1, t_net1,
                    traceparent=traceparent_from_metadata(metadata))
            return result
        except grpc.RpcError as e:
            if _sink is not None:
                _sink.append((False, time.perf_counter() - t0, req_bytes,
                              0, rid))
            else:
                tel.record_request(
                    prep.template.model_name, "grpc", "infer",
                    time.perf_counter() - t0, ok=False,
                    request_bytes=req_bytes, request_id=rid)
                if tel.tracing_enabled:
                    tel.record_infer_spans(
                        rid, prep.template.model_name, "grpc", "infer",
                        t_ser0, t_ser1, time.monotonic_ns(),
                        traceparent=traceparent_from_metadata(metadata),
                        ok=False)
            raise_error_grpc(e)

    def infer_many(
        self,
        model_name,
        requests,
        model_version="",
        outputs=None,
        priority=0,
        timeout=None,
        parameters=None,
        request_ids=None,
        headers=None,
        tenant: Optional[str] = None,
        client_timeout=None,
        retry_policy: Optional[RetryPolicy] = None,
        deadline_s: Optional[float] = None,
    ) -> List[InferResult]:
        """Batch submit: every item (a list of data-carrying
        ``InferInput`` matching the first item's specs) rides ONE pre-built
        protobuf skeleton and ONE retry/deadline/telemetry envelope.
        Results keep submission order and equal N sequential ``infer``
        calls; a mid-batch retry resumes at the failed item."""
        items = list(requests)
        if not items:
            return []
        template = RequestTemplate(
            model_name, items[0], outputs, model_version, priority, timeout,
            parameters)
        prep = PreparedRequest(self, template)
        raws_list = [template.raws_for(item) for item in items]
        ids = list(request_ids) if request_ids else [""] * len(items)
        if len(ids) != len(items):
            raise_error("request_ids length must match requests")
        results: List[Optional[InferResult]] = [None] * len(items)
        next_idx = [0]
        tel = telemetry()

        def flight(remaining, _attempt):
            # ONE deadline for the whole flight, re-derived per item (a
            # slow batch must raise, not grant each item the full budget)
            deadline = (time.monotonic() + remaining
                        if remaining is not None else None)
            sink: list = []
            try:
                while next_idx[0] < len(items):
                    i = next_idx[0]
                    rem_i = None
                    if deadline is not None:
                        rem_i = deadline - time.monotonic()
                        if rem_i <= 0:
                            raise deadline_exceeded_error()
                    results[i] = self._infer_prepared(
                        prep, ids[i], headers, tenant, client_timeout,
                        _remaining_s=rem_i, raws=raws_list[i],
                        _sink=sink)
                    next_idx[0] += 1
            finally:
                tel.record_request_batch(model_name, "grpc", "infer", sink)
            return results

        policy = retry_policy if retry_policy is not None \
            else self._retry_policy
        if policy is None and deadline_s is None:
            return flight(None, 1)
        return call_with_retry(
            policy, flight, method="infer", deadline_s=deadline_s,
            retry_meta=(model_name, "grpc", "infer", ""))

    def _infer_once(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        client_timeout=None,
        headers=None,
        compression_algorithm=None,
        parameters=None,
        tenant=None,
        _remaining_s=None,
    ) -> InferResult:
        tel = telemetry()
        t_ser0 = time.monotonic_ns()
        if timeout is None and _remaining_s is not None:
            # remaining deadline budget as the v2 timeout parameter (µs),
            # restamped per attempt: the server drops the request once it
            # expires instead of burning compute for a caller that gave up
            timeout = remaining_us(_remaining_s)
        request = get_inference_request(
            model_name, inputs, model_version, request_id, outputs,
            sequence_id, sequence_start, sequence_end, priority, timeout, parameters,
        )
        metadata, rid = _with_trace_metadata(
            self._get_metadata(headers), request_id)
        if tenant:
            # QoS identity: appended LAST so the explicit kwarg wins over
            # a header-supplied value (the server reads the final entry)
            metadata = metadata + (("triton-tenant", str(tenant)),)
        t_ser1 = time.monotonic_ns()
        if self._verbose:
            print(f"infer, metadata {metadata}\n{request}")
        req_bytes = request.ByteSize()
        t0 = time.perf_counter()
        try:
            response = self._client_stub.ModelInfer(
                request,
                metadata=metadata,
                timeout=min_timeout(client_timeout, _remaining_s),
                compression=get_grpc_compression(compression_algorithm),
            )
            t_net1 = time.monotonic_ns()
            if self._verbose:
                print(response)
            tel.record_request(
                model_name, "grpc", "infer", time.perf_counter() - t0,
                ok=True, request_bytes=req_bytes,
                response_bytes=response.ByteSize(), request_id=rid)
            result = InferResult(response)
            if tel.tracing_enabled:
                tel.record_infer_spans(
                    rid, model_name, "grpc", "infer",
                    t_ser0, t_ser1, t_net1,
                    traceparent=traceparent_from_metadata(metadata))
            return result
        except grpc.RpcError as e:
            tel.record_request(
                model_name, "grpc", "infer", time.perf_counter() - t0,
                ok=False, request_bytes=req_bytes, request_id=rid)
            if tel.tracing_enabled:
                # failed attempts stay on the journey's trace — the
                # journeys report counts every attempt, not just winners
                tel.record_infer_spans(
                    rid, model_name, "grpc", "infer", t_ser0, t_ser1,
                    time.monotonic_ns(),
                    traceparent=traceparent_from_metadata(metadata),
                    ok=False)
            raise_error_grpc(e)

    def async_infer(
        self,
        model_name,
        inputs,
        callback=None,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        client_timeout=None,
        headers=None,
        compression_algorithm=None,
        parameters=None,
        tenant=None,
    ):
        """Asynchronous inference via gRPC future (reference :1574-1741).

        With ``callback``: invoked as ``callback(result, error)`` from a gRPC
        thread; returns a ``CallContext`` for cancellation.  Without:
        returns an ``InferAsyncRequest`` whose ``get_result()`` blocks.

        The client-level retry policy does NOT apply here: the call is a
        single gRPC future whose cancellation handle the caller owns, and
        re-issuing it behind that handle would detach cancel() from the
        in-flight attempt.  Use ``infer`` (or the HTTP client's
        ``async_infer``) for retried inference."""
        request = get_inference_request(
            model_name, inputs, model_version, request_id, outputs,
            sequence_id, sequence_start, sequence_end, priority, timeout, parameters,
        )
        metadata, rid = _with_trace_metadata(
            self._get_metadata(headers), request_id)
        if tenant:
            metadata = metadata + (("triton-tenant", str(tenant)),)
        req_bytes = request.ByteSize()
        t0 = time.perf_counter()
        call = self._client_stub.ModelInfer.future(
            request,
            metadata=metadata,
            timeout=client_timeout,
            compression=get_grpc_compression(compression_algorithm),
        )

        def _record(c):
            try:
                response = c.result()
                telemetry().record_request(
                    model_name, "grpc", "async_infer",
                    time.perf_counter() - t0, ok=True,
                    request_bytes=req_bytes,
                    response_bytes=response.ByteSize(), request_id=rid)
            except Exception:
                telemetry().record_request(
                    model_name, "grpc", "async_infer",
                    time.perf_counter() - t0, ok=False,
                    request_bytes=req_bytes, request_id=rid)

        call.add_done_callback(_record)
        if callback is None:
            return InferAsyncRequest(call)

        def _done(c):
            try:
                response = c.result()
                callback(result=InferResult(response), error=None)
            except grpc.RpcError as rpc_error:
                callback(result=None, error=get_error_grpc(rpc_error))
            except grpc.FutureCancelledError:
                from ..utils import InferenceServerException

                callback(
                    result=None,
                    error=InferenceServerException(
                        msg="Locally cancelled by application!",
                        status="StatusCode.CANCELLED",
                    ),
                )

        call.add_done_callback(_done)
        return CallContext(call)

    # -- streaming ---------------------------------------------------------
    def start_stream(
        self,
        callback,
        stream_timeout=None,
        headers=None,
        compression_algorithm=None,
    ) -> None:
        """Open the bidi stream; ``callback(result, error)`` runs on a reader
        thread for every stream message (reference :1743-1798)."""
        if self._stream is not None:
            raise_error(
                "cannot start another stream with one already running. "
                "'InferenceServerClient' supports only a single active stream "
                "at a given time."
            )
        self._stream = _InferStream(callback, self._verbose)
        # one trace context per stream: every request on the stream shares it
        metadata, _rid = _with_trace_metadata(self._get_metadata(headers))
        try:
            response_iterator = self._client_stub.ModelStreamInfer(
                _RequestIterator(self._stream),
                metadata=metadata,
                timeout=stream_timeout,
                compression=get_grpc_compression(compression_algorithm),
            )
            self._stream._init_handler(response_iterator)
        except grpc.RpcError as e:
            self._stream = None
            raise_error_grpc(e)

    def async_stream_infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        enable_empty_final_response=False,
        priority=0,
        timeout=None,
        parameters=None,
    ) -> None:
        """Enqueue a request on the active stream (reference :1815-1935)."""
        if self._stream is None:
            raise_error("stream not available, start_stream() must be called first.")
        request = get_inference_request(
            model_name, inputs, model_version, request_id, outputs,
            sequence_id, sequence_start, sequence_end, priority, timeout, parameters,
        )
        if enable_empty_final_response:
            request.parameters["triton_enable_empty_final_response"].bool_param = True
        if self._verbose:
            print(f"async_stream_infer\n{request}")
        self._stream._enqueue_request(request)
        # stream submits count without a latency observation: completion
        # arrives on the stream callback, decoupled from this send
        telemetry().record_request(
            model_name, "grpc", "stream_infer", None, ok=True,
            request_bytes=request.ByteSize(), request_id=request_id)

    def stop_stream(self, cancel_requests: bool = False) -> None:
        """Close the active stream (reference :1800-1813)."""
        if self._stream is not None:
            self._stream.close(cancel_requests)
        self._stream = None


def _maybe_json(message, as_json: bool):
    if not as_json:
        return message
    from google.protobuf import json_format

    return json_format.MessageToDict(message, preserving_proto_field_name=True)
