"""Bidirectional-stream plumbing for the gRPC client.

Parity target: reference ``tritonclient/grpc/_infer_stream.py`` (192 LoC):
``_InferStream`` = request ``queue.Queue`` + dedicated response-reader thread
invoking the user callback (:57-167); ``_RequestIterator`` blocks on the
queue with a ``None`` sentinel ending the stream (:170-191); cancellation
surfaces ``StatusCode.CANCELLED`` per in-flight request (:157-167).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

import grpc

from ..utils import InferenceServerException, raise_error
from ._infer_result import InferResult
from ._utils import get_error_grpc, stream_error_to_exception


class _InferStream:
    def __init__(self, callback: Callable, verbose: bool = False):
        self._callback = callback
        self._verbose = verbose
        self._request_queue: "queue.Queue" = queue.Queue()
        self._handler: Optional[threading.Thread] = None
        self._response_iterator = None
        self._active = True

    def __del__(self):
        self.close(cancel_requests=False)

    def close(self, cancel_requests: bool = False) -> None:
        """End the stream: optionally cancel in-flight requests, else flush
        the queue with the None sentinel and join the reader."""
        if cancel_requests and self._response_iterator is not None:
            self._response_iterator.cancel()
            self._active = False
        if self._handler is not None:
            self._request_queue.put(None)
            if self._handler.is_alive():
                self._handler.join()
            if self._verbose:
                print("stream stopped...")
            self._handler = None

    def _init_handler(self, response_iterator) -> None:
        self._response_iterator = response_iterator
        if self._handler is not None:
            raise_error("Attempted to initialize already initialized InferStream")
        self._handler = threading.Thread(
            target=self._process_response, name="tc-tpu-stream-reader"
        )
        self._handler.daemon = True
        self._handler.start()

    def _enqueue_request(self, request) -> None:
        if not self._active:
            raise_error("The stream is no longer in valid state, the error detail "
                        "is reported through provided callback. A new stream should "
                        "be started after stopping the current stream.")
        self._request_queue.put(request)

    def _get_request(self):
        return self._request_queue.get()

    def _process_response(self) -> None:
        """Reader loop: each stream message is either an in-band error or an
        infer response handed to the callback."""
        try:
            for response in self._response_iterator:
                if self._verbose:
                    print(response)
                result = error = None
                if response.error_message != "":
                    # "[NNN] "-prefixed messages carry the server status
                    # in-band — mapped back so stream failures classify
                    # like unary ones (shed/deadline gating)
                    error = stream_error_to_exception(response.error_message)
                else:
                    result = InferResult(response.infer_response)
                self._callback(result=result, error=error)
        except grpc.RpcError as rpc_error:
            # On cancellation only notify once with CANCELLED (reference
            # :157-167); other errors deactivate the stream and surface.
            if rpc_error.code() == grpc.StatusCode.CANCELLED:
                self._callback(result=None, error=get_error_grpc(rpc_error))
            else:
                self._active = False
                self._callback(result=None, error=get_error_grpc(rpc_error))


class _RequestIterator:
    """Iterator the gRPC sender thread pulls requests from."""

    def __init__(self, stream: _InferStream):
        self._stream = stream

    def __iter__(self):
        return self

    def __next__(self):
        request = self._stream._get_request()
        if request is None:
            raise StopIteration
        return request
