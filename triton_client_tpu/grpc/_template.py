"""Pre-built protobuf request templates — the gRPC wire fast path.

The slow path rebuilds a ``ModelInferRequest`` per call: tensor submessages,
parameter maps, shape lists — all python-level protobuf construction.  The
reference C++ client earns much of its speed from keeping the request
message alive across calls and pointer-swapping the tensor payloads
(PAPER.md survey of ``src/c++/library``); this is the Python analog.

:class:`RequestTemplate` builds the full request ONCE via the real
slow-path builder (``get_inference_request`` — so the field population can
never drift), clears the per-call payload list, and ``stamp()`` then only:

* sets/clears the request ``id``,
* restamps the v2 ``timeout`` parameter when a deadline budget is active,
* swaps ``raw_input_contents`` wholesale (payload handoff, no submessage
  rebuild).

What invalidates a template: input name/shape/dtype or representation
changes, different outputs/priority/frozen-timeout/parameters.  ``stamp``
validates the frozen fixed-dtype payload sizes and raises rather than send
a corrupt request.

Thread-safety: ``stamp(copy=False)`` mutates the ONE shared message —
single-thread use only (one PreparedRequest per worker, the perf_analyzer
session model).  ``copy=True`` stamps into a fresh ``CopyFrom`` of the
skeleton (C-speed in upb) for concurrent in-flight requests — the aio
clients always do this, because grpc.aio may serialize after the call
returns to the event loop.
"""

from __future__ import annotations

from typing import List, Optional

from ..protocol import inference_pb2 as pb
from ..utils import raise_error
from ._utils import get_inference_request

__all__ = ["RequestTemplate"]


class RequestTemplate:
    """Compiled invariant skeleton of one (model, inputs-spec, outputs,
    params) request shape.  Build via ``client.prepare(...)``."""

    def __init__(self, model_name: str, inputs, outputs=None,
                 model_version: str = "", priority: int = 0,
                 timeout: Optional[int] = None, parameters=None):
        self.model_name = model_name
        self.model_version = model_version
        self._inputs = list(inputs)
        self._outputs = list(outputs) if outputs else []
        self._timeout = timeout
        self._request = get_inference_request(
            model_name, inputs, model_version, "", outputs, 0, False, False,
            priority, timeout, parameters)
        # which inputs contribute a raw payload (shm inputs don't), plus
        # the frozen wire size per fixed-dtype slot (None = BYTES, varies).
        # Header-only (shm) inputs have their whole submessage frozen into
        # the request — snapshot it so a representation/region switch
        # after prepare() raises instead of silently sending stale routing
        self._raw_idx: List[int] = []
        self._frozen_sizes: List[Optional[int]] = []
        self._static_inputs: List[tuple] = []
        # shapes are frozen into the request submessages; size checks
        # alone can't catch a same-byte-count reshape (or BYTES reshape).
        # Epochs make the per-stamp check one int compare; the full shape
        # compare runs only when an epoch moved (re-synced if the shape
        # round-tripped back to the frozen one).
        self._frozen_shapes: List[List[int]] = []
        self._frozen_epochs: List[int] = []
        for i, inp in enumerate(self._inputs):
            raw = inp._get_raw_data()
            self._frozen_shapes.append(list(inp.shape()))
            self._frozen_epochs.append(inp._shape_epoch)
            if raw is None:
                self._static_inputs.append(
                    (i, inp._get_tensor_pb().SerializeToString(
                        deterministic=True)))
                continue
            self._raw_idx.append(i)
            self._frozen_sizes.append(
                None if inp.datatype() == "BYTES" else len(raw))
        # requested outputs are compiled into the request too (incl. shm
        # routing): snapshot their submessages so a post-prepare output
        # mutation raises instead of silently riding the stale routing —
        # guarded by the outputs' mutation epochs (int compare per stamp;
        # the serialize-and-compare runs only when an epoch moved)
        self._frozen_outputs: List[bytes] = [
            o._get_tensor_pb().SerializeToString(deterministic=True)
            for o in self._outputs]
        self._frozen_out_epochs: List[int] = [
            o._mut_epoch for o in self._outputs]
        del self._request.raw_input_contents[:]  # payloads stamp per call

    def _check_static(self, inputs) -> None:
        """Header-only (shm) inputs are frozen into the request — the
        given request's state must still serialize identically.
        Requested outputs are validated the same way (their submessages,
        incl. shm routing, are compiled in)."""
        for i, frozen in self._static_inputs:
            inp = inputs[i]
            if inp._get_raw_data() is not None \
                    or inp._get_tensor_pb().SerializeToString(
                        deterministic=True) != frozen:
                raise_error(
                    f"template invalidated: input {inp.name()!r} changed "
                    "representation or shm parameters after prepare (its "
                    "submessage is compiled in — re-prepare)")
        for j, o in enumerate(self._outputs):
            if o._mut_epoch == self._frozen_out_epochs[j]:
                continue
            if o._get_tensor_pb().SerializeToString(
                    deterministic=True) != self._frozen_outputs[j]:
                raise_error(
                    f"template invalidated: output {o.name()!r} "
                    "parameters changed after prepare (its submessage is "
                    "compiled in — re-prepare)")
            self._frozen_out_epochs[j] = o._mut_epoch  # round-tripped

    def raws_for(self, inputs) -> List[bytes]:
        """Extract (and spec-validate) another request's payloads in this
        template's slot order — the ``infer_many`` per-item path.  Every
        input is validated: payload slots for spec+data, header-only
        (shm) inputs against the frozen submessage, so an item whose shm
        region differs from the template's cannot silently ride the
        compiled one."""
        if len(inputs) != len(self._inputs):
            raise_error("infer_many item does not match the template's "
                        f"input count ({len(inputs)} != "
                        f"{len(self._inputs)})")
        self._check_static(inputs)
        raws = []
        for i in self._raw_idx:
            tpl_inp, inp = self._inputs[i], inputs[i]
            if inp.name() != tpl_inp.name() \
                    or inp.datatype() != tpl_inp.datatype() \
                    or list(inp.shape()) != list(tpl_inp.shape()):
                raise_error(
                    f"infer_many item input {inp.name()!r} does not match "
                    "the template spec (name/dtype/shape must be "
                    "identical; re-prepare for a new shape)")
            raw = inp._get_raw_data()
            if raw is None:
                raise_error(
                    f"infer_many item input {inp.name()!r} has no data "
                    "attached")
            raws.append(raw)
        return raws

    def stamp(self, request_id: str = "", raws=None,
              timeout_us: Optional[int] = None,
              copy: bool = False) -> pb.ModelInferRequest:
        """Re-stamp the variable fields and return the request message.

        ``raws`` overrides the payloads (default: the bound inputs'
        current data); ``timeout_us`` restamps the v2 deadline parameter
        for this attempt; ``copy=True`` returns a fresh message (required
        for concurrent in-flight use — see the module docstring).
        """
        if raws is None:
            self._check_static(self._inputs)
            for i, epoch in enumerate(self._frozen_epochs):
                inp = self._inputs[i]
                if inp._shape_epoch != epoch:
                    if list(inp.shape()) != self._frozen_shapes[i]:
                        raise_error(
                            "template invalidated: input "
                            f"{inp.name()!r} shape changed to "
                            f"{list(inp.shape())} after prepare froze "
                            f"{self._frozen_shapes[i]} (re-prepare)")
                    self._frozen_epochs[i] = inp._shape_epoch
            raws = []
            for i in self._raw_idx:
                raw = self._inputs[i]._get_raw_data()
                if raw is None:
                    raise_error(
                        "template invalidated: input "
                        f"{self._inputs[i].name()!r} no longer carries "
                        "raw data (representation changed after prepare "
                        "— re-prepare)")
                raws.append(raw)
        elif len(raws) != len(self._raw_idx):
            raise_error(
                f"template expects {len(self._raw_idx)} tensor payloads, "
                f"got {len(raws)}")
        for slot, frozen in enumerate(self._frozen_sizes):
            if frozen is not None and len(raws[slot]) != frozen:
                raise_error(
                    "template invalidated: input "
                    f"{self._inputs[self._raw_idx[slot]].name()!r} payload "
                    f"is {len(raws[slot])} bytes, template froze {frozen} "
                    "(re-prepare after a shape change)")
        request = self._request
        if copy:
            fresh = pb.ModelInferRequest()
            fresh.CopyFrom(request)
            request = fresh
        if request_id:
            request.id = request_id
        elif request.id:
            request.ClearField("id")
        if timeout_us is not None:
            request.parameters["timeout"].int64_param = timeout_us
        elif self._timeout is None and "timeout" in request.parameters:
            # a prior deadline-budgeted attempt stamped one; a plain call
            # must not inherit it
            del request.parameters["timeout"]
        del request.raw_input_contents[:]
        request.raw_input_contents.extend(raws)
        return request
