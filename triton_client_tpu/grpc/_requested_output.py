"""gRPC-protocol ``InferRequestedOutput`` (reference
``tritonclient/grpc/_requested_output.py``)."""

from __future__ import annotations

from ..protocol import inference_pb2 as pb


class InferRequestedOutput:
    def __init__(self, name: str, class_count: int = 0):
        self._output = pb.ModelInferRequest.InferRequestedOutputTensor(name=name)
        if class_count != 0:
            self._output.parameters["classification"].int64_param = class_count
        # bumped on every mutation: lets a template detect post-prepare
        # changes with one int compare on the stamp hot path
        self._mut_epoch = 0

    def name(self) -> str:
        return self._output.name

    def set_shared_memory(self, region_name: str, byte_size: int, offset: int = 0):
        self._output.parameters["shared_memory_region"].string_param = region_name
        self._output.parameters["shared_memory_byte_size"].int64_param = byte_size
        if offset != 0:
            self._output.parameters["shared_memory_offset"].int64_param = offset
        self._mut_epoch += 1
        return self

    def unset_shared_memory(self):
        self._output.parameters.pop("shared_memory_region", None)
        self._output.parameters.pop("shared_memory_byte_size", None)
        self._output.parameters.pop("shared_memory_offset", None)
        self._mut_epoch += 1
        return self

    def _get_tensor_pb(self) -> pb.ModelInferRequest.InferRequestedOutputTensor:
        return self._output
