"""Auth plugins for the gRPC client (reference ``tritonclient/grpc/auth``)."""

from ..._auth import BasicAuth

__all__ = ["BasicAuth"]
