"""gRPC client for the v2 inference protocol.

Mirrors the reference's ``tritonclient.grpc`` package surface, including the
``service_pb2`` module aliases used by advanced callers."""

from .._auth import BasicAuth  # noqa: F401 (re-export parity)
from ..protocol import inference_pb2 as service_pb2
from ..protocol import inference_pb2 as model_config_pb2
from ._client import (
    CallContext,
    InferAsyncRequest,
    InferenceServerClient,
    KeepAliveOptions,
    PreparedRequest,
)
from ._infer_input import InferInput
from ._infer_result import InferResult
from ._requested_output import InferRequestedOutput

__all__ = [
    "InferenceServerClient",
    "InferAsyncRequest",
    "CallContext",
    "KeepAliveOptions",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "PreparedRequest",
    "service_pb2",
    "model_config_pb2",
]
