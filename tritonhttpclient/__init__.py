"""Deprecated flat-layout alias (reference parity: tritonhttpclient/
re-exports the packaged layout with a DeprecationWarning)."""

import warnings

warnings.warn(
    "tritonhttpclient is deprecated; use tritonclient.http or "
    "triton_client_tpu.http",
    DeprecationWarning,
    stacklevel=2,
)

from triton_client_tpu.http import *  # noqa: E402,F401,F403
from triton_client_tpu.http import InferenceServerClient, InferInput, InferRequestedOutput  # noqa: E402,F401
from triton_client_tpu.utils import *  # noqa: E402,F401,F403
