"""Headline benchmark: client→server infer throughput on the real chip.

Runs the in-process serving harness (HTTP + gRPC frontends over the jax
`simple` sum/diff model — BASELINE config #1) and drives it with the sync
gRPC client at concurrency, perf_analyzer style.  Prints ONE JSON line:
``{"metric", "value", "unit", "vs_baseline", ...extras}``.

The reference publishes no numbers (SURVEY.md §6), so ``vs_baseline`` is
relative to the first recorded round (1.0 when no prior record exists).
"""

from __future__ import annotations

import json
import sys
import threading
import time

import numpy as np


def main() -> int:
    from triton_client_tpu.grpc import InferenceServerClient, InferInput
    from triton_client_tpu.models import zoo
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness

    registry = ModelRegistry()
    zoo.register_all(registry)
    harness = ServerHarness(registry)
    harness.start()

    url = f"127.0.0.1:{harness.grpc_port}"
    concurrency = 8

    def simple_inputs():
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        b = np.ones((1, 16), dtype=np.int32)
        i0 = InferInput("INPUT0", [1, 16], "INT32")
        i0.set_data_from_numpy(a)
        i1 = InferInput("INPUT1", [1, 16], "INT32")
        i1.set_data_from_numpy(b)
        return [i0, i1]

    def dense_inputs():
        x = np.random.default_rng(0).normal(size=(1, 512)).astype(np.float32)
        i = InferInput("INPUT", [1, 512], "FP32")
        i.set_data_from_numpy(x)
        return [i]

    def sweep(model_name, inputs_fn, warmup_s=2.0, measure_s=5.0):
        """perf_analyzer-style fixed-concurrency closed-loop sweep."""
        latencies: list = []
        counts = [0] * concurrency
        errors: list = []
        stop = threading.Event()
        start_measuring = threading.Event()

        def worker(idx: int):
            try:
                client = InferenceServerClient(url)
                inputs = inputs_fn()
                local_lat = []
                n = 0
                while not stop.is_set():
                    t0 = time.perf_counter()
                    client.infer(model_name, inputs)
                    dt = time.perf_counter() - t0
                    if start_measuring.is_set():
                        local_lat.append(dt)
                        n += 1
                counts[idx] = n
                latencies.append(local_lat)
                client.close()
            except Exception as e:  # surface worker failures in the output
                errors.append(f"worker {idx}: {e}")

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(concurrency)]
        for t in threads:
            t.start()
        time.sleep(warmup_s)
        start_measuring.set()
        t0 = time.perf_counter()
        time.sleep(measure_s)
        stop.set()
        elapsed = time.perf_counter() - t0
        for t in threads:
            t.join(timeout=10)
        total = sum(counts)
        chunks = [np.asarray(l) for l in latencies if l]
        lat = np.sort(np.concatenate(chunks)) if chunks else np.empty((0,))
        return {
            "infer_per_sec": round(total / elapsed, 2),
            "p50_ms": round(float(np.percentile(lat, 50) * 1e3), 3) if lat.size else None,
            "p99_ms": round(float(np.percentile(lat, 99) * 1e3), 3) if lat.size else None,
            "errors": errors,
            "total": total,
        }

    simple_res = sweep("simple", simple_inputs)
    dense_res = sweep("dense_tpu", dense_inputs, warmup_s=4.0)
    harness.stop()

    errors = simple_res["errors"] + dense_res["errors"]
    out = {
        "metric": "grpc_infer_throughput_simple_c8",
        "value": simple_res["infer_per_sec"],
        "unit": "infer/sec",
        "vs_baseline": 1.0,
        "p50_ms": simple_res["p50_ms"],
        "p99_ms": simple_res["p99_ms"],
        "tpu_batched_infer_per_sec": dense_res["infer_per_sec"],
        "tpu_batched_p50_ms": dense_res["p50_ms"],
        "tpu_batched_p99_ms": dense_res["p99_ms"],
        "concurrency": concurrency,
    }
    if errors:
        out["errors"] = errors[:4]
    print(json.dumps(out))
    return 0 if simple_res["total"] and dense_res["total"] and not errors else 1


if __name__ == "__main__":
    sys.exit(main())
