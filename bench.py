"""Headline benchmark: client→server infer throughput on the real chip.

Runs the in-process serving harness (HTTP + gRPC frontends over the jax
`simple` sum/diff model — BASELINE config #1) and drives it with the sync
gRPC client at concurrency, perf_analyzer style.  Also sweeps the TPU-resident
``dense_tpu`` model (BASELINE config #4 dynamic-batching contract) at higher
concurrency so batches coalesce.

Prints ONE JSON line: ``{"metric", "value", "unit", "vs_baseline", ...}``.

The reference publishes no numbers (SURVEY.md §6), so ``vs_baseline`` compares
the headline metric against the earliest recorded round (``BENCH_r*.json``
written by the driver; 1.0 when none exists).

Interpreting the TPU numbers: on this bench host the single chip is reached
through a tunnel whose device round trip is ~100 ms (reported here as
``tpu_rtt_floor_ms``, measured as a blocking device_put+readback).  Per-request
p50 on a synchronous closed loop is floored by that RTT no matter how fast the
server is; the honest health signals are (a) p50 staying near the floor (server
overhead ≈ p50 − floor) and (b) throughput scaling past 1/RTT via dynamic
batching + pipelined dispatch.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import threading
import time

import numpy as np


def _timed_infer(client, model, inputs) -> float:
    t0 = time.perf_counter()
    client.infer(model, inputs)
    return time.perf_counter() - t0


def _client_telemetry_summary() -> list:
    """Compact snapshot of the process-wide client telemetry registry:
    one row per (protocol, method, model) with counts and quantiles."""
    from triton_client_tpu._telemetry import telemetry

    rows = []
    for s in telemetry().snapshot()["requests"]:
        rows.append({
            "key": f"{s['protocol']}/{s['method']}/{s['model']}",
            "success": s["success"],
            "failure": s["failure"],
            "p50_us": (round(s["p50_us"], 1)
                       if s["p50_us"] is not None else None),
            "p99_us": (round(s["p99_us"], 1)
                       if s["p99_us"] is not None else None),
        })
    return rows


def _previous_baseline() -> float | None:
    """Headline value from the earliest recorded round (driver-written
    BENCH_r{N}.json files at the repo root)."""
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed") or {}
        value = parsed.get("value")
        if isinstance(value, (int, float)) and value > 0:
            rounds.append((int(m.group(1)), float(value)))
    if not rounds:
        return None
    return min(rounds)[1]


def _measure_generation(harness) -> dict:
    """LLM serving leg: server-side generation over the generate extension
    with weight-only int8 (BASELINE row 10).  TPU-only — the point is the
    on-device decode rate, meaningless on CPU.  The quant env is set before
    the llama weights first initialize (no earlier leg touches them)."""
    import jax

    if jax.default_backend() != "tpu":
        return {}
    from triton_client_tpu.genai_perf import profile_generate

    saved_quant = os.environ.get("TRITON_TPU_QUANT")
    os.environ["TRITON_TPU_QUANT"] = "int8"
    http_url = f"127.0.0.1:{harness.http_port}"
    try:
        # warm pass compiles prefill AND the decode step (2-token run);
        # the decode stack reads the quant env here (first generate call)
        profile_generate(http_url, "llama_generate", concurrency=1,
                         output_tokens=2, num_requests=1,
                         stream_timeout=1200.0)
        rep = profile_generate(http_url, "llama_generate", concurrency=8,
                               output_tokens=24, num_requests=8,
                               stream_timeout=1200.0)
    except Exception as e:  # noqa: BLE001 — bench keeps going without it
        return {"gen_error": str(e)[:120]}
    finally:
        # restore: every _LazyTransformer honors the global quant env now,
        # so leaking int8 would silently quantize any later-initialized
        # model while its leg reports a bf16 label
        if saved_quant is None:
            os.environ.pop("TRITON_TPU_QUANT", None)
        else:
            os.environ["TRITON_TPU_QUANT"] = saved_quant
    if rep["errors"]:
        return {"gen_error": str(rep.get("first_error"))[:120]}
    return {
        "gen_int8_tok_per_sec_c8": rep["output_token_throughput_per_sec"],
        "gen_int8_ttft_p50_ms": round(
            rep["time_to_first_token_ms"].get("p50", 0.0), 1),
        # the streaming-path headline ITL metrics ROADMAP item 2 calls
        # for, off the same generate_stream (SSE) leg as the TTFT above.
        # p50 uses the de-burst steady cadence (genai_perf's itl_steady:
        # prefetched readbacks land in client-side bursts, so the raw-gap
        # p50 under-reads); p99 stays the raw gap — the tail IS the burst
        # stall a user perceives
        "gen_stream_itl_p50": round(
            rep["itl_steady_ms"].get("p50", 0.0), 2),
        "gen_stream_itl_p99": round(
            rep["inter_token_latency_ms"].get("p99", 0.0), 2),
    }


def _measure_null_rpc(url: str, concurrency: int = 8,
                      measure_s: float = 2.0,
                      protocol: str = "grpc") -> float:
    """Drift control: closed-loop no-compute RPC rate (is_server_live) at
    the headline concurrency.  The headline simple-c8 number is host-CPU
    bound, so round-over-round 'regressions' are often host drift — this
    floor, measured in the SAME session, lets `vs_baseline` be read against
    a null-RPC normalization instead of re-arguing the A/B by hand."""
    if protocol == "grpc":
        from triton_client_tpu.grpc import InferenceServerClient
    else:
        from triton_client_tpu.http import InferenceServerClient

    counts = [0] * concurrency
    stop = threading.Event()

    def worker(idx):
        n = 0
        try:
            with InferenceServerClient(url) as c:
                while not stop.is_set():
                    c.is_server_live()
                    n += 1
        except Exception:  # noqa: BLE001 — control leg must not fail bench
            pass
        finally:
            counts[idx] = n  # a mid-loop error must not deflate the floor

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(measure_s)
    stop.set()
    elapsed = time.perf_counter() - t0
    for t in threads:
        t.join(timeout=10)
    return round(sum(counts) / elapsed, 1)


def _measure_client_wire_breakdown(harness, headline_value,
                                   null_rpc_grpc) -> dict:
    """Satellite of the wire fast path: decompose per-call client cost so
    the template/batch win is attributable, not asserted.

    Three layers, A/B'd with each toggled:

    * **build vs stamp** (serialize layer): slow-path request construction
      vs template re-stamp, µs/call per protocol, in-process (no server).
    * **wrap** (telemetry+resilience layer): one retry-envelope entry +
      telemetry record per call vs ONE per 64-request flight (the
      ``infer_many`` amortization) — ``wrap_reduction`` is the acceptance
      ratio (target >= 2x vs the r05 ~1.7 µs/call cost).
    * **transport**: the same-session null-RPC closed loop per protocol,
      plus a short http simple-c8 window so ``value_per_null_rpc`` exists
      per protocol (grpc's rides the headline).
    """
    import triton_client_tpu.grpc as grpcclient
    import triton_client_tpu.http as httpclient
    from triton_client_tpu._resilience import RetryPolicy, call_with_retry
    from triton_client_tpu.grpc._template import \
        RequestTemplate as GrpcTemplate
    from triton_client_tpu.grpc._utils import get_inference_request
    from triton_client_tpu.http._template import \
        RequestTemplate as HttpTemplate
    from triton_client_tpu.http._utils import get_inference_request_body

    def us_per(fn, n):
        fn()  # warm (allocator, caches)
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n * 1e6

    N = 2000
    out: dict = {}
    try:
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        b = np.ones((1, 16), dtype=np.int32)

        def http_inputs():
            i0 = httpclient.InferInput("INPUT0", [1, 16], "INT32")
            i0.set_data_from_numpy(a)
            i1 = httpclient.InferInput("INPUT1", [1, 16], "INT32")
            i1.set_data_from_numpy(b)
            return [i0, i1]

        def grpc_inputs():
            i0 = grpcclient.InferInput("INPUT0", [1, 16], "INT32")
            i0.set_data_from_numpy(a)
            i1 = grpcclient.InferInput("INPUT1", [1, 16], "INT32")
            i1.set_data_from_numpy(b)
            return [i0, i1]

        hi, gi = http_inputs(), grpc_inputs()
        http_tpl = HttpTemplate("simple", hi)
        grpc_tpl = GrpcTemplate("simple", gi)
        http_build = us_per(lambda: get_inference_request_body(
            hi, "rid-0123456789", None, 0, False, False, 0, None, None), N)
        http_stamp = us_per(lambda: http_tpl.stamp("rid-0123456789"), N)
        grpc_build = us_per(lambda: get_inference_request(
            "simple", gi, "", "rid-0123456789", None, 0, False, False, 0,
            None, None), N)
        grpc_stamp = us_per(lambda: grpc_tpl.stamp("rid-0123456789"), N)

        # wrap layer (shared by both protocols): retry envelope +
        # telemetry, entered per call vs per 64-request flight.  A
        # THROWAWAY registry, not the process singleton — thousands of
        # synthetic 1µs observations must not surface in the bench
        # record's client_telemetry section as real grpc/infer traffic
        from triton_client_tpu._telemetry import ClientTelemetry

        tel = ClientTelemetry()
        policy = RetryPolicy(max_attempts=3, retry_infer=True)
        meta = ("wire_breakdown", "grpc", "infer", "")

        def per_call():
            call_with_retry(policy, lambda _r, _a: None, method="infer",
                            retry_meta=meta)
            tel.record_request("wire_breakdown", "grpc", "infer", 1e-6,
                               ok=True)

        flight_outcomes = [(True, 1e-6, 0, 0, "")] * 64

        def per_flight():
            call_with_retry(policy, lambda _r, _a: None, method="infer",
                            retry_meta=meta)
            tel.record_request_batch("wire_breakdown", "grpc", "infer",
                                     flight_outcomes)

        wrap_us = us_per(per_call, N)
        batch_wrap_us = us_per(per_flight, max(N // 64, 50)) / 64.0

        # transport floor + per-protocol normalization
        http_url = f"127.0.0.1:{harness.http_port}"
        null_http = _measure_null_rpc(http_url, measure_s=1.5,
                                      protocol="http")
        from triton_client_tpu.perf_analyzer import (_make_data,
                                                     _resolve_model,
                                                     run_level)
        with httpclient.InferenceServerClient(http_url) as meta_client:
            pa_inputs, pa_outputs, pa_max_batch = _resolve_model(
                meta_client, "http", "simple", "")
        arrays = _make_data(pa_inputs, {}, 1, pa_max_batch,
                            np.random.default_rng(0))
        http_run = run_level("http", http_url, "simple", "", 8, arrays,
                             pa_outputs, "none", 1 << 20, 2.0, warmup_s=0.5)
        out = {
            "wrap_us_per_call": round(wrap_us, 3),
            "wrap_us_per_request_batched": round(batch_wrap_us, 3),
            "wrap_reduction": (round(wrap_us / batch_wrap_us, 2)
                               if batch_wrap_us else None),
            "grpc": {
                "build_us": round(grpc_build, 3),
                "stamp_us": round(grpc_stamp, 3),
                "serialize_speedup": (round(grpc_build / grpc_stamp, 2)
                                      if grpc_stamp else None),
                "null_rpc_per_sec_c8": null_rpc_grpc,
                "infer_per_sec_c8": headline_value,
                "value_per_null_rpc": (
                    round(headline_value / null_rpc_grpc, 4)
                    if null_rpc_grpc else None),
            },
            "http": {
                "build_us": round(http_build, 3),
                "stamp_us": round(http_stamp, 3),
                "serialize_speedup": (round(http_build / http_stamp, 2)
                                      if http_stamp else None),
                "null_rpc_per_sec_c8": null_http,
                "infer_per_sec_c8": round(http_run["throughput"], 2),
                "value_per_null_rpc": (
                    round(http_run["throughput"] / null_http, 4)
                    if null_http else None),
            },
        }
        if http_run["errors"]:
            out["http"]["errors"] = http_run["errors"]
            out["http"]["first_error"] = http_run.get("first_error")
    except Exception as e:  # noqa: BLE001 — breakdown leg never kills bench
        return {"wire_breakdown_error": str(e)[:120]}
    return {"client_wire_breakdown": out}


def _mp_null_worker(url, protocol, secs, conc, barrier, q):
    """One CLIENT process of the multi-process null-RPC closed loop.
    Module-level (spawn-picklable); measurement window starts only after
    every process connected (barrier), so spawn/import time never
    deflates the rate."""
    import threading

    if protocol == "grpc":
        from triton_client_tpu.grpc import InferenceServerClient
    else:
        from triton_client_tpu.http import InferenceServerClient
    try:
        clients = [InferenceServerClient(url) for _ in range(conc)]
        for c in clients:
            c.is_server_live()  # connect + warm
        counts = [0] * conc
        stop = threading.Event()

        def w(i):
            c = clients[i]
            n = 0
            while not stop.is_set():
                c.is_server_live()
                n += 1
            counts[i] = n

        barrier.wait(timeout=120)
        threads = [threading.Thread(target=w, args=(i,), daemon=True)
                   for i in range(conc)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(secs)
        stop.set()
        elapsed = time.perf_counter() - t0
        for t in threads:
            t.join(timeout=5)
        q.put(sum(counts) / elapsed)
    except Exception:  # noqa: BLE001 — a dead client proc must not hang join
        q.put(0.0)


def _mp_infer_worker(url, secs, conc, barrier, q):
    """One CLIENT process of the multi-process gRPC infer closed loop
    (template-stamped prepare/infer on `simple`, the headline shape)."""
    import threading

    import triton_client_tpu.grpc as grpcclient
    try:
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        b = np.ones((1, 16), dtype=np.int32)
        clients, preps = [], []
        for _ in range(conc):
            c = grpcclient.InferenceServerClient(url)
            i0 = grpcclient.InferInput("INPUT0", [1, 16], "INT32")
            i0.set_data_from_numpy(a)
            i1 = grpcclient.InferInput("INPUT1", [1, 16], "INT32")
            i1.set_data_from_numpy(b)
            p = c.prepare("simple", [i0, i1])
            p.infer()  # warm (connect + first jit)
            clients.append(c)
            preps.append(p)
        counts = [0] * conc
        stop = threading.Event()

        def w(i):
            p = preps[i]
            n = 0
            while not stop.is_set():
                p.infer()
                n += 1
            counts[i] = n

        barrier.wait(timeout=120)
        threads = [threading.Thread(target=w, args=(i,), daemon=True)
                   for i in range(conc)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(secs)
        stop.set()
        elapsed = time.perf_counter() - t0
        for t in threads:
            t.join(timeout=5)
        q.put(sum(counts) / elapsed)
    except Exception:  # noqa: BLE001
        q.put(0.0)


def _mp_measure(worker, url, nproc, conc, secs=2.5, protocol=None) -> float:
    """Run ``nproc`` client processes of ``worker`` against ``url`` and
    sum their closed-loop rates.  Multi-PROCESS clients, deliberately:
    the thing under test is the SERVER'S process ceiling, and a single
    GIL-bound client process caps out around the single-server rate —
    it would mask exactly the scaling this leg exists to measure."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    barrier = ctx.Barrier(nproc)
    args = ((url, protocol, secs, conc, barrier, q) if protocol
            else (url, secs, conc, barrier, q))
    procs = [ctx.Process(target=worker, args=args) for _ in range(nproc)]
    for p in procs:
        p.start()
    try:
        total = sum(q.get(timeout=180) for _ in procs)
    finally:
        for p in procs:
            p.join(timeout=15)
            if p.is_alive():
                p.kill()
    return total


def _measure_server_encode_breakdown() -> dict:
    """Serialize-vs-stamp µs for the SERVER response path (the mirror of
    the client build-vs-stamp numbers): slow-path encode vs template
    stamp, per protocol, in-process."""
    from triton_client_tpu.server import wire
    from triton_client_tpu.server.types import (InferResponse, OutputTensor,
                                                InferRequest, RequestedOutput)

    def us_per(fn, n=3000):
        """Best-of-3 windows: single-digit-µs calls on a shared bench
        host need the min, not one arbitrary window."""
        fn()
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            best = min(best, (time.perf_counter() - t0) / n * 1e6)
        return best

    data = np.arange(16, dtype=np.int32).reshape(1, 16)
    resp = InferResponse("simple", "1", id="rid-0123456789", outputs=[
        OutputTensor("OUTPUT0", "INT32", (1, 16), data),
        OutputTensor("OUTPUT1", "INT32", (1, 16), data),
    ])
    resp.parameters["triton_request_id"] = "rid-0123456789"
    req = InferRequest(model_name="simple", outputs=[
        RequestedOutput("OUTPUT0"), RequestedOutput("OUTPUT1")])
    requested = {o.name: o for o in req.outputs}
    # one cache per protocol, like the server's (a shared cache would
    # cross-match foreign templates and poison the measurement)
    http_cache = wire.ResponseTemplateCache()
    grpc_cache = wire.ResponseTemplateCache()
    wire.encode_http_response(resp, requested, True, cache=http_cache,
                              generation=1)  # compile once
    http_encode = us_per(lambda: wire.encode_http_response(
        resp, requested, True))
    http_stamp = us_per(lambda: wire.encode_http_response(
        resp, requested, True, cache=http_cache, generation=1))
    wire.encode_pb_response(resp, cache=grpc_cache, generation=1)
    grpc_encode = us_per(lambda: wire.build_pb_response(resp))
    grpc_stamp = us_per(lambda: wire.encode_pb_response(
        resp, cache=grpc_cache, generation=1))
    return {
        "http": {
            "encode_us": round(http_encode, 3),
            "stamp_us": round(http_stamp, 3),
            "serialize_speedup": (round(http_encode / http_stamp, 2)
                                  if http_stamp else None),
        },
        "grpc": {
            "encode_us": round(grpc_encode, 3),
            "stamp_us": round(grpc_stamp, 3),
            "serialize_speedup": (round(grpc_encode / grpc_stamp, 2)
                                  if grpc_stamp else None),
        },
    }


def _measure_server_wire_breakdown() -> dict:
    """Satellite of the SERVER wire fast path (ISSUE 11): serialize-vs-
    stamp µs per protocol, the null-RPC floor per protocol, and single-
    vs multi-process (--frontends N, SO_REUSEPORT) scaling of both the
    floor and the c=8 template-stamped infer throughput.

    Spawns real CLI servers (the production multi-process entrypoint) on
    JAX_PLATFORMS=cpu: the null-RPC and `simple` legs are host-CPU work
    by construction (the thing under test is the Python frontend data
    plane), and a TPU bench host must not have N workers fight over the
    chip."""
    import os as _os
    import signal as _signal
    import subprocess
    import urllib.request

    from triton_client_tpu.server.testing import free_port

    nfront = max(2, min(4, (_os.cpu_count() or 4) // 4))
    repo_root = os.path.dirname(os.path.abspath(__file__))

    def run_config(frontends: int) -> dict:
        http_port, grpc_port = free_port(), free_port()
        env = dict(_os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "triton_client_tpu.server", "--zoo",
             "--host", "127.0.0.1", "--http-port", str(http_port),
             "--grpc-port", str(grpc_port), "--metrics-port", "0",
             "--frontends", str(frontends), "--drain-timeout", "2"],
            cwd=repo_root, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        try:
            deadline = time.time() + 120
            ready = False
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{http_port}/v2/health/ready",
                            timeout=2) as r:
                        if r.status == 200:
                            ready = True
                            break
                except Exception:  # noqa: BLE001
                    time.sleep(0.5)
            if not ready:
                return {"error": f"server (frontends={frontends}) not ready"}
            time.sleep(2.0)  # post-warmup settle: registration churn off
            grpc_url = f"127.0.0.1:{grpc_port}"
            http_url = f"127.0.0.1:{http_port}"

            def best_of(worker, url, protocol=None, runs=2):
                # best-of-N windows, like the headline sweep: host-side
                # contention on a shared box under-reports single windows
                return round(max(_mp_measure(worker, url, 4, 2,
                                             protocol=protocol)
                                 for _ in range(runs)), 1)

            # c=8 across 4 client processes (2 connections each)
            return {
                "null_rpc_grpc_c8": best_of(_mp_null_worker, grpc_url,
                                            protocol="grpc"),
                "null_rpc_http_c8": best_of(_mp_null_worker, http_url,
                                            protocol="http"),
                "grpc_infer_c8": best_of(_mp_infer_worker, grpc_url),
            }
        finally:
            if proc.poll() is None:
                proc.send_signal(_signal.SIGTERM)
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
    try:
        # the in-process encode-vs-stamp leg sits INSIDE the never-kill-
        # bench envelope too: a template-compile surprise must degrade to
        # the error field, not abort the whole record
        out: dict = dict(_measure_server_encode_breakdown())
        out["frontends"] = nfront
        single = run_config(1)
        multi = run_config(nfront)
        out["single_process"] = single
        out["multi_process"] = multi
        if "error" not in single and "error" not in multi:
            base = single["null_rpc_grpc_c8"]
            out["null_rpc_scaling_c8"] = (
                round(multi["null_rpc_grpc_c8"] / base, 2) if base else None)
            bound = multi["null_rpc_grpc_c8"]
            out["value_per_null_rpc_multiproc"] = (
                round(multi["grpc_infer_c8"] / bound, 4) if bound else None)
    except Exception as e:  # noqa: BLE001 — breakdown leg never kills bench
        return {"server_wire_breakdown_error": str(e)[:160]}
    return {"server_wire_breakdown": out}


def _measure_bert_mfu(harness) -> dict:
    """BERT-large serving efficiency (BASELINE row 4): streaming gRPC with
    WIRE outputs at RTT-covering concurrency, reported as MFU so the
    flagship efficiency number is driver-captured, not builder-run-only.
    Wire (not xla-shm) because MFU must count device-synchronous
    completions — see the inline comment and benchmarks/BERT_PROFILE.md."""
    import jax

    if jax.default_backend() != "tpu":
        return {}
    import triton_client_tpu.http as httpclient
    from triton_client_tpu.models import language
    from triton_client_tpu.perf_analyzer import (_make_data, _resolve_model,
                                                 run_level)

    grpc_url = f"127.0.0.1:{harness.grpc_port}"
    try:
        # warm every batch bucket first: an XLA compile (tens of seconds)
        # inside a measured window would sink the sweep
        with httpclient.InferenceServerClient(
                f"127.0.0.1:{harness.http_port}",
                network_timeout=600.0) as warm:
            for b in (1, 2, 4, 8, 16, 32):
                x = np.zeros((b, language.BERT_SEQ_LEN), np.int32)
                inp = httpclient.InferInput(
                    "INPUT_IDS", list(x.shape), "INT32")
                inp.set_data_from_numpy(x)
                warm.infer("bert_large", [inp])
        from triton_client_tpu.grpc import InferenceServerClient

        meta = InferenceServerClient(grpc_url)
        inputs, outputs, max_batch = _resolve_model(
            meta, "grpc", "bert_large", "")
        meta.close()
        arrays = _make_data(inputs, {}, 1, max_batch,
                            np.random.default_rng(0))
        # WIRE outputs, deliberately: with xla-shm outputs the response
        # returns at dispatch time (zero-copy device-resident handoff), so
        # a closed loop measures dispatch rate with the device backlog
        # draining after the window — NOT compute (benchmarks/
        # BERT_PROFILE.md quantifies the ~2x inflation).  Wire outputs
        # ([384,2] f32, 3KB) force device-synchronous completion, which is
        # what an MFU number must count.
        best = None
        # levels cover the tunnel RTT (c >= device_rate x RTT) so the
        # closed loop measures the chip, not the link
        for level in (32, 96):
            res = run_level("grpc", grpc_url, "bert_large", "", level,
                            arrays, outputs, "none", 1 << 22, 4.0,
                            warmup_s=3.0, streaming=True)
            if res["errors"]:
                return {"bert_error": str(res.get("first_error"))[:120]}
            if best is None or res["throughput"] > best["throughput"]:
                best = res
                best_level = level
        mfu = language.serving_mfu(
            best["throughput"], language.BERT_LARGE, language.BERT_SEQ_LEN,
            head_cols=language.BERT_HEAD_COLS)
        return {
            "bert_infer_per_sec": round(best["throughput"], 1),
            "bert_mfu_pct": round(100.0 * mfu, 1),
            "bert_best_concurrency": best_level,
        }
    except Exception as e:  # noqa: BLE001 — bench keeps going without it
        return {"bert_error": str(e)[:120]}


def _measure_generation_ab() -> dict:
    """Same-precision batched-vs-independent generation A/B in ONE session
    (both bf16, c=8 and c=16), plus the bucketed c=64 capacity point —
    settles whether continuous batching wins without cross-session RTT
    caveats.  Each mode runs its own harness AFTER the previous stopped
    (decode mode is fixed at registration; harnesses must never nest)."""
    import jax

    if jax.default_backend() != "tpu":
        return {}
    from triton_client_tpu.genai_perf import profile_generate
    from triton_client_tpu.models import language, zoo
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness

    keys = ("TRITON_TPU_DECODE_MODE", "TRITON_TPU_DECODE_SLOTS",
            "TRITON_TPU_PREFILL_CHUNK", "TRITON_TPU_DECODE_BUCKETS",
            "TRITON_TPU_QUANT", "TRITON_TPU_KV_QUANT")
    saved = {k: os.environ.get(k) for k in keys}
    out: dict = {}

    def run_mode(mode, tag, env, levels):
        # collect BEFORE building this mode's zoo: the previous mode's
        # registry (llama weights + caches) died with its frame, but cycle
        # garbage only frees on a collect — without it the chip still
        # holds the previous arrays when the new harness allocates
        import gc

        gc.collect()
        for k in keys:
            os.environ.pop(k, None)
        os.environ["TRITON_TPU_DECODE_MODE"] = mode
        os.environ.update(env)
        try:
            registry = ModelRegistry()
            zoo.register_all(registry)
            with ServerHarness(registry) as h:
                url = f"127.0.0.1:{h.http_port}"
                profile_generate(url, "llama_generate", concurrency=1,
                                 output_tokens=2, num_requests=1,
                                 stream_timeout=1800.0)  # compile warm
                for conc, n_req in levels:
                    rep = profile_generate(
                        url, "llama_generate", concurrency=conc,
                        output_tokens=24, num_requests=n_req,
                        stream_timeout=1800.0)
                    key = f"gen_ab_{tag}_c{conc}"
                    if rep["errors"]:
                        out[key + "_error"] = str(
                            rep.get("first_error"))[:120]
                    else:
                        out[key] = round(
                            rep["output_token_throughput_per_sec"], 1)
        except Exception as e:  # noqa: BLE001
            out[f"gen_ab_{tag}_error"] = str(e)[:120]

    try:
        run_mode("independent", "independent", {},
                 [(8, 16), (16, 32), (64, 64)])
        # flat 32-slot config for the like-for-like c8/c16 comparison (a
        # 64-wide step would tick 64 slots for 8 active ones)
        run_mode("batched", "batched", {
            "TRITON_TPU_PREFILL_CHUNK": "32",
            "TRITON_TPU_DECODE_SLOTS": "32",
        }, [(8, 16), (16, 32)])
        P = language.LLAMA_SEQ_LEN
        # bucketed capacity points (r5: same-cap POOLS — 8 independent
        # 32-slot buckets, so a tick only steps pools holding active work
        # and the step width stays 32 at any concurrency — plus int8 KV):
        # c=64 for the like-for-like row and c=256 for the capacity proof
        # (benchmarks/GEN_CAPACITY.json has the full pool-shape sweep:
        # one 256-wide bucket collapses to 26 tok/s, 8x32 pools hold
        # ~100-122 tok/s flat from c=64 through c=256)
        run_mode("batched", "bucketed", {
            "TRITON_TPU_PREFILL_CHUNK": "32",
            "TRITON_TPU_DECODE_BUCKETS": ",".join([f"32x{P + 32}"] * 8),
            "TRITON_TPU_KV_QUANT": "int8",
        }, [(64, 64), (256, 256)])
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    # continuity with r3's field name: the batched c8 point
    if "gen_ab_batched_c8" in out:
        out["gen_batched_tok_per_sec_c8"] = out["gen_ab_batched_c8"]
    return out


def _measure_gen_tick_breakdown() -> dict:
    """Decode-tick fast-path microbench (ISSUE 12) — CPU-runnable on the
    tiny preset: per-token host overhead, control uploads and fused
    syncs per token, and the steps-per-dispatch A/B at T in {1, 4, 8}
    (TRITON_TPU_DECODE_STEPS).

    The sync/upload columns come from the nv_tpu_tick_* counters the
    worker records per dispatch, so they are host-independent: on a
    CPU-only host the tok/s absolutes mean little (the tiny model is
    compute-cheap and the chip is a CPU), but uploads-per-token == 0 and
    syncs-per-token == 1/T hold wherever the code runs.  ``host_us_per_tok``
    is the worker's tick-assembly time (job collection to dispatch)
    amortized per token — the host-overhead axis the fused tick shrinks."""
    import gc
    import threading
    import time as _time

    import jax

    from triton_client_tpu.models import language
    from triton_client_tpu.server.device_stats import DeviceStatsCollector

    keys = ("TRITON_TPU_DECODE_MODE", "TRITON_TPU_DECODE_SLOTS",
            "TRITON_TPU_DECODE_STEPS", "TRITON_TPU_DECODE_BUCKETS",
            "TRITON_TPU_PREFILL_CHUNK", "TRITON_TPU_KV_QUANT")
    saved = {k: os.environ.get(k) for k in keys}
    CONC, N_TOK = 4, 24
    out: dict = {"cpu_only": jax.default_backend() != "tpu"}

    window = np.zeros((1, language.LLAMA_SEQ_LEN), np.int32)
    b = np.frombuffer(b"gen tick breakdown probe", np.uint8)
    window[0, language.LLAMA_SEQ_LEN - b.size:] = b

    def run_steps(T: int) -> dict:
        gc.collect()
        for k in keys:
            os.environ.pop(k, None)
        os.environ["TRITON_TPU_DECODE_MODE"] = "batched"
        os.environ["TRITON_TPU_DECODE_SLOTS"] = str(CONC)
        os.environ["TRITON_TPU_DECODE_STEPS"] = str(T)
        from triton_client_tpu.models.decode import DecodeModel

        dec = DecodeModel(name=f"llama_decode_tickbench_t{T}")
        ds = DeviceStatsCollector()
        dec.attach_device_stats(ds)
        try:
            # warm: compile prefill + the fused T-step kernel off-clock
            for s in [dec.submit_generation(window.copy(), 2)
                      for _ in range(CONC)]:
                while True:
                    item = s.get(timeout=600)
                    if item is None:
                        break
                    if isinstance(item, Exception):
                        # surface the real failure (a compile error here
                        # would otherwise read as a token and stall the
                        # loop 600s waiting for a None that never comes)
                        raise item
            ds.reset()
            counts: list = []
            stream_errors: list = []
            t0 = _time.monotonic()

            def drain(sink):
                c = 0
                while True:
                    item = sink.get(timeout=600)
                    if item is None:
                        break
                    if isinstance(item, Exception):
                        # record, don't raise: a daemon-thread traceback
                        # is exactly the stderr noise this bench round
                        # eliminates, and a silent short count would make
                        # a partial failure look like a clean result
                        stream_errors.append(str(item)[:120])
                        break
                    c += 1
                counts.append(c)

            sinks = [dec.submit_generation(window.copy(), N_TOK)
                     for _ in range(CONC)]
            ts = [threading.Thread(target=drain, args=(s,), daemon=True)
                  for s in sinks]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=600)
            wall = _time.monotonic() - t0
            snap = ds.snapshot()
            entry = {}
            # flat-slot config => exactly one bucket entry; the sum is a
            # no-op but keeps the fold shape-stable
            for bucket in snap["ticks"].get(dec.model.name, {}).values():
                for k2, v in bucket.items():
                    if isinstance(v, (int, float)) and v is not None:
                        entry[k2] = entry.get(k2, 0) + v
            n = sum(counts)
            ticks = entry.get("ticks", 0)
            if stream_errors:
                return {"tokens": n, "stream_errors": stream_errors[:4]}
            return {
                "tokens": n,
                "tok_per_s": round(n / wall, 1) if wall else None,
                "dispatches": ticks,
                "steps_per_dispatch": (round(entry.get("steps", 0) / ticks, 2)
                                       if ticks else None),
                # fused-dispatch D2H syncs and H2D control uploads, per
                # token — the host-independent reductions
                "syncs_per_tok": (round(entry.get("syncs", 0) / n, 3)
                                  if n else None),
                "uploads_per_tok": (round(entry.get("uploads", 0) / n, 3)
                                    if n else None),
                "host_us_per_tok": (
                    round(entry.get("avg_assembly_us", 0.0)
                          * ticks / n, 1) if n else None),
            }
        finally:
            dec._shutdown()

    try:
        for T in (1, 4, 8):
            out[f"steps_{T}"] = run_steps(T)
        t1 = out["steps_1"].get("host_us_per_tok")
        t8 = out["steps_8"].get("host_us_per_tok")
        if t1 and t8:
            out["host_overhead_reduction_t8_vs_t1"] = round(t1 / t8, 2)
    except Exception as e:  # noqa: BLE001 — bench keeps going without it
        out["gen_tick_breakdown_error"] = str(e)[:120]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def _measure_gen_trace_overhead() -> dict:
    """Streaming-trace overhead A/B (ISSUE 15) — CPU-runnable on the tiny
    preset: ``generate_stream`` token throughput with span tracing OFF vs
    sampled ON at trace_rate=1 (EVERY stream traced — the worst case;
    production sampling defaults 1000x sparser), plus the per-token
    upload/sync counters proving the decode fast path is untouched: all
    stream-trace recording is host-side at admission/resolve boundaries,
    so a traced tick pays the same 1/T fused syncs and zero control
    uploads as an untraced one."""
    import gc
    import tempfile
    import urllib.request

    from triton_client_tpu.genai_perf import profile_generate
    from triton_client_tpu.models import zoo
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness

    keys = ("TRITON_TPU_DECODE_MODE", "TRITON_TPU_DECODE_SLOTS",
            "TRITON_TPU_PREFILL_CHUNK", "TRITON_TPU_DECODE_BUCKETS",
            "TRITON_TPU_KV_QUANT", "TRITON_TPU_DECODE_STEPS")
    saved = {k: os.environ.get(k) for k in keys}
    CONC, N_REQ, N_TOK = 4, 12, 24
    out: dict = {"trace_rate": 1, "concurrency": CONC,
                 "output_tokens": N_TOK}
    gc.collect()
    for k in keys:
        os.environ.pop(k, None)
    os.environ["TRITON_TPU_DECODE_MODE"] = "batched"
    os.environ["TRITON_TPU_DECODE_SLOTS"] = str(CONC)
    try:
        registry = ModelRegistry()
        zoo.register_all(registry)
        with ServerHarness(registry) as h:
            url = f"127.0.0.1:{h.http_port}"
            # compile warm off-clock (prefill + fused tick kernels)
            profile_generate(url, "llama_generate", concurrency=1,
                             output_tokens=2, num_requests=1,
                             stream_timeout=1800.0)

            def set_trace(settings):
                req = urllib.request.Request(
                    f"http://{url}/v2/trace/setting",
                    data=json.dumps(settings).encode(),
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=30).read()

            def tick_counters():
                snap = json.loads(urllib.request.urlopen(
                    f"http://{url}/v2/debug/device_stats",
                    timeout=30).read())
                steps = syncs = uploads = 0
                for b in snap["ticks"].get("llama_decode", {}).values():
                    steps += b["steps"] or 0
                    syncs += b["syncs"] or 0
                    uploads += b["uploads"] or 0
                return steps, syncs, uploads

            def run_window(tag):
                h.core.device_stats.reset()
                rep = profile_generate(
                    url, "llama_generate", concurrency=CONC,
                    output_tokens=N_TOK, num_requests=N_REQ,
                    stream_timeout=1800.0)
                if rep["errors"]:
                    out[f"{tag}_error"] = str(
                        rep.get("first_error"))[:120]
                    return None
                steps, syncs, uploads = tick_counters()
                return {
                    "tok_per_s": round(
                        rep["output_token_throughput_per_sec"], 1),
                    # steps ~= decoded token positions; the regression
                    # counters the fused fast path is gated on
                    "syncs_per_tok": (round(syncs / steps, 3)
                                      if steps else None),
                    "uploads_per_tok": (round(uploads / steps, 3)
                                        if steps else None),
                }

            # INTERLEAVED best-of-3 per arm (off, traced, off, traced,
            # ...): back-to-back arms read host warm-up drift as a trace
            # delta — alternating windows expose both arms to the same
            # drift, and best-of soaks the remaining variance
            tf = os.path.join(tempfile.mkdtemp(prefix="gen_trace_bench_"),
                              "trace.jsonl")
            off = traced = None
            for _ in range(3):
                set_trace({"trace_level": ["OFF"]})
                w = run_window("off")
                if w and (off is None
                          or w["tok_per_s"] > off["tok_per_s"]):
                    off = w
                set_trace({"trace_file": [tf],
                           "trace_level": ["TIMESTAMPS"],
                           "trace_rate": ["1"]})
                w = run_window("traced")
                if w and (traced is None
                          or w["tok_per_s"] > traced["tok_per_s"]):
                    traced = w
            if off is not None:
                out["off"] = off
            if traced is not None:
                out["traced"] = traced
            if off and traced and off["tok_per_s"]:
                out["overhead_pct"] = round(
                    100.0 * (1.0 - traced["tok_per_s"] / off["tok_per_s"]),
                    1)
            if traced is not None:
                # count the traced window's records so the A/B provably
                # exercised the stream-emit path
                with open(tf) as f:
                    out["traced_records"] = sum(1 for l in f if l.strip())
    except Exception as e:  # noqa: BLE001 — bench keeps going without it
        out["gen_trace_overhead_error"] = str(e)[:120]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


class _StubOtlpCollector:
    """Loopback OTLP/HTTP sink for the journey A/B: counts the POSTed
    ResourceSpans batches and spans so the traced arm provably exported,
    without a collector dependency.  Only the first few bodies are fully
    parsed (well-formedness proof); the rest are counted by substring —
    a real collector parses OUT of process, and an in-process
    ``json.loads`` of a 100-span batch holds the GIL for milliseconds,
    which would bill collector CPU to the client/server under test."""

    def __init__(self):
        import http.server

        self.posts = 0
        self.spans = 0
        self.wellformed = 0
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                size = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(size)
                outer.posts += 1
                if outer.wellformed < 3:
                    try:
                        parsed = json.loads(body)
                        assert parsed["resourceSpans"][0]["scopeSpans"]
                        outer.wellformed += 1
                    except Exception:  # noqa: BLE001 — counted below anyway
                        pass
                outer.spans += body.count(b'"spanId"')
                self.send_response(200)
                self.end_headers()

            def log_message(self, *args):
                pass

        self._srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        self.endpoint = f"http://127.0.0.1:{self._srv.server_port}"

    def close(self):
        self._srv.shutdown()


def _measure_journey_trace_overhead() -> dict:
    """Journey-observability A/B (ISSUE 17): the same c=8 closed infer
    loop and the streaming generate loop with the WHOLE journey plane on
    — client attempt records (JSONL + OTLP export), retry-loop journey
    scopes, server span tracing at trace_rate=1 with replica identity,
    server OTLP export to a loopback stub collector — vs all tracing off.
    BOTH arms run under RetryPolicy(max_attempts=3), so the delta
    isolates the tracing/export cost, not the resilience wrapper (its
    own leg).  Interleaved best-of windows per arm; acceptance is <= 3%
    throughput with the usual single-host noise caveat (negative =
    noise)."""
    import gc
    import tempfile

    from triton_client_tpu._resilience import RetryPolicy
    from triton_client_tpu._telemetry import telemetry
    from triton_client_tpu.genai_perf import profile_generate
    from triton_client_tpu.http import InferenceServerClient, InferInput
    from triton_client_tpu.models import zoo
    from triton_client_tpu.perf_analyzer import (_make_data, _resolve_model,
                                                 run_level)
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness
    from triton_client_tpu.tools.trace_summary import (load_trace_files,
                                                       summarize,
                                                       trace_id_of)

    gc.collect()
    out: dict = {"concurrency": 8, "trace_rate": 1}
    collector = _StubOtlpCollector()
    tmp = tempfile.mkdtemp(prefix="journey_bench_")
    server_tf = os.path.join(tmp, "server.jsonl")
    client_tf = os.path.join(tmp, "client.jsonl")
    otlp_totals = {"ok": 0, "error": 0, "dropped": 0}

    def detach(h):
        """Tracing fully off: trace_level OFF, both exporters drained,
        detached, and their counters folded into the leg totals."""
        h.core.trace_settings["trace_level"] = ["OFF"]
        srv, h.core.tracer.otlp = h.core.tracer.otlp, None
        cli = telemetry().otlp_exporter
        telemetry().disable_tracing()
        telemetry().disable_otlp()
        for ex in (srv, cli):
            if ex is not None:
                ex.flush(10.0)
                for k, v in ex.counters().items():
                    otlp_totals[k] += v
                ex.shutdown()

    def attach(h):
        h.core.trace_settings.update({
            "trace_level": ["TIMESTAMPS"], "trace_file": [server_tf],
            "trace_rate": ["1"], "trace_count": ["-1"],
            "log_frequency": ["0"]})
        h.core.tracer.settings_updated()
        h.core.enable_otlp(collector.endpoint, replica=h.replica)
        telemetry().enable_tracing(client_tf)
        telemetry().enable_otlp(collector.endpoint)

    policy = RetryPolicy(max_attempts=3, retry_infer=True)
    try:
        registry = ModelRegistry()
        registry.register_model(zoo.make_simple())
        with ServerHarness(registry) as h:
            url = f"127.0.0.1:{h.http_port}"
            with InferenceServerClient(url) as warm:
                a = np.arange(16, dtype=np.int32).reshape(1, 16)
                i0 = InferInput("INPUT0", [1, 16], "INT32")
                i0.set_data_from_numpy(a)
                i1 = InferInput("INPUT1", [1, 16], "INT32")
                i1.set_data_from_numpy(a)
                warm.infer("simple", [i0, i1])
            meta = InferenceServerClient(url)
            pa_inputs, pa_outputs, pa_max_batch = _resolve_model(
                meta, "http", "simple", "")
            meta.close()
            arrays = _make_data(pa_inputs, {}, 1, pa_max_batch,
                                np.random.default_rng(0))

            def window():
                return run_level("http", url, "simple", "", 8, arrays,
                                 pa_outputs, "none", 1 << 20, 2.0,
                                 warmup_s=0.5, retry_policy=policy)

            off = traced = None
            for _ in range(3):
                detach(h)
                w = window()
                if not w["errors"] and (off is None or
                                        w["throughput"] > off["throughput"]):
                    off = w
                attach(h)
                w = window()
                if not w["errors"] and (
                        traced is None
                        or w["throughput"] > traced["throughput"]):
                    traced = w
            detach(h)  # final drain folds the last window's counters in
            infer: dict = {}
            if off is not None:
                infer["off_infer_per_sec"] = round(off["throughput"], 2)
                if np.isfinite(off["p99_us"]):
                    infer["off_p99_ms"] = round(off["p99_us"] / 1e3, 3)
            if traced is not None:
                infer["traced_infer_per_sec"] = round(
                    traced["throughput"], 2)
                if np.isfinite(traced["p99_us"]):
                    infer["traced_p99_ms"] = round(
                        traced["p99_us"] / 1e3, 3)
            if off and traced and off["throughput"]:
                infer["overhead_pct"] = round(
                    100.0 * (1.0 - traced["throughput"]
                             / off["throughput"]), 1)
            out["infer"] = infer
            # journey cross-check over the traced windows' files: every
            # client-visible journey reconstructs (count == complete)
            try:
                server_recs = load_trace_files([server_tf + "*"])
                client_recs = load_trace_files([client_tf])
                jo = summarize(server_recs, client_recs).get("journeys")
                if jo:
                    out["journeys"] = {"count": jo["count"],
                                       "complete": jo["complete"]}
                out["traced_client_records"] = len(client_recs)
                out["traced_server_records"] = len(
                    [r for r in server_recs if trace_id_of(r)])
            except (OSError, ValueError) as e:
                out["journeys_error"] = str(e)[:120]
    except Exception as e:  # noqa: BLE001 — observability leg never kills bench
        out["infer_error"] = str(e)[:120]

    # streaming half: tiny CPU generate preset, off vs fully-traced arms
    keys = ("TRITON_TPU_DECODE_MODE", "TRITON_TPU_DECODE_SLOTS",
            "TRITON_TPU_PREFILL_CHUNK", "TRITON_TPU_DECODE_BUCKETS",
            "TRITON_TPU_KV_QUANT", "TRITON_TPU_DECODE_STEPS")
    saved = {k: os.environ.get(k) for k in keys}
    for k in keys:
        os.environ.pop(k, None)
    os.environ["TRITON_TPU_DECODE_MODE"] = "batched"
    os.environ["TRITON_TPU_DECODE_SLOTS"] = "4"
    gc.collect()
    try:
        registry = ModelRegistry()
        zoo.register_all(registry)
        with ServerHarness(registry) as h:
            url = f"127.0.0.1:{h.http_port}"
            profile_generate(url, "llama_generate", concurrency=1,
                             output_tokens=2, num_requests=1,
                             stream_timeout=1800.0)

            def gen_window():
                rep = profile_generate(url, "llama_generate",
                                       concurrency=4, output_tokens=24,
                                       num_requests=12,
                                       stream_timeout=1800.0)
                if rep["errors"]:
                    return None
                return round(rep["output_token_throughput_per_sec"], 1)

            g_off = g_traced = None
            for _ in range(2):
                detach(h)
                w = gen_window()
                if w and (g_off is None or w > g_off):
                    g_off = w
                attach(h)
                w = gen_window()
                if w and (g_traced is None or w > g_traced):
                    g_traced = w
            detach(h)
            stream: dict = {}
            if g_off is not None:
                stream["off_tok_per_s"] = g_off
            if g_traced is not None:
                stream["traced_tok_per_s"] = g_traced
            if g_off and g_traced:
                stream["overhead_pct"] = round(
                    100.0 * (1.0 - g_traced / g_off), 1)
            out["streaming"] = stream
    except Exception as e:  # noqa: BLE001 — observability leg never kills bench
        out["streaming_error"] = str(e)[:120]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        collector.close()
    out["otlp"] = dict(otlp_totals,
                       collector_posts=collector.posts,
                       collector_spans=collector.spans,
                       wellformed_batches=collector.wellformed)
    return out


def _measure_bert_int8() -> dict:
    """int8 BERT serving leg (r5): same sweep as _measure_bert_mfu but with
    TRITON_TPU_QUANT_BERT_LARGE=int8 in a FRESH harness (quantization is
    resolved at the model's first inference, so the A/B needs its own
    session).  Runs after the main harness stopped — serialized device use,
    per the contention rules in benchmarks/BERT_PROFILE.md."""
    import gc

    import jax

    if jax.default_backend() != "tpu":
        return {}
    from triton_client_tpu.models import zoo
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness

    gc.collect()  # free the stopped main harness's device arrays first
    os.environ["TRITON_TPU_QUANT_BERT_LARGE"] = "int8"
    try:
        registry = ModelRegistry()
        zoo.register_all(registry)
        harness = ServerHarness(registry).start()
        try:
            m = _measure_bert_mfu(harness)
        finally:
            harness.stop()
        return {k.replace("bert_", "bert_int8_"): v for k, v in m.items()}
    except Exception as e:  # noqa: BLE001 — bench keeps going without it
        return {"bert_int8_error": str(e)[:120]}
    finally:
        os.environ.pop("TRITON_TPU_QUANT_BERT_LARGE", None)


def _measure_trace_breakdown(url: str, sweep, inputs_fn) -> dict:
    """Short traced closed loop: enable server span tracing, run ~2s at c=4,
    and fold the trace_summary per-stage breakdown (count/p50/p99 + share of
    request time) into the bench record next to the telemetry snapshot."""
    import tempfile

    from triton_client_tpu.grpc import InferenceServerClient
    from triton_client_tpu.tools.trace_summary import (load_trace_file,
                                                       summarize)

    tf = os.path.join(tempfile.mkdtemp(prefix="bench_trace_"), "trace.json")
    ctl = InferenceServerClient(url)
    try:
        ctl.update_trace_settings(settings={
            "trace_file": [tf],
            "trace_level": ["TIMESTAMPS"],
            "trace_rate": ["10"],
        })
        sweep("simple", inputs_fn, concurrency=4, warmup_s=0.5, measure_s=2.0)
    except Exception as e:  # noqa: BLE001 — observability leg never kills bench
        return {"trace_error": str(e)[:120]}
    finally:
        try:
            ctl.update_trace_settings(settings={"trace_level": ["OFF"]})
        except Exception:
            pass
        ctl.close()
    try:
        summary = summarize(load_trace_file(tf))
        entry = summary["models"].get("simple")
        if entry is None:
            return {"trace_error": "no simple traces recorded"}
        stages = {}
        for name, st in entry["stages"].items():
            stages[name] = {
                "count": st["count"],
                "p50_us": (round(st["p50_us"], 1)
                           if st["p50_us"] is not None else None),
                "p99_us": (round(st["p99_us"], 1)
                           if st["p99_us"] is not None else None),
                "share_pct": (round(st["share_pct"], 2)
                              if st["share_pct"] is not None else None),
            }
        return {"trace_stage_breakdown": {
            "requests": entry["count"], "stages": stages}}
    except (OSError, ValueError) as e:
        return {"trace_error": str(e)[:120]}


def _measure_recorder_overhead(core, sweep, inputs_fn) -> dict:
    """Flight-recorder fast-path cost: the same closed-loop window with the
    always-on recorder recording (default) vs disabled, recorded next to
    the trace/telemetry snapshots.  Single 2s windows on a shared host
    carry ±20% noise — read overhead_pct as a bound (negative = noise),
    and read it against the <2% acceptance target over rounds."""
    try:
        on = sweep("simple", inputs_fn, concurrency=8,
                   warmup_s=0.5, measure_s=2.0)
        core.flight_recorder.enabled = False
        try:
            off = sweep("simple", inputs_fn, concurrency=8,
                        warmup_s=0.5, measure_s=2.0)
        finally:
            core.flight_recorder.enabled = True
    except Exception as e:  # noqa: BLE001 — observability leg never kills bench
        core.flight_recorder.enabled = True
        return {"flight_recorder_error": str(e)[:120]}
    result = {
        "recorded_infer_per_sec": on["infer_per_sec"],
        "disabled_infer_per_sec": off["infer_per_sec"],
        "recorded_p99_ms": on["p99_ms"],
        "disabled_p99_ms": off["p99_ms"],
    }
    if off["infer_per_sec"]:
        result["overhead_pct"] = round(
            100.0 * (1.0 - on["infer_per_sec"] / off["infer_per_sec"]), 2)
    errors = on["errors"] + off["errors"]
    if errors:
        result["errors"] = errors[:2]
    return {"flight_recorder_overhead": result}


def _measure_tick_profiler_overhead(core, sweep, inputs_fn) -> dict:
    """Device-stats fast-path cost: the same closed-loop window with the
    always-on collector (per-execute signature + window accounting, per-
    tick records) recording vs disabled — the acceptance bar is <=1% of
    headline c=8 throughput, with the usual ±20% single-window noise
    caveat (negative = noise)."""
    try:
        on = sweep("simple", inputs_fn, concurrency=8,
                   warmup_s=0.5, measure_s=2.0)
        core.device_stats.enabled = False
        try:
            off = sweep("simple", inputs_fn, concurrency=8,
                        warmup_s=0.5, measure_s=2.0)
        finally:
            core.device_stats.enabled = True
    except Exception as e:  # noqa: BLE001 — observability leg never kills bench
        core.device_stats.enabled = True
        return {"tick_profiler_error": str(e)[:120]}
    result = {
        "enabled_infer_per_sec": on["infer_per_sec"],
        "disabled_infer_per_sec": off["infer_per_sec"],
        "enabled_p99_ms": on["p99_ms"],
        "disabled_p99_ms": off["p99_ms"],
    }
    if off["infer_per_sec"]:
        result["overhead_pct"] = round(
            100.0 * (1.0 - on["infer_per_sec"] / off["infer_per_sec"]), 2)
    errors = on["errors"] + off["errors"]
    if errors:
        result["errors"] = errors[:2]
    return {"tick_profiler_overhead": result}


def _measure_host_profiler_overhead(core, sweep, inputs_fn) -> dict:
    """Host-profiler fast-path cost (ISSUE 18): the same closed-loop
    window with the always-on sampling profiler at its production
    default rate vs paused.  Pausing sets hz=0 live (the sampler thread
    parks on a 250ms wait) rather than stop()ing it, so the loop-lag
    probes and GC accounting — O(ns) a piece, and on in BOTH arms —
    survive for the rest of the session; the delta isolates the
    ``sys._current_frames`` stack walk, the only per-sample cost.
    Six interleaved rounds, one window per arm per round.  Single 2s
    windows on a shared host carry ±5% noise — an order bigger than the
    sampler's real cost — and it drifts over the run, so neither
    single-window nor best-of-N deltas converge; instead each round's
    adjacent (paused, sampling) pair shares its drift, and
    ``overhead_pct`` is the **median of the per-round paired ratios**
    (best-of throughputs still reported for the record).  Acceptance is
    <=2% of the headline c=8 throughput (negative = noise)."""
    from triton_client_tpu.server.profiler import DEFAULT_PROFILE_HZ

    prof = core.profiler
    base_hz = prof.hz
    on_hz = base_hz if base_hz > 0 else DEFAULT_PROFILE_HZ
    try:
        if prof._thread is None:
            # env-disabled session: spawn the sampler for the on arm
            # (start() alone early-returns — core already "started" it)
            with prof._lock:
                prof._started = False
            prof.hz = on_hz
            prof.start()

        def samples_total():
            return sum(v for _, v in prof.metric_rows()["samples"])

        on = off = None
        sampled = 0
        ratios = []
        for _ in range(6):
            prof.hz = 0.0
            w_off = sweep("simple", inputs_fn, concurrency=8,
                          warmup_s=0.5, measure_s=2.0)
            if not w_off["errors"] and (
                    off is None
                    or w_off["infer_per_sec"] > off["infer_per_sec"]):
                off = w_off
            prof.hz = on_hz
            before = samples_total()
            w_on = sweep("simple", inputs_fn, concurrency=8,
                         warmup_s=0.5, measure_s=2.0)
            # the on arm must provably have sampled, else the A/B is void
            sampled += samples_total() - before
            if not w_on["errors"] and (
                    on is None
                    or w_on["infer_per_sec"] > on["infer_per_sec"]):
                on = w_on
            if (not w_off["errors"] and not w_on["errors"]
                    and w_off["infer_per_sec"]):
                ratios.append(w_on["infer_per_sec"]
                              / w_off["infer_per_sec"])
    except Exception as e:  # noqa: BLE001 — observability leg never kills bench
        return {"host_profiler_error": str(e)[:120]}
    finally:
        # enabled session: resume the production rate; env-disabled: park
        # the spawned sampler again (hz=0) to respect the operator intent
        prof.hz = base_hz
    if on is None or off is None or not ratios:
        return {"host_profiler_error": "no clean window in one arm"}
    result = {
        "hz": on_hz,
        "sampling_infer_per_sec": on["infer_per_sec"],
        "paused_infer_per_sec": off["infer_per_sec"],
        "sampling_p99_ms": on["p99_ms"],
        "paused_p99_ms": off["p99_ms"],
        "samples_in_on_windows": sampled,
        "rounds": len(ratios),
        "overhead_pct": round(
            100.0 * (1.0 - sorted(ratios)[len(ratios) // 2]), 2),
    }
    return {"host_profiler_overhead": result}


def _measure_host_profiler_overhead_standalone() -> dict:
    """Own-harness variant of the host-profiler A/B for single-leg runs
    (``python -c "import bench; bench._measure_host_profiler_overhead_standalone()"``):
    same arms and windows, with a run_level shim standing in for main()'s
    sweep closure, plus a streaming half (gen tok/s on the tiny CPU
    decode preset) the acceptance bar also covers."""
    import gc

    from triton_client_tpu.genai_perf import profile_generate
    from triton_client_tpu.http import InferenceServerClient, InferInput
    from triton_client_tpu.models import zoo
    from triton_client_tpu.perf_analyzer import (_make_data, _resolve_model,
                                                 run_level)
    from triton_client_tpu.server.profiler import DEFAULT_PROFILE_HZ
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness

    gc.collect()
    try:
        registry = ModelRegistry()
        registry.register_model(zoo.make_simple())
        with ServerHarness(registry) as h:
            url = f"127.0.0.1:{h.http_port}"
            with InferenceServerClient(url) as warm:
                a = np.arange(16, dtype=np.int32).reshape(1, 16)
                i0 = InferInput("INPUT0", [1, 16], "INT32")
                i0.set_data_from_numpy(a)
                i1 = InferInput("INPUT1", [1, 16], "INT32")
                i1.set_data_from_numpy(a)
                warm.infer("simple", [i0, i1])
            meta = InferenceServerClient(url)
            pa_inputs, pa_outputs, pa_max_batch = _resolve_model(
                meta, "http", "simple", "")
            meta.close()
            arrays = _make_data(pa_inputs, {}, 1, pa_max_batch,
                                np.random.default_rng(0))

            def sweep(model, inputs_fn, concurrency, warmup_s, measure_s):
                w = run_level("http", url, model, "", concurrency, arrays,
                              pa_outputs, "none", 1 << 20, measure_s,
                              warmup_s=warmup_s)
                return {"infer_per_sec": round(w["throughput"], 2),
                        "p99_ms": (round(w["p99_us"] / 1e3, 3)
                                   if np.isfinite(w["p99_us"]) else None),
                        "errors": w["errors"]}

            out = _measure_host_profiler_overhead(h.core, sweep, None)
    except Exception as e:  # noqa: BLE001 — observability leg never kills bench
        return {"host_profiler_error": str(e)[:120]}

    # streaming half: generate_stream tok/s with the sampler at the
    # production default rate vs paused, same interleaved best-of arms
    keys = ("TRITON_TPU_DECODE_MODE", "TRITON_TPU_DECODE_SLOTS",
            "TRITON_TPU_PREFILL_CHUNK", "TRITON_TPU_DECODE_BUCKETS",
            "TRITON_TPU_KV_QUANT", "TRITON_TPU_DECODE_STEPS")
    saved = {k: os.environ.get(k) for k in keys}
    for k in keys:
        os.environ.pop(k, None)
    os.environ["TRITON_TPU_DECODE_MODE"] = "batched"
    os.environ["TRITON_TPU_DECODE_SLOTS"] = "4"
    gc.collect()
    try:
        registry = ModelRegistry()
        zoo.register_all(registry)
        with ServerHarness(registry) as h:
            url = f"127.0.0.1:{h.http_port}"
            profile_generate(url, "llama_generate", concurrency=1,
                             output_tokens=2, num_requests=1,
                             stream_timeout=1800.0)
            prof = h.core.profiler
            base_hz = prof.hz
            on_hz = base_hz if base_hz > 0 else DEFAULT_PROFILE_HZ

            def gen_window():
                rep = profile_generate(url, "llama_generate",
                                       concurrency=4, output_tokens=24,
                                       num_requests=12,
                                       stream_timeout=1800.0)
                if rep["errors"]:
                    return None
                return round(rep["output_token_throughput_per_sec"], 1)

            g_on = g_off = None
            g_ratios = []
            for _ in range(3):
                prof.hz = 0.0
                w_off = gen_window()
                if w_off and (g_off is None or w_off > g_off):
                    g_off = w_off
                prof.hz = on_hz
                w_on = gen_window()
                if w_on and (g_on is None or w_on > g_on):
                    g_on = w_on
                if w_off and w_on:
                    g_ratios.append(w_on / w_off)
            prof.hz = base_hz
            gen: dict = {}
            if g_off is not None:
                gen["paused_tok_per_s"] = g_off
            if g_on is not None:
                gen["sampling_tok_per_s"] = g_on
            if g_ratios:
                # same paired-median estimator as the infer half
                gen["overhead_pct"] = round(
                    100.0 * (1.0 - sorted(g_ratios)[len(g_ratios) // 2]), 1)
            key = ("host_profiler_overhead" if "host_profiler_overhead"
                   in out else "host_profiler_gen")
            if key == "host_profiler_overhead":
                out[key]["gen"] = gen
            else:
                out[key] = gen
    except Exception as e:  # noqa: BLE001 — observability leg never kills bench
        out["host_profiler_gen_error"] = str(e)[:120]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def _measure_device_fault_recovery() -> dict:
    """Device-fault containment leg (ISSUE 19) — CPU-runnable on the tiny
    batched decode preset, standalone
    (``python -c "import bench, json; print(json.dumps(bench._measure_device_fault_recovery()))"``).

    Two halves:

    * steady-state overhead: an ARMED model (DeviceFaultManager attached,
      a rate=0 chaos injector consulted at every dispatch boundary, and
      the tick-stall watchdog watching every readback) vs a PLAIN model
      with none of it, interleaved best-of-3 cohorts on two warm
      instances — acceptance bar <=1% of cohort tok/s (single-window
      host noise is ±5%, so small negatives = noise).
    * the acceptance drill, timed: a seeded transient ``device_error``
      (rate=1, max_faults=1) against a full 4-slot cohort on the armed
      model.  Every server-side stream must recover BIT-IDENTICAL to the
      armed model's own clean run with zero caller-visible errors; the
      wall-clock delta vs the armed clean cohort is the end-to-end
      recovery cost (donated-cache rebuild + re-prefill of
      prompt+emitted for all 4 sequences, serialized on the one worker).
    """
    import gc

    from triton_client_tpu.server.chaos import ChaosInjector
    from triton_client_tpu.server.core import DeviceFaultManager

    keys = ("TRITON_TPU_DECODE_MODE", "TRITON_TPU_DECODE_SLOTS",
            "TRITON_TPU_PREFILL_CHUNK", "TRITON_TPU_DECODE_BUCKETS",
            "TRITON_TPU_KV_QUANT", "TRITON_TPU_DECODE_STEPS",
            "TRITON_TPU_RECOVERY_BUDGET", "TRITON_TPU_TICK_STALL_MS")
    saved = {k: os.environ.get(k) for k in keys}
    SLOTS, N_TOK, ROUNDS = 4, 24, 3
    out: dict = {"slots": SLOTS, "output_tokens": N_TOK}
    gc.collect()
    for k in keys:
        os.environ.pop(k, None)
    os.environ["TRITON_TPU_DECODE_MODE"] = "batched"
    os.environ["TRITON_TPU_DECODE_SLOTS"] = str(SLOTS)
    plain = armed = None
    try:
        from triton_client_tpu.models.decode import DecodeModel

        win = np.zeros((1, 128), np.int32)
        win[0, -5:] = [7, 11, 13, 17, 19]

        def drain(sink):
            toks = []
            while True:
                item = sink.get(timeout=600)
                if item is None:
                    return toks, None
                if isinstance(item, Exception):
                    return toks, item
                toks.append(int(item[0]))

        def cohort(m):
            t0 = time.perf_counter()
            outs = [drain(s) for s in
                    [m.submit_generation(win, N_TOK)
                     for _ in range(SLOTS)]]
            dt = time.perf_counter() - t0
            return (dt, [t for t, _ in outs],
                    [e for _, e in outs if e is not None])

        plain = DecodeModel(name="llama_decode_bench_plain")
        # the watchdog arms from env at construction — plain is already
        # built, so only the armed instance pays for readback watching
        # (30 s stall bar: bookkeeping cost without ever tripping on CPU)
        os.environ["TRITON_TPU_TICK_STALL_MS"] = "30000"
        armed = DecodeModel(name="llama_decode_bench_armed")
        mgr = DeviceFaultManager(threshold=100)
        armed.attach_device_faults(mgr)
        # rate=0: the seeded draw is consulted at every dispatch boundary
        # and never fires — this IS the steady-state consult cost
        armed.attach_chaos(ChaosInjector(rate=0.0, kinds=["device_error"],
                                         seed=1))
        cohort(plain)  # compile warm off-clock (prefill + fused tick)
        _, want, werr = cohort(armed)
        if werr:
            out["warm_error"] = str(werr[0])[:120]
            return out

        plain_best = armed_best = None  # (tok_per_s, dt)
        for _ in range(ROUNDS):
            for tag, m in (("plain", plain), ("armed", armed)):
                dt, _toks, errs = cohort(m)
                if errs:
                    out[f"{tag}_error"] = str(errs[0])[:120]
                    continue
                tps = round(SLOTS * N_TOK / dt, 1)
                if tag == "plain" and (plain_best is None
                                       or tps > plain_best[0]):
                    plain_best = (tps, dt)
                if tag == "armed" and (armed_best is None
                                       or tps > armed_best[0]):
                    armed_best = (tps, dt)
        if plain_best:
            out["plain_tok_per_s"] = plain_best[0]
        if armed_best:
            out["armed_tok_per_s"] = armed_best[0]
            out["armed_clean_cohort_ms"] = round(armed_best[1] * 1e3, 1)
        if plain_best and armed_best:
            out["containment_overhead_pct"] = round(
                100.0 * (1.0 - armed_best[0] / plain_best[0]), 1)

        # the drill: one seeded transient fault against a live cohort
        armed.attach_chaos(ChaosInjector(rate=1.0, kinds=["device_error"],
                                         seed=5, max_faults=1))
        dt, toks, errs = cohort(armed)
        snap = mgr.snapshot()
        drill = {
            "cohort_ms": round(dt * 1e3, 1),
            "injected": armed._chaos.injected_total,
            "recovered": snap["recovered"].get(
                "llama_decode_bench_armed", 0),
            "aborted": snap.get("aborted", {}),
            "caller_errors": len(errs),
            "bit_identical": toks == want,
        }
        if armed_best:
            drill["recovery_added_ms"] = round(
                (dt - armed_best[1]) * 1e3, 1)
        out["drill"] = drill
        out["metric"] = "device_fault_recovery_added_ms"
        out["value"] = drill.get("recovery_added_ms")
        out["unit"] = "ms_wallclock_vs_armed_clean_cohort"
    except Exception as e:  # noqa: BLE001 — robustness leg never kills bench
        out["device_fault_recovery_error"] = str(e)[:120]
    finally:
        for m in (plain, armed):
            if m is not None:
                try:
                    m._shutdown()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def _measure_shared_prefix() -> dict:
    """Prefix/KV-cache leg (ISSUE 20) — CPU-runnable on the tiny batched
    decode preset, standalone
    (``python -c "import bench, json; print(json.dumps(bench._measure_shared_prefix()))"``).

    The ``gen_shared_prefix`` drill: 64 requests sharing one 1k-token
    prompt against a cache-enabled model.  Request 1 is COLD (full
    prefill, commits the 15-block chain); requests 2..64 are WARM (chain
    restore + 64-token tail prefill), submitted sequentially so each
    TTFT is a clean submit-to-first-token measurement rather than a
    queueing artifact.  Every stream must be bit-identical to the cold
    one.  A final distinct 1k prompt overflows the deliberately tight
    budget so the eviction counter is exercised live, not just declared.

    Honesty label: ``cpu_only`` — on the CPU stand-in the ratio reflects
    host compute, not HBM bandwidth; the shape of the win (tail tokens
    vs full window) carries to the device, the constant does not.
    """
    import gc

    import jax

    keys = ("TRITON_TPU_DECODE_MODE", "TRITON_TPU_DECODE_SLOTS",
            "TRITON_TPU_PREFILL_CHUNK", "TRITON_TPU_DECODE_BUCKETS",
            "TRITON_TPU_KV_QUANT", "TRITON_TPU_DECODE_STEPS",
            "TRITON_TPU_KV_BLOCK_TOKENS", "TRITON_TPU_KV_CACHE_BYTES")
    saved = {k: os.environ.get(k) for k in keys}
    N_REQ, PROMPT, N_TOK = 64, 1024, 4
    out: dict = {"cpu_only": jax.default_backend() != "tpu",
                 "requests": N_REQ, "prompt_tokens": PROMPT,
                 "output_tokens": N_TOK}
    gc.collect()
    for k in keys:
        os.environ.pop(k, None)
    os.environ["TRITON_TPU_DECODE_MODE"] = "batched"
    os.environ["TRITON_TPU_DECODE_SLOTS"] = "4"
    # two 15-block chains (warm-up + shared prompt) fit; a third evicts
    os.environ["TRITON_TPU_KV_CACHE_BYTES"] = "1000000"
    m = None
    try:
        from triton_client_tpu.models.decode import DecodeModel
        from triton_client_tpu.server import kvcache

        def window(seed):
            win = np.zeros((1, PROMPT), np.int32)
            seed = np.asarray(seed, np.int32) % 250 + 1
            win[0, -len(seed):] = seed
            return win

        def run(mdl, win):
            """(tokens, ttft_s): submit-to-first-token wall clock."""
            t0 = time.perf_counter()
            sink = mdl.submit_generation(win, N_TOK)
            ttft = None
            toks = []
            while True:
                item = sink.get(timeout=600)
                if item is None:
                    return toks, ttft
                if isinstance(item, Exception):
                    raise item
                if ttft is None:
                    ttft = time.perf_counter() - t0
                toks.append(int(item[0]))

        m = DecodeModel(name="llama_decode_bench_kvc", prompt_len=PROMPT)
        warmup = window(list(range(300)))
        run(m, warmup)   # compile the cold prefill path, off-clock
        run(m, warmup)   # compile the chain-restore + tail path
        cache = kvcache.get("llama_decode_bench_kvc")
        out["block_tokens"] = cache.block_tokens
        out["budget_bytes"] = cache.budget_bytes

        shared = window(list(range(7, 1031)))
        want, cold_ttft = run(m, shared)
        warm_ttfts, identical = [], True
        for _ in range(N_REQ - 1):
            toks, ttft = run(m, shared)
            identical = identical and toks == want
            warm_ttfts.append(ttft)
        warm = np.asarray(warm_ttfts)
        out["cold_ttft_ms"] = round(cold_ttft * 1e3, 2)
        out["warm_ttft_ms_p50"] = round(
            float(np.percentile(warm, 50)) * 1e3, 2)
        out["warm_ttft_ms_mean"] = round(float(warm.mean()) * 1e3, 2)
        out["bit_identical"] = identical

        # overflow the budget with a third distinct chain: the eviction
        # counter must move for real, not just be declared
        run(m, window(list(range(500, 1524))))
        st = cache.stats()
        out["cache"] = {k: st[k] for k in
                        ("blocks", "pinned_bytes", "hits", "misses",
                         "evictions", "hit_tokens")}
        speedup = cold_ttft / float(np.percentile(warm, 50))
        out["metric"] = "gen_shared_prefix_ttft_speedup"
        out["value"] = round(speedup, 2)
        out["unit"] = "x_cold_over_warm_p50_ttft"
    except Exception as e:  # noqa: BLE001 — bench leg never kills bench
        out["shared_prefix_error"] = str(e)[:120]
    finally:
        if m is not None:
            try:
                m._shutdown()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def _measure_cost_attribution_overhead(core, sweep, inputs_fn) -> dict:
    """Cost-ledger fast-path cost: the same closed-loop window with the
    always-on per-tenant attribution (ledger charge per execute + slot-
    share arithmetic) recording vs disabled — the acceptance bar is <=1%
    of headline c=8 throughput, with the usual ±20% single-window noise
    caveat (negative = noise)."""
    try:
        on = sweep("simple", inputs_fn, concurrency=8,
                   warmup_s=0.5, measure_s=2.0)
        core.cost_ledger.enabled = False
        try:
            off = sweep("simple", inputs_fn, concurrency=8,
                        warmup_s=0.5, measure_s=2.0)
        finally:
            core.cost_ledger.enabled = True
    except Exception as e:  # noqa: BLE001 — observability leg never kills bench
        core.cost_ledger.enabled = True
        return {"cost_attribution_error": str(e)[:120]}
    result = {
        "enabled_infer_per_sec": on["infer_per_sec"],
        "disabled_infer_per_sec": off["infer_per_sec"],
        "enabled_p99_ms": on["p99_ms"],
        "disabled_p99_ms": off["p99_ms"],
    }
    if off["infer_per_sec"]:
        result["overhead_pct"] = round(
            100.0 * (1.0 - on["infer_per_sec"] / off["infer_per_sec"]), 2)
    errors = on["errors"] + off["errors"]
    if errors:
        result["errors"] = errors[:2]
    return {"cost_attribution_overhead": result}


def _cost_summary(core) -> dict:
    """End-of-session cost observability snapshot: the roofline verdict
    per (model, bucket) from XLA cost analysis and the per-tenant cost
    ledger totals — the BENCH json's who-paid-for-the-device axis."""
    out: dict = {}
    try:
        snap = core.device_stats.snapshot()
        rooflines = {}
        for model, per_bucket in (snap.get("ticks") or {}).items():
            for bucket, bs in (per_bucket or {}).items():
                roof = (bs or {}).get("roofline")
                if roof:
                    rooflines[f"{model}@{bucket}"] = {
                        "verdict": roof.get("verdict"),
                        "arithmetic_intensity": roof.get(
                            "arithmetic_intensity"),
                        "pct_of_peak": roof.get("pct_of_peak"),
                    }
        if rooflines:
            out["rooflines"] = rooflines
    except Exception as e:  # noqa: BLE001 — observability leg never kills bench
        out["roofline_error"] = str(e)[:120]
    try:
        out["cost_attribution"] = core.cost_ledger.snapshot()
    except Exception as e:  # noqa: BLE001
        out["cost_attribution_error"] = str(e)[:120]
    return out


def _device_stats_summary(core) -> dict:
    """Utilization trajectory from the live device-stats collector at the
    end of the serving legs: duty cycle / live MFU (worst-case: the
    busiest model), the cumulative pad-waste fraction, and the compact
    snapshot — so the BENCH json tracks utilization, not just
    throughput."""
    try:
        snap = core.device_stats.snapshot()
    except Exception as e:  # noqa: BLE001 — observability leg never kills bench
        return {"device_stats_error": str(e)[:120]}
    models = snap.get("models", {})
    duties = [m["duty_cycle"] for m in models.values()
              if m.get("duty_cycle") is not None]
    mfus = [m["live_mfu"] for m in models.values()
            if m.get("live_mfu") is not None]
    pad = core.device_stats.pad_waste()
    out = {
        "duty_cycle": round(max(duties), 4) if duties else None,
        "live_mfu": round(max(mfus), 6) if mfus else None,
        "pad_waste_fraction": round(pad, 4) if pad is not None else None,
        "device_stats": {
            "models": {
                name: {"duty_cycle": m.get("duty_cycle"),
                       "live_mfu": m.get("live_mfu"),
                       "executions": m.get("executions"),
                       "compiles": m.get("compile", {}).get("count")}
                for name, m in models.items()
            },
            "ticks": snap.get("ticks", {}),
            "transfers": snap.get("transfers", {}),
        },
    }
    return out


def _measure_resilience_overhead(sweep, inputs_fn) -> dict:
    """Happy-path cost of the client resilience layer: the same closed-loop
    window with every infer running under RetryPolicy(max_attempts=3) vs
    the plain call path.  No faults are injected, so the delta is pure
    wrapper overhead (one closure + deadline arithmetic per request) —
    read overhead_pct against the <1% acceptance target, with the usual
    ±20% single-window noise caveat (negative = noise)."""
    from triton_client_tpu._resilience import RetryPolicy

    policy = RetryPolicy(max_attempts=3, retry_infer=True)
    try:
        on = sweep("simple", inputs_fn, concurrency=8,
                   warmup_s=0.5, measure_s=2.0, retry_policy=policy)
        off = sweep("simple", inputs_fn, concurrency=8,
                    warmup_s=0.5, measure_s=2.0)
    except Exception as e:  # noqa: BLE001 — resilience leg never kills bench
        return {"resilience_error": str(e)[:120]}
    result = {
        "enabled_infer_per_sec": on["infer_per_sec"],
        "disabled_infer_per_sec": off["infer_per_sec"],
        "enabled_p99_ms": on["p99_ms"],
        "disabled_p99_ms": off["p99_ms"],
    }
    if off["infer_per_sec"]:
        result["overhead_pct"] = round(
            100.0 * (1.0 - on["infer_per_sec"] / off["infer_per_sec"]), 2)
    errors = on["errors"] + off["errors"]
    if errors:
        result["errors"] = errors[:2]
    return {"resilience_overhead": result}


def _measure_cluster() -> dict:
    """Cluster-client A/Bs on a 3-replica in-process fleet (own harnesses,
    run after the main harness stopped):

    * ``cluster_routing`` — least-outstanding ``ClusterClient`` over 3
      replicas vs a single-endpoint client at the same fixed concurrency.
      All replicas share this process's CPU, so ``speedup`` here mostly
      bounds the routing layer's overhead (±noise); on a real multi-host
      fleet the same A/B measures the capacity win.
    * ``hedging_tail`` — p99 with vs without hedged requests while one
      replica is a chaos-latency straggler (every request to it +80 ms);
      round-robin on both sides so the straggler is hit deterministically.
      The acceptance bar is hedged p99 strictly below unhedged p99.
    """
    import gc

    from triton_client_tpu._resilience import RetryPolicy
    from triton_client_tpu.grpc import InferenceServerClient, InferInput
    from triton_client_tpu.models import zoo
    from triton_client_tpu.server.chaos import ChaosInjector
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ClusterHarness

    gc.collect()  # free the stopped main harness's device arrays first

    def factory():
        r = ModelRegistry()
        r.register_model(zoo.make_simple())
        return r

    def make_inputs():
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        b = np.ones((1, 16), dtype=np.int32)
        i0 = InferInput("INPUT0", [1, 16], "INT32")
        i0.set_data_from_numpy(a)
        i1 = InferInput("INPUT1", [1, 16], "INT32")
        i1.set_data_from_numpy(b)
        return [i0, i1]

    # the sweeps ride perf_analyzer.run_level: one SHARED ClusterClient
    # per level (a per-worker client would degrade least_outstanding to
    # random choice — its pool would never see another worker's
    # in-flight requests) and per-endpoint/hedge counters for free
    from triton_client_tpu.perf_analyzer import (_make_data,
                                                 _resolve_model, run_level)

    def p99_ms(res):
        return (round(res["p99_us"] / 1e3, 3)
                if np.isfinite(res["p99_us"]) else None)

    out: dict = {}
    try:
        with ClusterHarness(factory, n=3) as ch:
            urls = ch.grpc_urls
            # warm every replica before any clock (first request compiles)
            for u in urls:
                with InferenceServerClient(u) as warm:
                    warm.infer("simple", make_inputs())
            meta = InferenceServerClient(urls[0])
            pa_inputs, pa_outputs, pa_max_batch = _resolve_model(
                meta, "grpc", "simple", "")
            meta.close()
            arrays = _make_data(pa_inputs, {}, 1, pa_max_batch,
                                np.random.default_rng(0))
            single = run_level("grpc", urls[0], "simple", "", 8, arrays,
                               pa_outputs, "none", 1 << 20, 2.0,
                               warmup_s=0.5)
            cluster = run_level("grpc", urls, "simple", "", 8, arrays,
                                pa_outputs, "none", 1 << 20, 2.0,
                                warmup_s=0.5,
                                balancing="least_outstanding")
            routing = {
                "cluster_infer_per_sec": round(cluster["throughput"], 2),
                "single_infer_per_sec": round(single["throughput"], 2),
                "cluster_p99_ms": p99_ms(cluster),
                "single_p99_ms": p99_ms(single),
                "endpoints": cluster.get("endpoints"),
            }
            if single["throughput"]:
                routing["speedup"] = round(
                    cluster["throughput"] / single["throughput"], 2)
            errors = single["errors"] + cluster["errors"]
            if errors:
                routing["errors"] = [single.get("first_error"),
                                     cluster.get("first_error")]
            out["cluster_routing"] = routing

            # hedging A/B: replica 0 becomes a deterministic straggler.
            # The straggler delay (400 ms) must dwarf the hedge delay
            # (100 ms), which in turn must exceed the loaded normal p99 —
            # all three replicas share this process's CPU, so "normal"
            # latency here is far above a real fleet's, and a hedge delay
            # below it makes every request hedge (doubling load and
            # inverting the A/B)
            ch.chaos(0, ChaosInjector(rate=1.0, kinds=["latency"],
                                      latency_ms=400.0, seed=7))
            unhedged = run_level("grpc", urls, "simple", "", 4, arrays,
                                 pa_outputs, "none", 1 << 20, 2.0,
                                 warmup_s=0.5, balancing="round_robin")
            # max_attempts=1 + retry_infer arms the hedge idempotency
            # gate without enabling retries (the perf_analyzer contract)
            hedged = run_level("grpc", urls, "simple", "", 4, arrays,
                               pa_outputs, "none", 1 << 20, 2.0,
                               warmup_s=0.5, balancing="round_robin",
                               hedge_ms=100.0,
                               retry_policy=RetryPolicy(
                                   max_attempts=1, retry_infer=True))
            tail = {
                "hedged_p99_ms": p99_ms(hedged),
                "unhedged_p99_ms": p99_ms(unhedged),
                "hedged_infer_per_sec": round(hedged["throughput"], 2),
                "unhedged_infer_per_sec": round(unhedged["throughput"], 2),
                "hedges": hedged.get("hedges", 0),
                "hedge_wins": hedged.get("hedge_wins", 0),
            }
            errors = unhedged["errors"] + hedged["errors"]
            if errors:
                tail["errors"] = [unhedged.get("first_error"),
                                  hedged.get("first_error")]
            out["hedging_tail"] = tail
    except Exception as e:  # noqa: BLE001 — cluster leg never kills bench
        return {"cluster_error": str(e)[:120]}
    return out


def _measure_qos_overload() -> dict:
    """QoS A/B: tier-0 p99 with vs without priority tiers under ~2x
    sustained overload.  A delay-model harness with a bounded queue takes
    a best-effort closed-loop flood plus a serial tier-0 probe stream;
    with QoS the flood rides priority 3 (shed first at half the queue
    bound, tier 0 keeps headroom), without it everything is priority 0
    and the probe competes FIFO.  Host-only (the delay model sleeps), so
    this leg runs on every backend and never kills the bench."""
    import gc

    import triton_client_tpu.http as httpclient
    from triton_client_tpu._resilience import RetryPolicy
    from triton_client_tpu.models import zoo
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness

    gc.collect()
    model = "custom_identity_int32"
    delay = {"execute_delay_ms": 15}
    queue_limit = 6
    flood_threads = 8  # ~2x what the queue bound admits

    def make_inputs():
        x = np.arange(4, dtype=np.int32).reshape(1, 4)
        i = httpclient.InferInput("INPUT0", [1, 4], "INT32")
        i.set_data_from_numpy(x)
        return [i]

    def window(qos_on: bool):
        registry = ModelRegistry()
        registry.register_model(zoo.make_custom_identity_int32())
        with ServerHarness(registry) as h:
            h.core.queue_limits[model] = queue_limit
            stop = threading.Event()

            def flood():
                with httpclient.InferenceServerClient(h.http_url) as c:
                    inputs = make_inputs()
                    while not stop.is_set():
                        try:
                            c.infer(model, inputs, parameters=delay,
                                    priority=3 if qos_on else 0,
                                    tenant="batch")
                        except Exception:
                            time.sleep(0.002)  # shed: brief local backoff

            threads = [threading.Thread(target=flood, daemon=True)
                       for _ in range(flood_threads)]
            for t in threads:
                t.start()
            time.sleep(0.4)  # flood reaches steady state
            lat = []
            policy = RetryPolicy(max_attempts=3, retry_infer=True,
                                 initial_backoff_s=0.01)
            with httpclient.InferenceServerClient(h.http_url) as c:
                inputs = make_inputs()
                for _ in range(50):
                    t0 = time.perf_counter()
                    c.infer(model, inputs, parameters=delay, priority=0,
                            tenant="gold", retry_policy=policy)
                    lat.append(time.perf_counter() - t0)
            stop.set()
            for t in threads:
                t.join(timeout=10)
            shed = sum(h.core.qos.rejected_counts().values())
            p99 = float(np.percentile(np.asarray(lat), 99) * 1e3)
            return round(p99, 2), shed

    try:
        p99_on, shed_on = window(qos_on=True)
        p99_off, shed_off = window(qos_on=False)
    except Exception as e:  # noqa: BLE001 — QoS leg never kills bench
        return {"qos_error": str(e)[:120]}
    result = {
        "tier0_p99_ms_with_qos": p99_on,
        "tier0_p99_ms_without_qos": p99_off,
        "shed_with_qos": shed_on,
        "shed_without_qos": shed_off,
    }
    if p99_on:
        result["tier0_p99_ratio"] = round(p99_off / p99_on, 2)
    return {"qos_overload": result}


def _measure_mem_overload() -> dict:
    """Memory-governor A/B (ISSUE 14): an oversized-payload burst at ~2x
    the host byte budget with the governor ON vs OFF.

    Eight closed-loop flood threads send 512 KiB best-effort payloads
    against a 2 MiB budget while a serial tier-0 small-payload probe
    stream measures p99.  Recorded per window: the governor's peak
    in-flight bytes (the ledger the budget bounds — OFF tracks but never
    sheds, so the A/B shows exactly the bytes the budget refused to
    hold), shed counts, whether every refusal was a typed 429 (zero
    connection resets), tier-0 p99, and the process RSS delta.
    Host-only; never kills the bench."""
    import gc
    import resource

    import triton_client_tpu.http as httpclient
    from triton_client_tpu.models import zoo
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness
    from triton_client_tpu.utils import InferenceServerException

    gc.collect()
    model = "custom_identity_int32"
    budget = 2 << 20
    big = np.zeros((1, 128 << 10), np.int32)   # 512 KiB payload
    small = np.arange(64, dtype=np.int32).reshape(1, 64)
    flood_threads = 8                           # ~2x budget in flight

    def make_inputs(arr):
        i = httpclient.InferInput("INPUT0", list(arr.shape), "INT32")
        i.set_data_from_numpy(arr)
        return [i]

    def window(governor_on: bool):
        registry = ModelRegistry()
        registry.register_model(zoo.make_custom_identity_int32())
        rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        with ServerHarness(registry) as h:
            h.core.memory.budget_bytes = budget if governor_on else 0
            stop = threading.Event()
            typed, resets = [0], [0]

            def flood():
                with httpclient.InferenceServerClient(h.http_url) as c:
                    inputs = make_inputs(big)
                    while not stop.is_set():
                        try:
                            c.infer(model, inputs, priority=3,
                                    tenant="whale")
                        except InferenceServerException as e:
                            if e.status() in ("429", "413"):
                                typed[0] += 1
                            else:
                                resets[0] += 1
                        except Exception:
                            resets[0] += 1

            threads = [threading.Thread(target=flood, daemon=True)
                       for _ in range(flood_threads)]
            for t in threads:
                t.start()
            time.sleep(0.4)
            lat = []
            with httpclient.InferenceServerClient(h.http_url) as c:
                inputs = make_inputs(small)
                for _ in range(50):
                    t0 = time.perf_counter()
                    c.infer(model, inputs, priority=0, tenant="gold")
                    lat.append(time.perf_counter() - t0)
            stop.set()
            for t in threads:
                t.join(timeout=10)
            gov = h.core.memory
            rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            return {
                "peak_inflight_bytes": gov.peak_inflight_bytes,
                "shed_total": gov.shed_total(),
                "typed_sheds_seen": typed[0],
                "connection_resets": resets[0],
                "tier0_p99_ms": round(float(
                    np.percentile(np.asarray(lat), 99) * 1e3), 2),
                "rss_delta_kb": max(0, rss1 - rss0),
            }

    try:
        on = window(governor_on=True)
        off = window(governor_on=False)
    except Exception as e:  # noqa: BLE001 — this leg never kills bench
        return {"mem_overload_error": str(e)[:120]}
    return {"mem_overload": {
        "budget_bytes": budget,
        "payload_bytes": int(big.nbytes),
        "flood_threads": flood_threads,
        # ru_maxrss is MONOTONIC per process: the first window (ON) also
        # absorbs harness/XLA warmup growth, so read rss_delta_kb as an
        # upper bound there; peak_inflight_bytes is the precise ledger
        "rss_note": "ru_maxrss is monotonic; first window absorbs warmup",
        "governor_on": on,
        "governor_off": off,
        # the acceptance read: ON keeps the ledger bounded by the budget
        # (+ one response's worth, which joins post-admission); OFF lets
        # it grow with the burst
        "peak_within_budget": bool(
            on["peak_inflight_bytes"] <= budget + int(big.nbytes) * 2),
        "peak_ratio_off_over_on": (
            round(off["peak_inflight_bytes"]
                  / on["peak_inflight_bytes"], 2)
            if on["peak_inflight_bytes"] else None),
    }}


def _measure_fleet_ops() -> dict:
    """Closed-loop fleet drill (ISSUE 13): recovery-time-to-SLO after a
    seeded replica kill plus a mid-run rolling model update.

    A 2-replica in-process fleet serves a 30 ms delay model pinned at 1
    batcher instance (bounds 1..4) under an 8-way closed-loop flood with
    ``RetryPolicy(3)`` clients — ~2x the pinned capacity, so the tier-0
    burn rate breaches.  Then, mid-run: a seeded ``worker_kill`` chaos
    fault takes replica 1 down (the replica supervisor heals it with
    backoff) while a rolling update flips replica 0 to a new version
    under traffic.  Recorded: the wall-clock from the kill until every
    replica's 5m burn rate is back under the breach threshold
    (``recovery_to_slo_s``), the autoscaler's actuation count, the
    rolling-update outcome/duration, the healed restart count, and the
    caller-visible error count (the acceptance bar: 0).  Host-only (the
    delay model sleeps), so this leg runs on every backend and never
    kills the bench."""
    import asyncio
    import gc
    import threading

    import triton_client_tpu.http as httpclient
    from triton_client_tpu._resilience import RetryPolicy
    from triton_client_tpu.cluster import ClusterClient
    from triton_client_tpu.server import (InferenceCore, ModelRegistry,
                                          PyModel, make_config)
    from triton_client_tpu.server.chaos import ChaosInjector
    from triton_client_tpu.server.device_stats import SloObjective
    from triton_client_tpu.server.fleet import FleetController
    from triton_client_tpu.server.testing import (ClusterHarness,
                                                  ReplicaSupervisor)

    gc.collect()
    model = "scaly"
    service_s = 0.03

    def drill_model():
        cfg = make_config(
            model,
            inputs=[("IN", "INT32", [-1])],
            outputs=[("OUT", "INT32", [-1])],
            max_batch_size=1,
            preferred_batch_sizes=[1],
        )

        def fn(inputs, params):
            time.sleep(service_s)
            return {"OUT": inputs["IN"]}

        return PyModel(cfg, fn)

    def factory():
        r = ModelRegistry()
        r.register_model(drill_model())
        return r

    controllers = {}

    def core_setup(h):
        core = h.core
        core.slo.set_objective(model, SloObjective(
            p99_ms=service_s * 2e3, availability=0.95))
        ctl = FleetController(core, interval_s=0.1,
                              bounds={model: (1, 4)}, queue_high=2.0,
                              scale_out_cooldown_s=0.25,
                              scale_in_cooldown_s=60.0)
        core.fleet = ctl
        ctl.scale_to(model, 1)
        ctl.start_on(h._loop)
        controllers[id(core)] = ctl

    out: dict = {"concurrency": 8, "service_ms": service_s * 1e3,
                 "instance_bounds": [1, 4]}
    errors: list = []
    try:
        with ClusterHarness(factory, n=2, core_setup=core_setup) as ch:
            sup = ReplicaSupervisor(ch)
            inj = ChaosInjector(rate=1.0, kinds=["worker_kill"], seed=42,
                                max_faults=1)
            inj.worker_kill_cb = lambda: sup.crash(1)
            policy = RetryPolicy(max_attempts=3, retry_infer=True,
                                 initial_backoff_s=0.02, seed=9)
            stop = threading.Event()
            x = np.ones((1, 4), dtype=np.int32)

            def flood():
                try:
                    with ClusterClient(ch.http_urls, protocol="http",
                                       policy="least_outstanding",
                                       retry_policy=policy) as c:
                        i0 = httpclient.InferInput("IN", [1, 4], "INT32")
                        i0.set_data_from_numpy(x)
                        while not stop.is_set():
                            c.infer(model, [i0], priority=0,
                                    retry_policy=policy)
                except Exception as e:  # noqa: BLE001 — the 0-error bar
                    errors.append(repr(e))

            threads = [threading.Thread(target=flood, daemon=True)
                       for _ in range(8)]
            for t in threads:
                t.start()
            try:
                core0 = ch.harnesses[0].core
                threshold = core0.slo.burn_threshold
                t0 = time.monotonic()
                while time.monotonic() - t0 < 20.0:
                    burn = core0.slo.burn_rate(model, 300.0)
                    if burn is not None and burn >= threshold:
                        break
                    time.sleep(0.05)
                else:
                    raise RuntimeError("overload never breached the SLO")
                out["time_to_breach_s"] = round(time.monotonic() - t0, 2)

                # the seeded kill + the concurrent rolling update
                ch.chaos(1, inj)
                kill_t = time.monotonic()
                fut = asyncio.run_coroutine_threadsafe(
                    controllers[id(core0)].rolling_update(
                        model, drill_model(), bake_s=0.3),
                    ch.harnesses[0]._loop)
                out["rolling_update_outcome"] = fut.result(timeout=30)
                out["rolling_update_s"] = round(
                    time.monotonic() - kill_t, 2)

                recovered = None
                while time.monotonic() - kill_t < 30.0:
                    burns = [h.core.slo.burn_rate(model, 300.0)
                             for h in ch.harnesses if h is not None]
                    if burns and all(b is None or b < threshold
                                     for b in burns):
                        recovered = time.monotonic()
                        break
                    time.sleep(0.1)
                out["recovery_to_slo_s"] = (
                    round(recovered - kill_t, 2)
                    if recovered is not None else None)
                sup.join(timeout=20)
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=30)
            out["scale_out_events"] = sum(
                ctl.scale_events.get((model, "out"), 0)
                for ctl in controllers.values())
            out["instances_after"] = controllers[
                id(core0)].desired_instances(model)
            out["worker_restarts"] = sup.state.counts()
            out["caller_errors"] = len(errors)
            if errors:
                out["first_error"] = errors[0][:120]
    except Exception as e:  # noqa: BLE001 — fleet leg never kills bench
        return {"fleet_ops_error": str(e)[:120]}
    return {"fleet_ops": out}


def _measure_rtt_floor() -> float:
    """Median blocking device round trip (H2D + sync + D2H) in ms — the
    physical latency floor for any synchronous per-request device path."""
    import jax

    dev = jax.devices()[0]
    x = np.ones((8, 512), np.float32)
    np.asarray(jax.device_put(x, dev))  # warm the transfer path
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(jax.device_put(x, dev))
        samples.append(time.perf_counter() - t0)
    return float(np.median(samples) * 1e3)


def _measure_flash_attention() -> dict:
    """Amortized pallas-vs-XLA causal attention at the long-context shape
    (B4 H32 S2048 D128). Returns {} off-TPU; the remote-dispatch floor makes
    single calls unmeasurable, so N kernel applications run inside one jit."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    if jax.default_backend() != "tpu":
        return {}
    from triton_client_tpu.ops import (
        flash_attention,
        flash_attention_reference,
    )

    import gc

    gc.collect()  # free the generation legs' zoos before allocating here
    B, H, S, D, N = 4, 32, 2048, 128, 20
    rng = np.random.default_rng(0)

    def loop(fn):
        @jax.jit
        def run(q, k, v):
            def body(i, acc):
                o = fn(q + (acc * 1e-6).astype(jnp.bfloat16), k, v)
                return acc + jnp.sum(o.astype(jnp.float32)) * 1e-9
            return lax.fori_loop(0, N, body, jnp.float32(0.0))
        return run

    out = {}
    try:
        # inside the guard: this allocation OOMs first if earlier legs'
        # harness memory hasn't fully released, and a failed leg must
        # never take the whole bench's JSON down with it
        base = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.bfloat16)
        for name, f in (
            ("xla", lambda q, k, v: flash_attention_reference(
                q, k, v, causal=True)),
            ("pallas", lambda q, k, v: flash_attention(q, k, v, causal=True)),
        ):
            fn = loop(f)
            float(fn(base, base, base))  # compile + warm
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                float(fn(base, base, base))
                ts.append(time.perf_counter() - t0)
            out[name] = float(np.median(ts)) / N * 1e3
    except Exception as e:  # noqa: BLE001 — bench keeps going without it
        err = {"flash_attn_error": str(e)[:120]}
        if "xla" in out:  # keep the baseline leg that did complete
            err["flash_attn_xla_s2048_ms"] = round(out["xla"], 3)
        return err
    return {
        "flash_attn_s2048_ms": round(out["pallas"], 3),
        "flash_attn_xla_s2048_ms": round(out["xla"], 3),
        "flash_attn_speedup": round(out["xla"] / out["pallas"], 2),
    }


def _measure_native_client(url: str) -> dict:
    """Headline config through the native C++ client (tpu_perf_client):
    same server, same model, same c=8 closed loop.  Skipped (empty dict)
    when the CMake tree isn't built — the driver bench must not spend its
    window compiling C++."""
    import subprocess

    binary = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "native", "client", "build", "tpu_perf_client")
    if not os.path.exists(binary):
        return {}
    try:
        proc = subprocess.run(
            [binary, "-i", "grpc", "-u", url, "-m", "simple",
             "--concurrency-range", "8:8", "-p", "5000",
             "--warmup-ms", "1000", "--json"],
            capture_output=True, text=True, timeout=60)
        if proc.returncode != 0:
            return {"native_client_error":
                    f"rc={proc.returncode}: {proc.stderr.strip()[:100]}"}
        row = next(json.loads(l) for l in proc.stdout.splitlines()
                   if l.startswith("{"))
        return {
            "native_client_infer_per_sec": round(
                row["throughput_infer_per_sec"], 2),
            "native_client_p50_ms": round(row["latency_p50_us"] / 1e3, 3),
            "native_client_p99_ms": round(row["latency_p99_us"] / 1e3, 3),
        }
    except Exception as e:  # noqa: BLE001 — optional leg never kills bench
        return {"native_client_error": str(e)[:120]}


def main() -> int:
    from triton_client_tpu.grpc import InferenceServerClient, InferInput
    from triton_client_tpu.models import zoo
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness

    registry = ModelRegistry()
    zoo.register_all(registry)
    harness = ServerHarness(registry)
    harness.start()

    url = f"127.0.0.1:{harness.grpc_port}"

    def simple_inputs():
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        b = np.ones((1, 16), dtype=np.int32)
        i0 = InferInput("INPUT0", [1, 16], "INT32")
        i0.set_data_from_numpy(a)
        i1 = InferInput("INPUT1", [1, 16], "INT32")
        i1.set_data_from_numpy(b)
        return [i0, i1]

    def dense_inputs():
        x = np.random.default_rng(0).normal(size=(1, 512)).astype(np.float32)
        i = InferInput("INPUT", [1, 512], "FP32")
        i.set_data_from_numpy(x)
        return [i]

    # Blocking warm-up infer per model BEFORE any clock starts: the first
    # request pays XLA compilation (tens of seconds on the real chip), which
    # must never sit inside a measured latency.
    warm = InferenceServerClient(url)
    warm.infer("simple", simple_inputs())
    # Warm every preferred batch bucket: the batcher pads to bucket shapes so
    # XLA compiles a bounded set — each must be compiled before the clock runs.
    for b in (1, 8, 16, 32, 64):
        x = np.zeros((b, 512), np.float32)
        i = InferInput("INPUT", [b, 512], "FP32")
        i.set_data_from_numpy(x)
        warm.infer("dense_tpu", [i])
    warm.close()

    def sweep(model_name, inputs_fn, concurrency, warmup_s=1.0, measure_s=5.0,
              retry_policy=None):
        """perf_analyzer-style fixed-concurrency closed-loop sweep."""
        latencies: list = []
        counts = [0] * concurrency
        errors: list = []
        stop = threading.Event()
        start_measuring = threading.Event()

        def worker(idx: int):
            try:
                client = InferenceServerClient(url)
                inputs = inputs_fn()
                # wire fast path on: the headline measures the template
                # path (prepare once per worker, re-stamp per call) —
                # exactly what perf_analyzer sessions run
                prep = client.prepare(model_name, inputs)
                local_lat = []
                n = 0
                while not stop.is_set():
                    t0 = time.perf_counter()
                    prep.infer(retry_policy=retry_policy)
                    dt = time.perf_counter() - t0
                    if start_measuring.is_set():
                        local_lat.append(dt)
                        n += 1
                counts[idx] = n
                latencies.append(local_lat)
                client.close()
            except Exception as e:  # surface worker failures in the output
                errors.append(f"worker {idx}: {e}")

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(concurrency)]
        for t in threads:
            t.start()
        time.sleep(warmup_s)
        start_measuring.set()
        t0 = time.perf_counter()
        time.sleep(measure_s)
        stop.set()
        elapsed = time.perf_counter() - t0
        for t in threads:
            t.join(timeout=10)
        total = sum(counts)
        chunks = [np.asarray(l) for l in latencies if l]
        lat = np.sort(np.concatenate(chunks)) if chunks else np.empty((0,))
        return {
            "infer_per_sec": round(total / elapsed, 2),
            "p50_ms": round(float(np.percentile(lat, 50) * 1e3), 3) if lat.size else None,
            "p99_ms": round(float(np.percentile(lat, 99) * 1e3), 3) if lat.size else None,
            "errors": errors,
            "total": total,
        }

    # best-of-3 measurement windows: host-side run-to-run variance on this
    # shared bench machine is ~±20%, so a single 5s window under-reports.
    # Errors from ALL runs are kept — a flaky losing run must still fail.
    simple_runs = [sweep("simple", simple_inputs, concurrency=8)
                   for _ in range(3)]
    simple_res = max(simple_runs, key=lambda r: r["infer_per_sec"])
    simple_errors = [e for r in simple_runs for e in r["errors"]]
    # drift control, same session: no-compute RPC rate at the same c=8
    null_rpc = _measure_null_rpc(url)
    # wire fast-path attribution: build-vs-stamp, wrap-vs-batched-wrap,
    # and per-protocol null-RPC normalization (ISSUE 10 satellite)
    wire_breakdown = _measure_client_wire_breakdown(
        harness, simple_res["infer_per_sec"], null_rpc)
    # traced window, SEPARATE from the headline (awaited trace-file appends
    # would perturb it): the per-stage breakdown rides the bench record so
    # queue/compute/serialize share is visible round over round
    trace_breakdown = _measure_trace_breakdown(url, sweep, simple_inputs)
    # flight-recorder A/B, also separate from the headline: recorded vs
    # recorder-disabled windows bound the always-on layer's fast-path cost
    recorder_overhead = _measure_recorder_overhead(
        harness.core, sweep, simple_inputs)
    # device-stats A/B: tick profiling + per-execute accounting on vs off
    # (acceptance: <=1% of the headline c=8 throughput)
    tick_overhead = _measure_tick_profiler_overhead(
        harness.core, sweep, simple_inputs)
    # host-profiler A/B (ISSUE 18): stack sampling at the production
    # default rate vs paused (acceptance: <=2% of the headline c=8
    # throughput)
    host_profiler_overhead = _measure_host_profiler_overhead(
        harness.core, sweep, simple_inputs)
    # cost-ledger A/B: per-tenant device-time attribution on vs off
    # (acceptance: <=1% of the headline c=8 throughput)
    cost_overhead = _measure_cost_attribution_overhead(
        harness.core, sweep, simple_inputs)
    # resilience-layer A/B: RetryPolicy-wrapped vs plain infer on the
    # happy path (target <1% overhead; no faults injected here)
    resilience_overhead = _measure_resilience_overhead(sweep, simple_inputs)
    # same config through the NATIVE C++ client (tools/perf_client.cc) when
    # its binary is built — a cross-language drift control on the headline:
    # same server, same model, same c=8 closed loop, no client-side GIL
    native_metrics = _measure_native_client(url)
    # Device path, wire data: concurrency = 4x max batch so the dynamic
    # batcher forms full 64-batches AND up to 4 of them pipeline over the
    # device link (at 64 the closed loop admits exactly one batch in flight,
    # serializing on the device round trip).
    # Solo-latency reference BEFORE the heavy leg: the quiesce barrier
    # below must compare against an uncongested floor — comparing only
    # within its own samples mistakes "uniformly congested" for "drained"
    # (r3: the 256-concurrency backlog outlasted the barrier and starved
    # the xla-shm sweep to 0 completions).
    solo_probe = InferenceServerClient(url)
    qi = dense_inputs()
    solo = min(_timed_infer(solo_probe, "dense_tpu", qi) for _ in range(3))
    solo_probe.close()

    dense_res = sweep("dense_tpu", dense_inputs, concurrency=256, warmup_s=2.0)

    # Quiesce before the next device leg: the 256-concurrency closed loop
    # leaves pipelined batches draining through the tunnel after its window
    # closes, which previously inflated the xla-shm sweep's tail latencies
    # by 10-100x.  Drained = two consecutive probes near the PRE-congestion
    # solo latency (tunnel RTT drift tolerated via the 2x headroom).
    quiesce = InferenceServerClient(url)
    time.sleep(1.0)
    deadline = time.time() + 120.0
    last_two: list = []
    while time.time() < deadline:
        last_two.append(_timed_infer(quiesce, "dense_tpu", qi))
        last_two = last_two[-2:]
        if len(last_two) == 2 and max(last_two) < 2.0 * solo:
            break
        time.sleep(0.5)
    quiesce.close()

    # Device path, xla shared memory (the cudashm north star): tensors stay
    # device-resident end to end, so latency is decoupled from the tunnel's
    # blocking-readback floor.
    from triton_client_tpu.perf_analyzer import (_make_data, _resolve_model,
                                                 run_level)
    meta = InferenceServerClient(url)
    pa_inputs, pa_outputs, pa_max_batch = _resolve_model(
        meta, "grpc", "dense_tpu", "")
    meta.close()
    pa_arrays = _make_data(pa_inputs, {}, 1, pa_max_batch,
                           np.random.default_rng(0))
    shm_res = run_level("grpc", url, "dense_tpu", "", 8, pa_arrays,
                        pa_outputs, "xla", 1 << 20, 4.0, warmup_s=3.0)
    if shm_res["throughput"] == 0 and not shm_res["errors"]:
        # starved window (congested session: the 256-concurrency backlog
        # outlasted the quiesce barrier, or first-shm-request compile ate
        # the window) — one retry with a longer warmup, not a dead leg
        time.sleep(5.0)
        shm_res = run_level("grpc", url, "dense_tpu", "", 8, pa_arrays,
                            pa_outputs, "xla", 1 << 20, 4.0, warmup_s=8.0)

    bert_metrics = _measure_bert_mfu(harness)

    gen_metrics = _measure_generation(harness)

    # utilization summary AFTER every leg on the main harness ran (the
    # collector's windows/ticks now reflect the whole session): duty
    # cycle, live MFU, pad-waste — the perf trajectory's efficiency axis
    device_summary = _device_stats_summary(harness.core)
    # cost observability snapshot, same point in the session: roofline
    # verdicts per (model, bucket) + the per-tenant attribution totals
    cost_summary = _cost_summary(harness.core)

    rtt_floor_ms = _measure_rtt_floor()
    harness.stop()
    # drop the ONLY references to the stopped harness's registry so the
    # follow-on legs' gc.collect() can actually free its device arrays —
    # stop() alone keeps self.registry (and every placed param) alive
    harness = None
    registry = None
    # independent of the int8 leg's outcome, and after the main harness
    # released its device memory: same-precision batched-vs-independent
    # generation A/B + the bucketed c=64 capacity point
    gen_metrics.update(_measure_generation_ab())
    # decode-tick fast path (ISSUE 12): steps-per-dispatch A/B + per-token
    # host-overhead/upload/sync counters — CPU-runnable on the tiny preset
    gen_metrics["gen_tick_breakdown"] = _measure_gen_tick_breakdown()
    # streaming-trace overhead (ISSUE 15): generate_stream tok/s with
    # every stream traced vs tracing off, sync/upload counters unchanged
    gen_metrics["gen_trace_overhead"] = _measure_gen_trace_overhead()
    # int8 BERT serving (r5): own harness, env-resolved at first inference
    bert_metrics.update(_measure_bert_int8())
    # cluster client: routing + hedged-tail A/Bs on a 3-replica fleet
    cluster_metrics = _measure_cluster()
    # QoS A/B: tier-0 p99 with vs without priority tiers at 2x overload
    qos_metrics = _measure_qos_overload()
    # memory governor A/B (ISSUE 14): oversized burst at 2x byte budget,
    # governor on vs off — peak ledger bytes, typed sheds, tier-0 p99
    mem_metrics = _measure_mem_overload()
    # closed-loop fleet ops (ISSUE 13): recovery-time-to-SLO after a
    # seeded replica kill + a mid-run rolling update
    fleet_metrics = _measure_fleet_ops()
    # server wire fast path (ISSUE 11): response encode-vs-stamp, per-
    # protocol null-RPC floors, and --frontends N SO_REUSEPORT scaling —
    # own CLI servers, after the main harness released its resources
    server_wire = _measure_server_wire_breakdown()

    baseline = _previous_baseline()
    value = simple_res["infer_per_sec"]
    errors = simple_errors + dense_res["errors"]
    if shm_res["errors"]:
        errors.append(
            f"xla-shm sweep: {shm_res['errors']} errors: "
            f"{shm_res['first_error']}")
    out = {
        "metric": "grpc_infer_throughput_simple_c8",
        "value": value,
        "unit": "infer/sec",
        "vs_baseline": round(value / baseline, 3) if baseline else 1.0,
        "p50_ms": simple_res["p50_ms"],
        "p99_ms": simple_res["p99_ms"],
        "tpu_batched_infer_per_sec": dense_res["infer_per_sec"],
        "tpu_batched_p50_ms": dense_res["p50_ms"],
        "tpu_batched_p99_ms": dense_res["p99_ms"],
        # None (JSON null), not NaN, when the sweep produced no samples —
        # the output must stay strict JSON
        "tpu_xlashm_infer_per_sec": round(shm_res["throughput"], 2),
        "tpu_xlashm_p50_ms": (round(shm_res["p50_us"] / 1e3, 3)
                              if np.isfinite(shm_res["p50_us"]) else None),
        "tpu_xlashm_p99_ms": (round(shm_res["p99_us"] / 1e3, 3)
                              if np.isfinite(shm_res["p99_us"]) else None),
        "tpu_rtt_floor_ms": round(rtt_floor_ms, 3),
        "concurrency": 8,
        "tpu_concurrency": 256,
        # drift control: headline normalized by the same-session null-RPC
        # floor — read vs_baseline against this when the raw number moves
        "null_rpc_per_sec_c8": null_rpc,
        "value_per_null_rpc": (round(value / null_rpc, 4)
                               if null_rpc else None),
    }
    out.update(native_metrics)
    # per-call client cost decomposition (build/stamp vs wrap vs
    # transport) + per-protocol value_per_null_rpc
    out.update(wire_breakdown)
    # server-side mirror: encode/stamp µs + multi-process frontend scaling
    out.update(server_wire)
    out.update(bert_metrics)
    out.update(gen_metrics)
    out.update(_measure_flash_attention())
    # server-side per-stage breakdown from the traced window (span tracing):
    # queue vs compute vs serialize share next to the client-observed numbers
    out.update(trace_breakdown)
    # always-on flight recorder: recorded-vs-disabled window delta
    out.update(recorder_overhead)
    # device-stats layer: tick-profiler on/off delta + utilization summary
    out.update(tick_overhead)
    # host layer: sampling-profiler on/off delta (ISSUE 18)
    out.update(host_profiler_overhead)
    out.update(device_summary)
    # cost observability: ledger on/off delta + roofline verdicts and the
    # per-tenant attribution snapshot
    out.update(cost_overhead)
    out.update(cost_summary)
    # client resilience layer: retry-wrapped vs plain happy-path delta
    out.update(resilience_overhead)
    # cluster routing + hedging tail: the client-side fleet layer's numbers
    out.update(cluster_metrics)
    # multi-tenant QoS: the graceful-degradation A/B under overload
    out.update(qos_metrics)
    # memory governor: the byte-budget overload A/B
    out.update(mem_metrics)
    # fleet operations: kill-recovery + rolling-update drill numbers
    out.update(fleet_metrics)
    # client-side telemetry (the instrumented clients recorded every leg):
    # a compact per-(protocol, method, model) view so the bench record
    # carries client-observed p50/p99 next to the server-derived numbers
    out["client_telemetry"] = _client_telemetry_summary()
    if errors:
        out["errors"] = errors[:4]
    print(json.dumps(out))
    ok = (simple_res["total"] and dense_res["total"] and shm_res["throughput"]
          and not errors)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
