"""Server harness tests driven with `requests` as an independent HTTP oracle
(our own client gets its own test file; testing the server against a neutral
library pins the wire protocol, not our client's interpretation of it)."""

import json
import struct

import numpy as np
import pytest
import requests

from triton_client_tpu.models import zoo
from triton_client_tpu.server import ModelRegistry
from triton_client_tpu.server.testing import ServerHarness


@pytest.fixture(scope="module")
def server():
    registry = ModelRegistry()
    zoo.register_all(registry)
    with ServerHarness(registry) as h:
        yield h


def _url(server, path):
    return f"http://{server.http_url}{path}"


class TestHealthMetadata:
    def test_live_ready(self, server):
        assert requests.get(_url(server, "/v2/health/live")).status_code == 200
        assert requests.get(_url(server, "/v2/health/ready")).status_code == 200

    def test_model_ready(self, server):
        assert requests.get(_url(server, "/v2/models/simple/ready")).status_code == 200
        assert requests.get(_url(server, "/v2/models/nope/ready")).status_code == 400

    def test_server_metadata(self, server):
        md = requests.get(_url(server, "/v2")).json()
        assert md["name"] == "triton_client_tpu_harness"
        assert "system_shared_memory" in md["extensions"]
        assert "xla_shared_memory" in md["extensions"]

    def test_model_metadata(self, server):
        md = requests.get(_url(server, "/v2/models/simple")).json()
        assert md["name"] == "simple"
        assert md["inputs"][0] == {"name": "INPUT0", "datatype": "INT32", "shape": [1, 16]}

    def test_model_config(self, server):
        cfg = requests.get(_url(server, "/v2/models/simple/config")).json()
        assert cfg["name"] == "simple"
        assert cfg["input"][0]["data_type"] == "TYPE_INT32"

    def test_unknown_model_404ish(self, server):
        r = requests.get(_url(server, "/v2/models/nope"))
        assert r.status_code == 400
        assert "error" in r.json()

    def test_repository_index(self, server):
        r = requests.post(_url(server, "/v2/repository/index"), json={})
        names = {m["name"] for m in r.json()}
        assert {"simple", "simple_identity", "repeat_int32"} <= names


def _infer_binary(server, model, inputs, outputs=None, parameters=None):
    """Hand-rolled v2 binary-protocol request (protocol oracle)."""
    header = {"inputs": [], "outputs": outputs or []}
    if parameters:
        header["parameters"] = parameters
    blobs = []
    for name, arr in inputs:
        from triton_client_tpu.utils import np_to_triton_dtype

        blob = arr.tobytes()
        header["inputs"].append(
            {
                "name": name,
                "datatype": np_to_triton_dtype(arr.dtype),
                "shape": list(arr.shape),
                "parameters": {"binary_data_size": len(blob)},
            }
        )
        blobs.append(blob)
    jb = json.dumps(header).encode()
    body = jb + b"".join(blobs)
    r = requests.post(
        _url(server, f"/v2/models/{model}/infer"),
        data=body,
        headers={"Inference-Header-Content-Length": str(len(jb))},
    )
    return r


def _parse_binary_response(r):
    hl = int(r.headers["Inference-Header-Content-Length"])
    header = json.loads(r.content[:hl])
    binary = r.content[hl:]
    outs = {}
    offset = 0
    for o in header["outputs"]:
        size = o.get("parameters", {}).get("binary_data_size")
        if size is None:
            outs[o["name"]] = (o, None)
            continue
        outs[o["name"]] = (o, binary[offset : offset + size])
        offset += size
    return header, outs


class TestInfer:
    def test_simple_binary(self, server):
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        b = np.ones((1, 16), dtype=np.int32)
        r = _infer_binary(server, "simple", [("INPUT0", a), ("INPUT1", b)])
        assert r.status_code == 200, r.text
        header, outs = _parse_binary_response(r)
        assert header["model_name"] == "simple"
        o0 = np.frombuffer(outs["OUTPUT0"][1], dtype=np.int32).reshape(1, 16)
        o1 = np.frombuffer(outs["OUTPUT1"][1], dtype=np.int32).reshape(1, 16)
        np.testing.assert_array_equal(o0, a + b)
        np.testing.assert_array_equal(o1, a - b)

    def test_simple_json(self, server):
        body = {
            "inputs": [
                {
                    "name": "INPUT0",
                    "datatype": "INT32",
                    "shape": [1, 4],
                    "data": [[1, 2, 3, 4]],
                },
                {
                    "name": "INPUT1",
                    "datatype": "INT32",
                    "shape": [1, 4],
                    "data": [[10, 20, 30, 40]],
                },
            ]
        }
        # 'simple' is fixed [1,16]; use identity model with dynamic dims for JSON
        body["inputs"] = body["inputs"][:1]
        body["inputs"][0]["shape"] = [1, 4]
        r = requests.post(
            _url(server, "/v2/models/custom_identity_int32/infer"), json=body
        )
        assert r.status_code == 200, r.text
        out = r.json()["outputs"][0]
        assert out["data"] == [1, 2, 3, 4]
        assert out["shape"] == [1, 4]

    def test_bytes_model(self, server):
        arr = np.array([[b"hello", b"world"]], dtype=np.object_)
        from triton_client_tpu.utils import serialize_byte_tensor

        blob = serialize_byte_tensor(arr).tobytes()
        header = {
            "inputs": [
                {
                    "name": "INPUT0",
                    "datatype": "BYTES",
                    "shape": [1, 2],
                    "parameters": {"binary_data_size": len(blob)},
                }
            ],
            "outputs": [{"name": "OUTPUT0", "parameters": {"binary_data": True}}],
        }
        jb = json.dumps(header).encode()
        r = requests.post(
            _url(server, "/v2/models/simple_identity/infer"),
            data=jb + blob,
            headers={"Inference-Header-Content-Length": str(len(jb))},
        )
        assert r.status_code == 200, r.text
        _, outs = _parse_binary_response(r)
        raw = outs["OUTPUT0"][1]
        assert struct.unpack_from("<I", raw, 0)[0] == 5
        assert raw[4:9] == b"hello"

    def test_shape_mismatch_error(self, server):
        a = np.zeros((1, 8), dtype=np.int32)
        r = _infer_binary(server, "simple", [("INPUT0", a), ("INPUT1", a)])
        assert r.status_code == 400
        assert "unexpected shape" in r.json()["error"]

    def test_dtype_mismatch_error(self, server):
        a = np.zeros((1, 16), dtype=np.float32)
        r = _infer_binary(server, "simple", [("INPUT0", a), ("INPUT1", a)])
        assert r.status_code == 400
        assert "data-type" in r.json()["error"]

    def test_missing_input_error(self, server):
        a = np.zeros((1, 16), dtype=np.int32)
        r = _infer_binary(server, "simple", [("INPUT0", a)])
        assert r.status_code == 400

    def test_decoupled_rejected_on_http(self, server):
        a = np.array([3], dtype=np.int32)
        r = _infer_binary(server, "square_int32", [("IN", a)])
        assert r.status_code == 400
        assert "decoupled" in r.json()["error"]

    def test_statistics_accumulate(self, server):
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        before = requests.get(_url(server, "/v2/models/simple/stats")).json()
        n0 = before["model_stats"][0]["inference_count"]
        _infer_binary(server, "simple", [("INPUT0", a), ("INPUT1", a)])
        after = requests.get(_url(server, "/v2/models/simple/stats")).json()
        assert after["model_stats"][0]["inference_count"] == n0 + 1

    def test_gzip_request(self, server):
        import gzip as gz

        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        blob = a.tobytes()
        header = {
            "inputs": [
                {
                    "name": "INPUT0",
                    "datatype": "INT32",
                    "shape": [1, 16],
                    "parameters": {"binary_data_size": len(blob)},
                },
                {
                    "name": "INPUT1",
                    "datatype": "INT32",
                    "shape": [1, 16],
                    "parameters": {"binary_data_size": len(blob)},
                },
            ]
        }
        jb = json.dumps(header).encode()
        body = gz.compress(jb + blob + blob)
        r = requests.post(
            _url(server, "/v2/models/simple/infer"),
            data=body,
            headers={
                "Inference-Header-Content-Length": str(len(jb)),
                "Content-Encoding": "gzip",
            },
        )
        assert r.status_code == 200, r.text
        _, outs = _parse_binary_response(r)
        o0 = np.frombuffer(outs["OUTPUT0"][1], dtype=np.int32).reshape(1, 16)
        np.testing.assert_array_equal(o0, a + a)


class TestModelControl:
    def test_load_unload_cycle(self, server):
        url = _url(server, "/v2/repository/models/custom_identity_int32/unload")
        assert requests.post(url, json={}).status_code == 200
        assert (
            requests.get(_url(server, "/v2/models/custom_identity_int32/ready")).status_code
            == 400
        )
        url = _url(server, "/v2/repository/models/custom_identity_int32/load")
        assert requests.post(url, json={}).status_code == 200
        assert (
            requests.get(_url(server, "/v2/models/custom_identity_int32/ready")).status_code
            == 200
        )

    def test_trace_settings(self, server):
        r = requests.get(_url(server, "/v2/trace/setting"))
        assert r.json()["trace_level"] == ["OFF"]
        r = requests.post(
            _url(server, "/v2/trace/setting"), json={"trace_level": ["TIMESTAMPS"]}
        )
        assert r.json()["trace_level"] == ["TIMESTAMPS"]
        requests.post(_url(server, "/v2/trace/setting"), json={"trace_level": ["OFF"]})

    def test_log_settings(self, server):
        r = requests.post(_url(server, "/v2/logging"), json={"log_verbose_level": 1})
        assert r.json()["log_verbose_level"] == 1
