"""Active server logging behind the log-settings API.

Settings registered through the client make the server actually emit log
lines (before r4 the dict was store-and-return-only, the same
accepted-but-inert pattern the trace API had).  Round-trip of the settings
dict is covered in the protocol suites; this file asserts the effect.
"""

import re

import numpy as np
import pytest

import triton_client_tpu.http as httpclient
from triton_client_tpu.models import zoo
from triton_client_tpu.server import ModelRegistry
from triton_client_tpu.server.testing import ServerHarness
from triton_client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def server():
    registry = ModelRegistry()
    zoo.register_all(registry)
    with ServerHarness(registry) as h:
        yield h


@pytest.fixture()
def client(server):
    with httpclient.InferenceServerClient(server.http_url, concurrency=2) as c:
        yield c


@pytest.fixture(autouse=True)
def _defaults_after(client):
    yield
    client.update_log_settings({
        "log_file": "", "log_info": True, "log_warning": True,
        "log_error": True, "log_verbose_level": 0, "log_format": "default"})


def _poll_log(path, *needles, timeout_s=10.0):
    """Wait for every needle to appear in the log file and return its
    text.  Lifecycle lines (load/unload) ride the executor off the event
    loop — the ASYNC-BLOCK invariant — so they land *after* the control
    response; read-after-response must poll, not assume."""
    import time

    deadline = time.time() + timeout_s
    text = ""
    while time.time() < deadline:
        text = path.read_text() if path.exists() else ""
        if all(n in text for n in needles):
            return text
        time.sleep(0.02)
    return text


def _simple_inputs():
    a = np.arange(16, dtype=np.int32).reshape(1, 16)
    inputs = [
        httpclient.InferInput("INPUT0", [1, 16], "INT32"),
        httpclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(a)
    inputs[1].set_data_from_numpy(a)
    return inputs


class TestServerLog:
    def test_load_unload_logged_default_format(self, client, tmp_path):
        lf = tmp_path / "server.log"
        client.update_log_settings({"log_file": str(lf)})
        client.unload_model("identity_fp32")
        client.load_model("identity_fp32")
        text = _poll_log(lf,
                         "successfully unloaded model 'identity_fp32'",
                         "successfully loaded model 'identity_fp32'")
        assert "successfully unloaded model 'identity_fp32'" in text
        assert "successfully loaded model 'identity_fp32'" in text
        # off-loop emits drain FIFO (single-worker log executor): the
        # unload line lands before the load line, same as the sync days
        assert (text.index("successfully unloaded model 'identity_fp32'")
                < text.index("successfully loaded model 'identity_fp32'"))
        # default format: level letter + MMDD + wall clock with microseconds
        assert re.search(r"^I\d{4} \d{2}:\d{2}:\d{2}\.\d{6} ", text, re.M)

    def test_iso8601_format(self, client, tmp_path):
        lf = tmp_path / "iso.log"
        client.update_log_settings({"log_file": str(lf),
                                    "log_format": "ISO8601"})
        client.unload_model("identity_fp32")
        client.load_model("identity_fp32")
        text = _poll_log(lf, "successfully loaded model 'identity_fp32'")
        assert re.search(r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}Z I ",
                         text, re.M)

    def test_json_format_one_object_per_line(self, client, tmp_path):
        """log_format=json: every line is one JSON object with level/ts/msg,
        and per-request lines carry the propagated triton-request-id — so
        structured logs join trace files on the same key."""
        import json
        import time

        lf = tmp_path / "json.log"
        client.update_log_settings({"log_file": str(lf),
                                    "log_format": "json",
                                    "log_verbose_level": 1})
        client.infer("simple", _simple_inputs())
        client.unload_model("identity_fp32")
        client.load_model("identity_fp32")
        text = _poll_log(lf, "/infer -> 200", "successfully loaded")
        records = [json.loads(l) for l in text.splitlines() if l.strip()]
        assert records, "no JSON log lines written"
        for rec in records:
            assert {"level", "ts", "msg"} <= set(rec)
            assert rec["level"] in ("info", "warning", "error")
            assert isinstance(rec["ts"], float)
        infer_recs = [r for r in records if "/infer -> 200" in r["msg"]]
        assert infer_recs
        # the client stamps triton-request-id on every inference; the
        # frontend threads it onto the request's log lines
        assert infer_recs[0].get("request_id")
        # lifecycle lines outside any request carry no request_id
        load_recs = [r for r in records if "successfully loaded" in r["msg"]]
        assert load_recs and "request_id" not in load_recs[0]

    def test_log_info_gate_suppresses(self, client, tmp_path):
        lf = tmp_path / "gated.log"
        client.update_log_settings({"log_file": str(lf), "log_info": False})
        client.unload_model("identity_fp32")
        client.load_model("identity_fp32")
        # negative assertion with a grace window: lifecycle lines land via
        # the executor, so "nothing right now" alone would pass vacuously
        text = _poll_log(lf, "successfully", timeout_s=0.5)
        assert "successfully" not in text

    def test_grpc_requests_logged_too(self, server, client, tmp_path):
        """Log-settings-driven lines exist on BOTH protocols — an operator
        tailing the log must see gRPC traffic, not just HTTP."""
        import time

        import triton_client_tpu.grpc as grpcclient

        lf = tmp_path / "grpc.log"
        client.update_log_settings({"log_file": str(lf),
                                    "log_verbose_level": 1})
        with grpcclient.InferenceServerClient(server.grpc_url) as gc:
            inputs = [
                grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
                grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
            ]
            a = np.arange(16, dtype=np.int32).reshape(1, 16)
            inputs[0].set_data_from_numpy(a)
            inputs[1].set_data_from_numpy(a)
            gc.infer("simple", inputs)
            with pytest.raises(InferenceServerException):
                gc.infer("nope", inputs)
        text = _poll_log(lf, "grpc ModelInfer 'simple' -> OK",
                         "grpc ModelInfer 'nope' -> 400")
        assert "grpc ModelInfer 'simple' -> OK" in text
        assert "grpc ModelInfer 'nope' -> 400" in text

    def test_grpc_unload_load_logged_off_loop(self, server, client,
                                              tmp_path):
        """Lifecycle lines from the gRPC control plane land too — via the
        executor (the ASYNC-BLOCK fix: RepositoryModelUnload used to
        append to the log file directly on the event loop)."""
        import triton_client_tpu.grpc as grpcclient

        lf = tmp_path / "grpc_lifecycle.log"
        client.update_log_settings({"log_file": str(lf)})
        with grpcclient.InferenceServerClient(server.grpc_url) as gc:
            gc.unload_model("identity_fp32")
            gc.load_model("identity_fp32")
        text = _poll_log(lf,
                         "successfully unloaded model 'identity_fp32'",
                         "successfully loaded model 'identity_fp32'")
        assert "successfully unloaded model 'identity_fp32'" in text
        assert "successfully loaded model 'identity_fp32'" in text

    def test_verbose_level_logs_requests(self, client, tmp_path):
        import time

        lf = tmp_path / "verbose.log"
        client.update_log_settings({"log_file": str(lf),
                                    "log_verbose_level": 1})
        client.infer("simple", _simple_inputs())
        with pytest.raises(InferenceServerException):
            client.get_model_metadata("nope")  # 400: verbose line, not error
        text = _poll_log(lf, "POST /v2/models/simple/infer -> 200",
                         "GET /v2/models/nope -> 400")
        assert re.search(r"POST /v2/models/simple/infer -> 200", text)
        assert re.search(r"GET /v2/models/nope -> 400", text)
        # verbosity off: requests stop appearing (both prior lines already
        # confirmed flushed above, so the count is race-free)
        client.update_log_settings({"log_verbose_level": 0})
        client.infer("simple", _simple_inputs())
        time.sleep(0.3)
        lines = [l for l in lf.read_text().splitlines()
                 if "/infer -> 200" in l]
        assert len(lines) == 1
