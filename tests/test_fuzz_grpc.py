"""gRPC wire-level robustness: malformed protobufs, oversize messages, and
truncated payloads must never crash the server or hang a connection —
every outcome is a clean gRPC status code (ISSUE 14's fuzz satellite,
the gRPC sibling of tests/test_fuzz_http.py).

The server under test runs a small ``--max-request-bytes`` so oversize
rejection is exercisable without allocating real 64 MiB payloads: the
channel-option cap refuses the message at the transport
(RESOURCE_EXHAUSTED carrying both sizes) before the handler runs.
"""

import random
import socket

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import grpc as grpc_mod  # noqa: E402

import triton_client_tpu.grpc as grpcclient  # noqa: E402
from triton_client_tpu.models import zoo  # noqa: E402
from triton_client_tpu.protocol import (GRPCInferenceServiceStub,  # noqa: E402
                                        SERVICE_NAME)
from triton_client_tpu.protocol import inference_pb2 as pb  # noqa: E402
from triton_client_tpu.server import ModelRegistry  # noqa: E402
from triton_client_tpu.server.testing import ServerHarness  # noqa: E402

CAP = 256 << 10  # small wire cap so oversize cases stay cheap


@pytest.fixture(scope="module")
def server():
    registry = ModelRegistry()
    zoo.register_all(registry)
    with ServerHarness(registry, max_request_bytes=CAP) as h:
        yield h


def _alive(server) -> bool:
    """The server still serves a clean inference after the abuse."""
    with grpcclient.InferenceServerClient(server.grpc_url) as c:
        a = np.ones((1, 16), np.int32)
        i0 = grpcclient.InferInput("INPUT0", [1, 16], "INT32")
        i0.set_data_from_numpy(a)
        i1 = grpcclient.InferInput("INPUT1", [1, 16], "INT32")
        i1.set_data_from_numpy(a)
        r = c.infer("simple", [i0, i1])
        return bool((r.as_numpy("OUTPUT0") == 2).all())


def _channel(server):
    return grpc_mod.insecure_channel(server.grpc_url)


def _simple_request(per_input_bytes=64):
    req = pb.ModelInferRequest(model_name="simple")
    for name in ("INPUT0", "INPUT1"):
        t = req.inputs.add(name=name, datatype="INT32")
        t.shape.extend([1, 16])
        req.raw_input_contents.append(b"\x00" * per_input_bytes)
    return req


class TestOversizeMessages:
    def test_over_cap_is_resource_exhausted_with_limit(self, server):
        """A message past --max-request-bytes is refused by the channel
        option BEFORE the handler runs — RESOURCE_EXHAUSTED whose details
        carry both sizes, never a connection reset."""
        channel = _channel(server)
        try:
            stub = GRPCInferenceServiceStub(channel)
            req = _simple_request()
            req.raw_input_contents[0] = b"\x00" * (CAP + (64 << 10))
            with pytest.raises(grpc_mod.RpcError) as e:
                stub.ModelInfer(req, timeout=30)
            assert e.value.code() == grpc_mod.StatusCode.RESOURCE_EXHAUSTED
            assert str(CAP) in (e.value.details() or "")
        finally:
            channel.close()
        assert _alive(server)

    def test_under_cap_boundary_still_serves(self, server):
        """Near-cap (but valid) messages pass: the cap refuses giants,
        not legitimate large tensors."""
        with grpcclient.InferenceServerClient(server.grpc_url) as c:
            n = (CAP // 2) // 4  # half the cap in int32s
            arr = np.zeros((1, n), np.int32)
            i = grpcclient.InferInput("INPUT0", [1, n], "INT32")
            i.set_data_from_numpy(arr)
            r = c.infer("custom_identity_int32", [i])
            assert r.as_numpy("OUTPUT0").shape == (1, n)
        assert _alive(server)

    def test_oversize_not_retried_by_policy(self, server):
        """Satellite regression: a RetryPolicy with RESOURCE_EXHAUSTED in
        its (default) retryable set must NOT re-send an oversize payload —
        the transport rejection is deterministic."""
        from triton_client_tpu._resilience import RetryPolicy

        calls = []
        with grpcclient.InferenceServerClient(server.grpc_url) as c:
            n = (CAP + (64 << 10)) // 4
            arr = np.zeros((1, n), np.int32)
            i = grpcclient.InferInput("INPUT0", [1, n], "INT32")
            i.set_data_from_numpy(arr)
            policy = RetryPolicy(max_attempts=3, retry_infer=True, seed=0)
            orig = policy.should_retry

            def spy(exc, method, attempt):
                verdict = orig(exc, method, attempt)
                calls.append((attempt, verdict))
                return verdict

            policy.should_retry = spy
            with pytest.raises(Exception):
                c.infer("custom_identity_int32", [i], retry_policy=policy)
        # exactly one attempt ever ran: the classifier refused the retry
        assert calls and all(v is False for _, v in calls)
        assert max(a for a, _ in calls) == 1


class TestMalformedProtobuf:
    def test_garbage_bytes_get_clean_status(self, server):
        """Seeded garbage through the raw method path: the server's
        deserializer must answer a status, never crash or hang."""
        rng = random.Random(4242)
        channel = _channel(server)
        try:
            call = channel.unary_unary(
                f"/{SERVICE_NAME}/ModelInfer",
                request_serializer=lambda b: b,       # ship raw bytes
                response_deserializer=lambda b: b)
            for i in range(40):
                blob = bytes(rng.getrandbits(8)
                             for _ in range(rng.randint(1, 512)))
                try:
                    call(blob, timeout=30)
                except grpc_mod.RpcError as e:
                    # any CLEAN status is acceptable; a hang (DEADLINE from
                    # our own 30s timeout) or a torn connection is not
                    assert e.code() not in (
                        grpc_mod.StatusCode.DEADLINE_EXCEEDED,
                        grpc_mod.StatusCode.UNAVAILABLE), (i, e.code())
        finally:
            channel.close()
        assert _alive(server)

    def test_truncated_and_mismatched_raw_contents(self, server):
        """raw_input_contents truncation in every direction: fewer entries
        than inputs, more entries than inputs, and entries shorter than
        the dtype demands — all INVALID_ARGUMENT."""
        cases = []
        r1 = _simple_request()
        del r1.raw_input_contents[1]          # fewer raws than inputs
        cases.append(r1)
        r2 = _simple_request()
        r2.raw_input_contents.append(b"\x00")  # more raws than inputs
        cases.append(r2)
        r3 = _simple_request(per_input_bytes=7)  # not 16 int32s
        cases.append(r3)
        channel = _channel(server)
        try:
            stub = GRPCInferenceServiceStub(channel)
            for i, req in enumerate(cases):
                with pytest.raises(grpc_mod.RpcError) as e:
                    stub.ModelInfer(req, timeout=30)
                assert e.value.code() == \
                    grpc_mod.StatusCode.INVALID_ARGUMENT, (i, e.value.code())
        finally:
            channel.close()
        assert _alive(server)

    def test_hostile_field_values(self, server):
        """Adversarial but well-formed protobufs: absurd shapes, empty
        names, negative dims, junk dtypes — clean INVALID_ARGUMENT /
        NOT_FOUND, never INTERNAL or UNKNOWN."""
        rng = random.Random(77)
        channel = _channel(server)
        try:
            stub = GRPCInferenceServiceStub(channel)
            for i in range(30):
                req = pb.ModelInferRequest(
                    model_name=rng.choice(["simple", "", "nope"]))
                t = req.inputs.add(
                    name=rng.choice(["INPUT0", "", "X" * 100]),
                    datatype=rng.choice(["INT32", "NOPE", "", "BYTES"]))
                t.shape.extend(rng.choice(
                    [[1, 16], [-1, -1], [0], [1 << 40], []]))
                req.raw_input_contents.append(
                    bytes(rng.getrandbits(8)
                          for _ in range(rng.randint(0, 64))))
                try:
                    stub.ModelInfer(req, timeout=30)
                except grpc_mod.RpcError as e:
                    assert e.code() in (
                        grpc_mod.StatusCode.INVALID_ARGUMENT,
                        grpc_mod.StatusCode.NOT_FOUND,
                        grpc_mod.StatusCode.RESOURCE_EXHAUSTED), \
                        (i, e.code(), e.details())
        finally:
            channel.close()
        assert _alive(server)


class TestRawSocket:
    def test_non_grpc_bytes_then_hard_close(self, server):
        """Raw garbage at the gRPC port (not even HTTP/2) plus an abrupt
        close — the listener must survive and keep serving."""
        for payload in (
            b"GET / HTTP/1.1\r\n\r\n",
            b"\x00" * 64,
            b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n" + b"\xff" * 32,
        ):
            s = socket.create_connection(
                ("127.0.0.1", server.grpc_port), timeout=10)
            try:
                s.sendall(payload)
            finally:
                s.close()
        assert _alive(server)
