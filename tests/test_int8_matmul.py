"""Fused quantize+int8-matmul pallas kernel (ops/int8_matmul.py).

The kernel's math must be the XLA int8 serving path's math exactly: same
per-row dynamic scale, same round/clip, same s32 accumulation, same
dequant epilogue — so the encoder's int8 closeness guarantees
(test_transformer.py::TestInt8EncoderServing) transfer unchanged when the
FFN matmuls switch to the kernel.  Runs in the pallas interpreter on CPU.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import importlib

from triton_client_tpu.ops import int8_matmul, int8_matmul_reference

_mod = importlib.import_module("triton_client_tpu.ops.int8_matmul")


def _mk(m, k, n, seed=0, dtype=jnp.bfloat16):
    kx, kw, ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    x = jax.random.normal(kx, (m, k), dtype)
    w = jax.random.randint(kw, (k, n), -127, 128, jnp.int8)
    ws = (jnp.abs(jax.random.normal(ks, (n,), jnp.float32)) + 0.01) * 0.02
    return x, w, ws


class TestKernelMatchesReference:
    def test_exact_vs_reference(self):
        x, w, ws = _mk(64, 256, 128)
        got = int8_matmul(x, w, ws, block_m=32, block_n=128, interpret=True)
        want = int8_matmul_reference(x, w, ws)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=1e-2, atol=1e-3)

    def test_padded_m(self):
        # M=50 not a multiple of block_m: kernel pads rows with zeros and
        # slices them off; padded rows must not perturb real ones
        x, w, ws = _mk(50, 128, 128, seed=1)
        got = int8_matmul(x, w, ws, block_m=32, block_n=128, interpret=True)
        want = int8_matmul_reference(x, w, ws)
        assert got.shape == (50, 128)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=1e-2, atol=1e-3)

    def test_batched_leading_dims(self):
        x, w, ws = _mk(48, 128, 256, seed=2)
        x3 = x.reshape(4, 12, 128)
        got = int8_matmul(x3, w, ws, block_m=16, block_n=128, interpret=True)
        want = int8_matmul_reference(x3, w, ws)
        assert got.shape == (4, 12, 256)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=1e-2, atol=1e-3)

    def test_scale_shape_row_vector(self):
        # w_scale arrives as [1, N] from the transformer's scanned
        # *_scale leaves; [N] and [1, N] must agree
        x, w, ws = _mk(32, 128, 128, seed=3)
        a = int8_matmul(x, w, ws, block_m=32, block_n=128, interpret=True)
        b = int8_matmul(x, w, ws.reshape(1, -1),
                        block_m=32, block_n=128, interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestFallbacks:
    def test_cpu_backend_uses_reference(self):
        # no interpret/force on CPU -> identical to reference (bitwise)
        x, w, ws = _mk(16, 128, 128, seed=4)
        got = int8_matmul(x, w, ws)
        want = int8_matmul_reference(x, w, ws)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_unaligned_k_falls_back(self):
        # K % 128 != 0 can't take the kernel; reference path, right answer
        x, w, ws = _mk(16, 96, 128, seed=5)
        got = int8_matmul(x, w, ws, interpret=True)
        want = int8_matmul_reference(x, w, ws)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_huge_k_falls_back(self, monkeypatch):
        monkeypatch.setattr(_mod, "_MAX_RESIDENT_K", 64)
        x, w, ws = _mk(16, 128, 128, seed=6)
        got = int8_matmul(x, w, ws, interpret=True)
        want = int8_matmul_reference(x, w, ws)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestQuantizationSemantics:
    def test_per_row_scale_isolation(self):
        # a huge outlier in one row must not change other rows' results
        x, w, ws = _mk(32, 128, 128, seed=7, dtype=jnp.float32)
        x_hot = x.at[3].multiply(1000.0)
        base = np.asarray(int8_matmul_reference(x, w, ws))
        hot = np.asarray(int8_matmul_reference(x_hot, w, ws))
        np.testing.assert_array_equal(np.delete(base, 3, 0),
                                      np.delete(hot, 3, 0))

    def test_int32_accumulation_no_overflow(self):
        # worst-case rows (all ±127 after quantize) at K=8192 stay inside
        # s32: 127*127*8192 = 1.3e8 << 2^31
        k = 8192
        x = jnp.ones((8, k), jnp.float32)
        w = jnp.full((k, 128), 127, jnp.int8)
        ws = jnp.ones((128,), jnp.float32)
        out = np.asarray(int8_matmul_reference(x, w, ws), np.float64)
        assert np.all(np.isfinite(out))
        np.testing.assert_allclose(out, 127.0 * k, rtol=1e-6)


class TestFusedModeSelection:
    """TRITON_TPU_INT8_FUSED drives which FFN matmuls take the kernel in
    the encoder's int8 path (models/transformer.py:_int8_fused_mode)."""

    def _mode(self, monkeypatch, val):
        from triton_client_tpu.models import transformer as tr
        if val is None:
            monkeypatch.delenv("TRITON_TPU_INT8_FUSED", raising=False)
        else:
            monkeypatch.setenv("TRITON_TPU_INT8_FUSED", val)
        return tr._int8_fused_mode()

    def test_default_is_w2_only(self, monkeypatch):
        # the measured default: FFN-down wins, FFN-up loses
        # (benchmarks/BERT_PROFILE.md §6)
        assert self._mode(monkeypatch, None) == frozenset(("w2",))

    def test_off_and_all(self, monkeypatch):
        assert self._mode(monkeypatch, "0") == frozenset()
        assert self._mode(monkeypatch, "1") == frozenset(("w1", "w2"))
        assert self._mode(monkeypatch, "all") == frozenset(("w1", "w2"))
        assert self._mode(monkeypatch, "w1,w2") == frozenset(("w1", "w2"))

    def test_weight_resident_default_blocks(self):
        # K>=2048 with a <=4MB weight picks the weight-resident schedule
        # (block_n = N); kernel output still matches the reference
        x, w, ws = _mk(16, 2048, 128, seed=8)
        got = int8_matmul(x, w, ws, interpret=True)
        want = int8_matmul_reference(x, w, ws)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=1e-2, atol=1e-3)

    def test_unknown_selector_rejected(self, monkeypatch):
        # typos must fail loudly, not silently disable the kernel
        with pytest.raises(ValueError, match="unknown selector"):
            self._mode(monkeypatch, "ffn_down")
        # case-insensitive: W2 means w2
        assert self._mode(monkeypatch, "W2") == frozenset(("w2",))

    def test_auto_block_n_divides_n(self):
        # N=640 passes the N%128 gate but 640 % 512 != 0 — auto selection
        # drops to the largest dividing block (128) and the kernel runs
        x, w, ws = _mk(16, 128, 640, seed=9)
        got = int8_matmul(x, w, ws, interpret=True)
        want = int8_matmul_reference(x, w, ws)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=1e-6)

    def test_explicit_non_dividing_block_n_raises(self):
        # an explicitly-requested block that can't cover N must fail
        # loudly, not silently measure the XLA path
        x, w, ws = _mk(16, 128, 1024, seed=10)
        with pytest.raises(ValueError, match="does not divide"):
            int8_matmul(x, w, ws, block_n=384, interpret=True)


class TestMInnerSchedule:
    def test_m_inner_matches_reference(self):
        # weight-resident grid order: output tiles land in the same
        # places, numerics identical to the default schedule
        x, w, ws = _mk(48, 128, 256, seed=11)
        got = int8_matmul(x, w, ws, block_m=16, block_n=128,
                          m_inner=True, interpret=True)
        want = int8_matmul_reference(x, w, ws)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=1e-6)

    def test_sched_env_typo_rejected(self, monkeypatch):
        monkeypatch.setenv("TRITON_TPU_INT8_SCHED", "minner")
        x, w, ws = _mk(16, 128, 128, seed=12)
        with pytest.raises(ValueError, match="TRITON_TPU_INT8_SCHED"):
            int8_matmul(x, w, ws, interpret=True)

    def test_sched_env_selects_m_inner(self, monkeypatch):
        monkeypatch.setenv("TRITON_TPU_INT8_SCHED", "m_inner")
        x, w, ws = _mk(32, 128, 256, seed=13)
        got = int8_matmul(x, w, ws, block_m=16, block_n=128, interpret=True)
        want = int8_matmul_reference(x, w, ws)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=1e-6)
